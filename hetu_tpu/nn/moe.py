"""Mixture-of-Experts with expert parallelism.

Parity target: HetuMoE (reference ``hetu/v1``): top-k gates
(``v1/python/hetu/layers/*Gate.py``), all-to-all expert dispatch
(``v1/python/hetu/gpu_ops/AllToAll.py``, backend primitive
``nccl_comm_group.h:44``), examples ``v1/examples/moe/``. The v2 graph layer
has no MoE — this module is the capability re-designed TPU-first:

- Router + load-balance aux loss computed on the GLOBAL token array under
  GSPMD (cheap; numerically identical across strategies).
- Dispatch/combine run inside a *partial-manual* ``shard_map`` over
  {dp, ep}: tokens scatter into per-expert capacity buffers via one-hot
  matmuls (MXU-friendly), ``jax.lax.all_to_all`` over the ep axis moves
  token blocks to the ranks owning their experts, expert FFNs apply
  batched (their tp-sharded dims stay GSPMD-auto), and a second
  all_to_all returns results for the weighted combine.
- Expert params are stacked on a leading ``expert`` axis (rule
  ``"expert" → "ep"``), so checkpoint/resharding treat them like any other
  param.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from hetu_tpu.nn.module import Module, normal_init
from hetu_tpu.ops import activations as act_ops
from hetu_tpu.parallel.sharding import (
    act_constrain, current_act_sharding, current_manual_axes,
)


class TopKGate(Module):
    """Softmax router with top-k selection and GShard/Switch aux loss.

    Reference gates: ``TopGate``/``KTop1Gate``/``BalanceGate``
    (``hetu/v1/python/hetu/layers/``).
    """

    def __init__(self, features: int, num_experts: int, k: int = 2,
                 init=None):
        super().__init__()
        self.num_experts = num_experts
        self.k = k
        self.param("weight", (features, num_experts),
                   init or normal_init(0.02), axes=("embed", None))

    def __call__(self, params, x):
        """x (T, d) → (idx (T,k) int32, weights (T,k) fp32, aux scalar)."""
        logits = jnp.einsum("td,de->te", x.astype(jnp.float32),
                            params["weight"].astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)
        top_w, top_idx = jax.lax.top_k(probs, self.k)
        if self.k > 1:
            top_w = top_w / jnp.sum(top_w, axis=-1, keepdims=True)
        # load-balance aux (Switch/GShard): E * Σ_e f_e · P_e, with f from
        # first-choice assignments
        first = jax.nn.one_hot(top_idx[:, 0], self.num_experts,
                               dtype=jnp.float32)
        f_e = jnp.mean(first, axis=0)
        p_e = jnp.mean(probs, axis=0)
        aux = self.num_experts * jnp.sum(f_e * p_e)
        return top_idx.astype(jnp.int32), top_w, aux


class HashGate(Module):
    """Deterministic hash routing (reference ``HashGate``): expert =
    token_id mod E. Needs token ids, so it routes on provided ids rather
    than hidden states; aux loss is zero."""

    def __init__(self, num_experts: int):
        super().__init__()
        self.num_experts = num_experts
        self.k = 1

    def __call__(self, params, token_ids):
        idx = (token_ids.reshape(-1, 1) % self.num_experts).astype(jnp.int32)
        w = jnp.ones(idx.shape, jnp.float32)
        return idx, w, jnp.zeros([], jnp.float32)


class MoEMLP(Module):
    """Expert-parallel FFN layer (drop-in for ParallelMLP; returns
    ``(out, aux_loss)``)."""

    returns_aux = True

    def __init__(self, features: int, hidden: int, num_experts: int, *,
                 k: int = 2, capacity_factor: float = 1.25,
                 gated: bool = False, init=None):
        super().__init__()
        self.num_experts = num_experts
        self.k = k
        self.capacity_factor = capacity_factor
        self.gated = gated
        self.activation = act_ops.swiglu if gated else jax.nn.gelu
        init = init or normal_init(0.02)
        self.gate = TopKGate(features, num_experts, k=k)
        self.param("wi", (num_experts, features, hidden), init,
                   axes=("expert", "embed", "mlp"))
        if gated:
            self.param("wg", (num_experts, features, hidden), init,
                       axes=("expert", "embed", "mlp"))
        self.param("wo", (num_experts, hidden, features), init,
                   axes=("expert", "mlp", "embed"))

    # -- expert application (local experts, batched tokens) ---------------
    def _apply_experts(self, params, xe):
        """xe (E_local, C_tot, d) → (E_local, C_tot, d)."""
        dt = self.compute_dtype()
        h = jnp.einsum("ecd,edh->ech", xe.astype(dt),
                       params["wi"].astype(dt))
        if self.gated:
            g = jnp.einsum("ecd,edh->ech", xe.astype(dt),
                           params["wg"].astype(dt))
            h = self.activation(g, h)
        else:
            h = self.activation(h)
        return jnp.einsum("ech,ehd->ecd", h, params["wo"].astype(dt))

    def _expert_params(self, params):
        return {n: params[n] for n in
                (("wi", "wg", "wo") if self.gated else ("wi", "wo"))}

    def __call__(self, params, x):
        b, s, d = x.shape
        xf = x.reshape(b * s, d)
        idx, wgt, aux = self.gate(params["gate"], xf)

        # inside a manual region (the pipeline executor) with a manual ep
        # axis: run the dispatch body directly on the bound axis — the
        # EP x PP composition (no nested shard_map allowed)
        man = current_manual_axes()
        if man is not None and "ep" in man.axes \
                and man.mesh.shape.get("ep", 1) > 1 \
                and self.num_experts % man.mesh.shape["ep"] == 0:
            out = _ep_dispatch(
                xf, idx, wgt, self._expert_params(params),
                ep=man.mesh.shape["ep"], num_experts=self.num_experts,
                k=self.k, capacity_factor=self.capacity_factor,
                apply_experts=self._apply_experts)
            aux = jax.lax.pmean(aux, "ep")
            return out.reshape(b, s, d).astype(x.dtype), aux

        ctx = current_act_sharding()
        ep_deg = 0
        if ctx is not None and ctx.mesh.shape.get("ep", 1) > 1 \
                and self.num_experts % ctx.mesh.shape["ep"] == 0:
            ep_deg = ctx.mesh.shape["ep"]

        if ep_deg > 1:
            out = self._ep_forward(params, xf, idx, wgt, ctx)
        else:
            out = self._dense_forward(params, xf, idx, wgt)
        out = act_constrain(out.reshape(b, s, d).astype(x.dtype), "tokens")
        return out, aux

    # -- dense oracle (single device / no ep axis): every expert computes
    # every token, combine by gate weights — capacity-free ------------------
    def _dense_forward(self, params, xf, idx, wgt):
        xe = jnp.broadcast_to(xf[None], (self.num_experts, *xf.shape))
        ye = self._apply_experts(params, xe)         # (E, T, d)
        combine = jnp.zeros((xf.shape[0], self.num_experts), jnp.float32)
        for j in range(self.k):
            combine = combine + wgt[:, j, None] * jax.nn.one_hot(
                idx[:, j], self.num_experts, dtype=jnp.float32)
        return jnp.einsum("te,etd->td", combine, ye.astype(jnp.float32))

    # -- expert-parallel path: capacity buffers + all_to_all ----------------
    def _ep_forward(self, params, xf, idx, wgt, ctx):
        expert_params = self._expert_params(params)
        tok_spec = P(("dp", "ep"))
        exp_spec = jax.tree.map(lambda _: P("ep"), expert_params)
        body = functools.partial(
            _ep_dispatch, ep=ctx.mesh.shape["ep"],
            num_experts=self.num_experts, k=self.k,
            capacity_factor=self.capacity_factor,
            apply_experts=self._apply_experts)

        fn = shard_map(
            body, mesh=ctx.mesh,
            in_specs=(tok_spec, tok_spec, tok_spec, exp_spec),
            out_specs=tok_spec, axis_names={"dp", "ep"}, check_vma=False)
        return fn(xf, idx, wgt, expert_params)


def _ep_dispatch(x, idx, wgt, eparams, *, ep, num_experts, k,
                 capacity_factor, apply_experts):
    """Per-rank EP dispatch body: capacity scatter → all_to_all → local
    experts → all_to_all → weighted combine. Requires a bound manual
    ``"ep"`` axis (from ``_ep_forward``'s shard_map or the pipeline's
    manual region)."""
    E, El = num_experts, num_experts // ep
    T = x.shape[0]                       # local tokens
    C = max(1, math.ceil(capacity_factor * T * k / E))
    idx_f = idx.reshape(T * k)           # token-major, k inner
    oh = jax.nn.one_hot(idx_f, E, dtype=jnp.int32)      # (Tk, E)
    pos = (jnp.cumsum(oh, axis=0) - oh)[
        jnp.arange(T * k), idx_f]        # rank within expert
    keep = (pos < C).astype(jnp.float32)
    slot = idx_f * C + jnp.clip(pos, 0, C - 1)
    disp = jax.nn.one_hot(slot, E * C, dtype=jnp.float32) \
        * keep[:, None]                  # (Tk, E*C)
    xk = jnp.repeat(x, k, axis=0)        # (Tk, d) matches idx_f
    buf = jnp.einsum("ts,td->sd", disp,
                     xk.astype(jnp.float32))   # (E*C, d)
    buf = buf.reshape(ep, El, C, -1)
    # send each expert block to its owner rank
    buf = jax.lax.all_to_all(buf, "ep", split_axis=0,
                             concat_axis=0)    # (ep, El, C, d)
    xe = jnp.swapaxes(buf, 0, 1).reshape(El, ep * C, -1)
    ye = apply_experts(eparams, xe)            # (El, ep*C, d)
    ye = jnp.swapaxes(ye.reshape(El, ep, C, -1), 0, 1)
    ye = jax.lax.all_to_all(ye, "ep", split_axis=0,
                            concat_axis=0)     # (ep, El, C, d)
    ye = ye.reshape(E * C, -1)
    outk = jnp.einsum("ts,sd->td", disp,
                      ye.astype(jnp.float32))  # (Tk, d)
    w = (wgt.reshape(T * k) * keep)[:, None]
    return jnp.sum((outk * w).reshape(T, k, -1), axis=1)
