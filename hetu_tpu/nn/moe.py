"""Mixture-of-Experts with expert parallelism.

Parity target: HetuMoE (reference ``hetu/v1``): top-k gates
(``v1/python/hetu/layers/*Gate.py``), all-to-all expert dispatch
(``v1/python/hetu/gpu_ops/AllToAll.py``, backend primitive
``nccl_comm_group.h:44``), examples ``v1/examples/moe/``. The v2 graph layer
has no MoE — this module is the capability re-designed TPU-first:

- Router + load-balance aux loss computed on the GLOBAL token array under
  GSPMD (cheap; numerically identical across strategies).
- Dispatch/combine run inside a *partial-manual* ``shard_map`` over
  {dp, ep}: tokens scatter into per-expert capacity buffers via one-hot
  matmuls (MXU-friendly), ``jax.lax.all_to_all`` over the ep axis moves
  token blocks to the ranks owning their experts, expert FFNs apply
  batched (their tp-sharded dims stay GSPMD-auto), and a second
  all_to_all returns results for the weighted combine.
- Expert params are stacked on a leading ``expert`` axis (rule
  ``"expert" → "ep"``), so checkpoint/resharding treat them like any other
  param.
- ``Strategy(ep_overlap="chunk")`` decomposes the dispatch-a2a → expert
  FFN → combine-a2a chain into ``ep_chunks`` capacity slices: chunk *i*'s
  combine-a2a (and chunk *i+1*'s dispatch-a2a) share no data with chunk
  *i*'s expert matmul, so the scheduler (and the TPU's async all_to_all)
  hides the exchanges behind compute — the EP twin of the PR 3/4 tp/fsdp
  rings, bitwise-identical to the serialized path (capacity slices are
  disjoint and the combine consumes the re-concatenated buffer). The
  analytic ledger audits it as ``comm_bytes_total{kind="ep_a2a"}`` with
  the overlapped split.
- The expert plane is observable: per-expert load gauges
  (``moe_expert_tokens{expert}``), the capacity-overflow counter
  (``moe_dropped_tokens_total`` — tokens past the capacity buffer used
  to vanish silently), and aux-loss/overflow-fraction histograms are
  emitted through a trace-time-gated ``jax.debug.callback`` when
  telemetry is enabled.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from hetu_tpu.nn.module import Module, normal_init
from hetu_tpu.ops import activations as act_ops
from hetu_tpu.parallel.sharding import (
    act_constrain, current_act_sharding, current_manual_axes,
)


class TopKGate(Module):
    """Softmax router with top-k selection and GShard/Switch aux loss.

    Reference gates: ``TopGate``/``KTop1Gate``/``BalanceGate``
    (``hetu/v1/python/hetu/layers/``).
    """

    def __init__(self, features: int, num_experts: int, k: int = 2,
                 init=None):
        super().__init__()
        self.num_experts = num_experts
        self.k = k
        self.param("weight", (features, num_experts),
                   init or normal_init(0.02), axes=("embed", None))

    def __call__(self, params, x):
        """x (T, d) → (idx (T,k) int32, weights (T,k) fp32, aux scalar)."""
        logits = jnp.einsum("td,de->te", x.astype(jnp.float32),
                            params["weight"].astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)
        top_w, top_idx = jax.lax.top_k(probs, self.k)
        if self.k > 1:
            top_w = top_w / jnp.sum(top_w, axis=-1, keepdims=True)
        # load-balance aux (Switch/GShard): E * Σ_e f_e · P_e, with f from
        # first-choice assignments
        first = jax.nn.one_hot(top_idx[:, 0], self.num_experts,
                               dtype=jnp.float32)
        f_e = jnp.mean(first, axis=0)
        p_e = jnp.mean(probs, axis=0)
        aux = self.num_experts * jnp.sum(f_e * p_e)
        return top_idx.astype(jnp.int32), top_w, aux


class KTop1Gate(Module):
    """k independent top-1 routers over disjoint expert groups.

    Reference: ``KTop1Gate`` (``hetu/v1/python/hetu/layers/KTop1Gate.py``,
    ``ktop1gating``): the E logits split into k prototype groups of E/k
    experts; each group runs its own softmax + top-1, so a token gets
    exactly one expert PER GROUP (cheaper top-1 selection, top-k-like
    capacity). Gate weight = the group softmax prob of the selected
    expert (raw, not renormalized across groups — reference ``gates_s``);
    aux = sum of per-group balance losses."""

    def __init__(self, features: int, num_experts: int, k: int = 2,
                 init=None):
        super().__init__()
        if num_experts % k != 0:
            raise ValueError(f"num_experts {num_experts} must divide by "
                             f"k {k} prototype groups")
        self.num_experts = num_experts
        self.k = k
        self.param("weight", (features, num_experts),
                   init or normal_init(0.02), axes=("embed", None))

    def __call__(self, params, x):
        T = x.shape[0]
        Eg = self.num_experts // self.k
        logits = jnp.einsum("td,de->te", x.astype(jnp.float32),
                            params["weight"].astype(jnp.float32))
        # (T, k, E/k): group g owns experts [g*Eg, (g+1)*Eg)
        probs = jax.nn.softmax(logits.reshape(T, self.k, Eg), axis=-1)
        local = jnp.argmax(probs, axis=-1)              # (T, k)
        w = jnp.take_along_axis(probs, local[..., None],
                                axis=-1)[..., 0]        # (T, k)
        offs = jnp.arange(self.k, dtype=jnp.int32) * Eg
        idx = local.astype(jnp.int32) + offs[None, :]
        first = jax.nn.one_hot(local, Eg, dtype=jnp.float32)  # (T,k,Eg)
        f_e = jnp.mean(first, axis=0)                   # (k, Eg)
        p_e = jnp.mean(probs, axis=0)
        aux = Eg * jnp.sum(f_e * p_e)                   # summed over groups
        return idx, w, aux


class SAMGate(Module):
    """Locality-aware gate: pick ONE expert group (device), then top-k
    within it.

    Reference: ``SAMGate`` (``hetu/v1/python/hetu/layers/SAMGate.py``,
    ``samgating``): softmax over all E experts; experts are grouped by
    owning device (``num_local_gpus`` groups); the group with the largest
    total gate mass wins (``sam_group_sum_op`` + top-1), then the top-k
    experts INSIDE that group are used — so all k experts of a token live
    on one device and dispatch needs no cross-group traffic. Aux combines
    the balance loss with an alignment term (``sam_max_op``) pushing gate
    mass into the chosen group; here alignment = mean out-of-group mass
    (a TPU-friendly closed form with the same gradient direction)."""

    def __init__(self, features: int, num_experts: int, k: int = 2,
                 num_groups: int = 2, alignment_coef: float = 1.0,
                 init=None):
        super().__init__()
        if num_experts % num_groups != 0:
            raise ValueError(f"num_experts {num_experts} must divide by "
                             f"num_groups {num_groups}")
        if k > num_experts // num_groups:
            raise ValueError("k cannot exceed experts per group")
        self.num_experts = num_experts
        self.k = k
        self.num_groups = num_groups
        self.alignment_coef = alignment_coef
        self.param("weight", (features, num_experts),
                   init or normal_init(0.02), axes=("embed", None))

    def __call__(self, params, x):
        T = x.shape[0]
        G, Eg = self.num_groups, self.num_experts // self.num_groups
        logits = jnp.einsum("td,de->te", x.astype(jnp.float32),
                            params["weight"].astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)         # (T, E)
        pg = probs.reshape(T, G, Eg)
        group_mass = jnp.sum(pg, axis=-1)               # (T, G)
        g_star = jnp.argmax(group_mass, axis=-1)        # (T,)
        in_group = jnp.take_along_axis(
            pg, g_star[:, None, None], axis=1)[:, 0]    # (T, Eg)
        w, local = jax.lax.top_k(in_group, self.k)      # raw probs
        idx = (local + (g_star[:, None] * Eg)).astype(jnp.int32)
        first = jax.nn.one_hot(idx[:, 0], self.num_experts,
                               dtype=jnp.float32)
        aux = self.num_experts * jnp.sum(
            jnp.mean(first, axis=0) * jnp.mean(probs, axis=0))
        out_of_group = 1.0 - jnp.take_along_axis(
            group_mass, g_star[:, None], axis=1)[:, 0]
        aux = aux + self.alignment_coef * jnp.mean(out_of_group)
        return idx, w, aux


class BalanceGate(Module):
    """Balanced-assignment routing (BASE-layers style), Sinkhorn form.

    Reference: ``BalanceAssignmentGate``
    (``hetu/v1/python/hetu/layers/BalanceGate.py``): token-expert affinity
    ``x @ centroids^T`` solved to a BALANCED assignment (every expert gets
    T/E tokens) by a native auction solver (``balance_assignment_op``).
    The TPU-native re-design replaces the sequential auction with fixed
    Sinkhorn iterations (row/col renormalization — pure matmul/softmax,
    jit- and MXU-friendly), then takes the per-token argmax of the
    transport plan; weight = sigmoid(affinity) as in BASE. k = 1, aux = 0
    (balance is enforced by construction, approximately under Sinkhorn)."""

    #: routing depends on the WHOLE co-batched row set (the Sinkhorn
    #: column marginal couples tokens) — decode paths that pack rows
    #: from unrelated requests must refuse this gate (MoEMLP.decode)
    batch_coupled = True

    def __init__(self, features: int, num_experts: int, *,
                 n_iters: int = 24, temperature: float = 0.02, init=None):
        # defaults measured (CPU sweep, r4): τ=0.02/24 iters → ~0.8%
        # capacity drop at factor 1.0 and load imbalance 1.03, vs 10%/1.31
        # for plain argmax — cold Sinkhorn ≈ the exact auction assignment
        super().__init__()
        self.num_experts = num_experts
        self.k = 1
        self.n_iters = n_iters
        self.temperature = temperature
        self.param("centroids", (num_experts, features),
                   init or normal_init(0.02), axes=(None, "embed"))

    def __call__(self, params, x):
        T = x.shape[0]
        scores = jnp.einsum("td,ed->te", x.astype(jnp.float32),
                            params["centroids"].astype(jnp.float32))
        # Sinkhorn to (approx) uniform marginals: rows sum to 1 (each
        # token routed once), cols to T/E (balanced expert load)
        logp = scores / self.temperature

        def body(logp, _):
            logp = jax.nn.log_softmax(logp, axis=1)       # row normalize
            logp = logp - jax.nn.logsumexp(logp, axis=0,
                                           keepdims=True) \
                + jnp.log(T / self.num_experts)            # col marginal
            return logp, None

        logp, _ = jax.lax.scan(body, logp, None, length=self.n_iters)
        idx = jnp.argmax(logp, axis=-1).astype(jnp.int32)[:, None]
        aff = jnp.take_along_axis(scores, idx, axis=-1)
        w = jax.nn.sigmoid(aff)
        return idx, w, jnp.zeros([], jnp.float32)


GATE_TYPES = {"topk": TopKGate, "ktop1": KTop1Gate, "sam": SAMGate,
              "balance": BalanceGate}


def make_gate(gate_type: str, features: int, num_experts: int,
              k: int = 2, **kw) -> Module:
    """Gate factory for config-driven model construction."""
    if gate_type not in GATE_TYPES:
        raise ValueError(f"unknown gate {gate_type!r}; "
                         f"have {sorted(GATE_TYPES)}")
    if gate_type == "balance":
        if k != 1:
            # not an error: k=2 is the untouched config default, so a
            # hard reject would break moe_gate="balance" out of the box —
            # but the downgrade must be visible
            import warnings
            warnings.warn(
                f"balance gate is top-1 by construction (BASE layers); "
                f"requested k={k} is downgraded to 1 (capacity and "
                f"per-token compute follow)", stacklevel=2)
        return BalanceGate(features, num_experts, **kw)
    return GATE_TYPES[gate_type](features, num_experts, k=k, **kw)


def gate_drop_stats(idx, num_experts: int, k: int,
                    capacity_factor: float) -> dict:
    """Capacity-drop statistics for a gate decision (surfaced in metrics
    / the EP workload): fraction of (token, choice) slots dropped by the
    capacity limit, plus the per-expert load histogram. Mirrors the
    position computation of ``_ep_dispatch`` exactly."""
    T = idx.shape[0]
    E = num_experts
    C = max(1, math.ceil(capacity_factor * T * k / E))
    idx_f = idx.reshape(T * k)
    oh = jax.nn.one_hot(idx_f, E, dtype=jnp.int32)
    pos = (jnp.cumsum(oh, axis=0) - oh)[jnp.arange(T * k), idx_f]
    dropped = (pos >= C)
    load = jnp.sum(oh, axis=0)
    return {
        "drop_frac": jnp.mean(dropped.astype(jnp.float32)),
        "expert_load": load,
        "load_imbalance": load.max() / jnp.maximum(1, load.mean()),
        "capacity": C,
    }


def _emit_expert_plane(load, dropped, aux):
    """Host side of the expert-plane telemetry callback (values arrive
    as numpy arrays via ``jax.debug.callback``)."""
    from hetu_tpu import telemetry
    if not telemetry.enabled():
        return
    import numpy as np
    reg = telemetry.get_registry()
    load = np.asarray(load)
    gauge = reg.gauge(
        "moe_expert_tokens",
        "tokens routed to each expert on the last observed MoE layer "
        "call (pre-capacity, global batch)")
    for e, n in enumerate(load.tolist()):
        gauge.set(float(n), expert=str(e))
    d = float(dropped)
    if d:
        reg.counter(
            "moe_dropped_tokens_total",
            "(token, choice) slots dropped by the EP capacity limit "
            "— contributions that silently vanish from the combine").inc(d)
    total = float(load.sum())
    reg.histogram(
        "moe_overflow_fraction",
        "fraction of (token, choice) slots dropped by the capacity "
        "limit, per MoE layer call").observe(d / max(total, 1.0))
    reg.histogram(
        "moe_aux_loss",
        "MoE load-balance aux loss per layer call").observe(float(aux))


@jax.custom_vjp
def _expert_plane_probe(out, load, dropped, aux):
    """Identity on ``out`` that emits the expert-plane stats exactly
    once per executed layer call, in BOTH execution modes:

    - un-differentiated traces (eval, the dense decode oracle, bench
      forwards) run the primal — the ``jax.debug.callback`` here fires;
    - differentiated traces replace the primal with the fwd/bwd pair,
      and the emission moves to the BACKWARD: under jax 0.4.37 an
      effect inside a scan body is silently dropped by partial-eval
      when the scan is differentiated (the train step's layer scan!),
      but the transposed backward scan executes its own effects — so
      the bwd is where training-step stats must be emitted. A remat
      forward replay runs the (emission-free) fwd, never the primal,
      so recompute cannot double-count.

    ``load``/``dropped``/``aux`` must be float arrays (their zero
    cotangents are returned as-is)."""
    jax.debug.callback(_emit_expert_plane, load, dropped, aux)
    return out


def _probe_fwd(out, load, dropped, aux):
    return out, (load, dropped, aux)


def _probe_bwd(res, ct):
    load, dropped, aux = res
    jax.debug.callback(_emit_expert_plane, load, dropped, aux)
    return (ct, jnp.zeros_like(load), jnp.zeros_like(dropped),
            jnp.zeros_like(aux))


_expert_plane_probe.defvjp(_probe_fwd, _probe_bwd)


def _expert_plane_stats(idx, *, num_experts: int, k: int,
                        capacity_factor: float, n_shards: int):
    """Traced expert-plane stats for one MoE layer call: global
    per-expert load plus the EXACT dropped-slot count of the EP dispatch
    — the position computation of :func:`_ep_dispatch` replayed per
    batch shard (the token dim is contiguously sharded over dp×ep, so
    shard r's rows are ``idx[r*Tl:(r+1)*Tl]``). ``n_shards=0`` marks the
    capacity-free dense-oracle path (nothing drops)."""
    T = idx.shape[0]
    E = num_experts
    oh_flat = jax.nn.one_hot(idx.reshape(T * k), E, dtype=jnp.int32)
    load = jnp.sum(oh_flat, axis=0)
    if n_shards <= 0 or T % n_shards:
        return load, jnp.zeros([], jnp.int32)
    Tl = T // n_shards
    C = max(1, math.ceil(capacity_factor * Tl * k / E))
    idx_s = idx.reshape(n_shards, Tl * k)
    oh = jax.nn.one_hot(idx_s, E, dtype=jnp.int32)   # (S, Tlk, E)
    pos = jnp.take_along_axis(jnp.cumsum(oh, axis=1) - oh,
                              idx_s[..., None], axis=2)[..., 0]
    dropped = jnp.sum((pos >= C).astype(jnp.int32))
    return load, dropped


class HashGate(Module):
    """Deterministic hash routing (reference ``HashGate``): expert =
    token_id mod E. Needs token ids, so it routes on provided ids rather
    than hidden states; aux loss is zero."""

    def __init__(self, num_experts: int):
        super().__init__()
        self.num_experts = num_experts
        self.k = 1

    def __call__(self, params, token_ids):
        idx = (token_ids.reshape(-1, 1) % self.num_experts).astype(jnp.int32)
        w = jnp.ones(idx.shape, jnp.float32)
        return idx, w, jnp.zeros([], jnp.float32)


class MoEMLP(Module):
    """Expert-parallel FFN layer (drop-in for ParallelMLP; returns
    ``(out, aux_loss)``)."""

    returns_aux = True

    def __init__(self, features: int, hidden: int, num_experts: int, *,
                 k: int = 2, capacity_factor: float = 1.25,
                 gated: bool = False, gate_type: str = "topk",
                 gate_kwargs: Optional[dict] = None, init=None):
        super().__init__()
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        self.gated = gated
        self.activation = act_ops.swiglu if gated else jax.nn.gelu
        init = init or normal_init(0.02)
        self.gate = make_gate(gate_type, features, num_experts, k=k,
                              **(gate_kwargs or {}))
        self.k = self.gate.k      # balance gate forces k=1
        self.param("wi", (num_experts, features, hidden), init,
                   axes=("expert", "embed", "mlp"))
        if gated:
            self.param("wg", (num_experts, features, hidden), init,
                       axes=("expert", "embed", "mlp"))
        self.param("wo", (num_experts, hidden, features), init,
                   axes=("expert", "mlp", "embed"))

    # -- expert application (local experts, batched tokens) ---------------
    def _apply_experts(self, params, xe):
        """xe (E_local, C_tot, d) → (E_local, C_tot, d)."""
        dt = self.compute_dtype()
        h = jnp.einsum("ecd,edh->ech", xe.astype(dt),
                       params["wi"].astype(dt))
        if self.gated:
            g = jnp.einsum("ecd,edh->ech", xe.astype(dt),
                           params["wg"].astype(dt))
            h = self.activation(g, h)
        else:
            h = self.activation(h)
        return jnp.einsum("ech,ehd->ecd", h, params["wo"].astype(dt))

    def _expert_params(self, params):
        return {n: params[n] for n in
                (("wi", "wg", "wo") if self.gated else ("wi", "wo"))}

    @staticmethod
    def _ep_axes_of(mesh) -> tuple:
        """("ep",) for the flat axis, ("ep_out", "ep_in") when the mesh
        factors expert parallelism for the hierarchical a2a (multi-slice:
        ep_out across DCN, ep_in within a slice), () when absent."""
        if mesh.shape.get("ep", 1) > 1:
            return ("ep",)
        if "ep_out" in mesh.shape and "ep_in" in mesh.shape \
                and mesh.shape["ep_out"] * mesh.shape["ep_in"] > 1:
            return ("ep_out", "ep_in")
        return ()

    @staticmethod
    def _ep_degree(mesh, axes) -> int:
        n = 1
        for a in axes:
            n *= mesh.shape.get(a, 1)
        return n

    def __call__(self, params, x):
        b, s, d = x.shape
        xf = x.reshape(b * s, d)
        idx, wgt, aux = self.gate(params["gate"], xf)

        # inside a manual region (the pipeline executor, or the delayed
        # grad-sync body) with a manual ep axis: run the dispatch body
        # directly on the bound axis — the EP x PP / EP x delayed-sync
        # composition (no nested shard_map allowed). Telemetry callbacks
        # stay out of manual regions (SPMD partitioning of the auto axes
        # rejects the callback custom-call under jax 0.4.37).
        man = current_manual_axes()
        if man is not None:
            axes = self._ep_axes_of(man.mesh)
            ep = self._ep_degree(man.mesh, axes)
            if axes and set(axes) <= man.axes and ep > 1 \
                    and self.num_experts % ep == 0:
                out = _ep_dispatch(
                    xf, idx, wgt, self._expert_params(params),
                    ep=ep, num_experts=self.num_experts,
                    k=self.k, capacity_factor=self.capacity_factor,
                    apply_experts=self._apply_experts, ep_axes=axes,
                    ep_overlap=getattr(man, "ep_overlap", "off"),
                    ep_chunks=getattr(man, "ep_chunks", 2))
                aux = jax.lax.pmean(aux, axes)
                return out.reshape(b, s, d).astype(x.dtype), aux

        ctx = current_act_sharding()
        ep_deg = 0
        axes = ()
        if ctx is not None:
            axes = self._ep_axes_of(ctx.mesh)
            ep_deg = self._ep_degree(ctx.mesh, axes) if axes else 0
            if ep_deg > 1 and self.num_experts % ep_deg != 0:
                ep_deg = 0

        if ep_deg > 1:
            out = self._ep_forward(params, xf, idx, wgt, ctx, axes, ep_deg)
        else:
            out = self._dense_forward(params, xf, idx, wgt)

        from hetu_tpu import telemetry
        if telemetry.enabled():
            # expert-plane observability: per-expert load + the EXACT
            # dropped-slot count of the EP dispatch (0 on the capacity-
            # free dense oracle). Trace-time gated; emission routed
            # through the custom_vjp probe so differentiated layer
            # scans still fire it (and remat cannot double-count).
            n_shards = 0
            if ep_deg > 1:
                n_shards = ep_deg * ctx.mesh.shape.get("dp", 1)
            load, dropped = _expert_plane_stats(
                idx, num_experts=self.num_experts, k=self.k,
                capacity_factor=self.capacity_factor, n_shards=n_shards)
            out = _expert_plane_probe(
                out, load.astype(jnp.float32),
                dropped.astype(jnp.float32), aux)

        out = act_constrain(out.reshape(b, s, d).astype(x.dtype), "tokens")
        return out, aux

    # -- decode path (serving / autoregressive generation) ------------------
    def prequantize(self, params, *, stacked: bool = False):
        """Quantize the expert FFN stacks ONCE into the W8A8 decode
        lane's ``{name: {"q": int8, "scale": fp32}}`` tree.

        Per-(expert, output-channel) symmetric scales over each
        einsum's contraction axis: ``wi``/``wg`` (E, d, H) quantize
        over d (scale (E, 1, H)), ``wo`` (E, H, d) over H (scale
        (E, 1, d)); a stacked (L, E, ...) tree shifts the axis by one.
        The decode gather then moves int8 expert slices — 1/4 the HBM
        bytes of the fp32 gather, which is where MoE decode time goes."""
        from hetu_tpu.ops.quantization import quantize_int8
        axis = 2 if stacked else 1
        names = ["wi", "wo"] + (["wg"] if self.gated else [])
        return {
            name: dict(zip(("q", "scale"),
                           quantize_int8(params[name], axis=axis)))
            for name in names
        }

    def decode(self, params, x, *, w8a8=None, wq=None):
        """Per-row top-k through GATHERED local-expert einsums — the
        decode-mode twin of the dense oracle that computes only the k
        selected experts per token (O(T·k) FFNs instead of O(T·E)).

        The serving engine's fused step (and one-shot ``generate``) call
        the transformer blocks in kv-cache mode with a handful of slot
        rows; experts are stacked params on the leading ``expert`` axis,
        so per-row routing is a ``jnp.take`` of (k, d, h) weight slices
        plus batched einsums. The combine accumulates the same
        ``Σ_j w_j·expert_{idx_j}(x)`` the dense oracle produces (k ≤ 2
        keeps fp addition commutative), so greedy serving tokens match
        one-shot generation. Returns the output only — aux is
        train-only.

        ``w8a8`` (traced bool) + ``wq`` (a :meth:`prequantize` tree)
        select the quantized-compute lane per call: expert slices
        gather as int8, activations quantize per token, and both
        expert einsums contract int8×int8 with int32 accumulation —
        the MoE extension of ``ParallelMLP``'s W8A8 decode lane. The
        gate always routes in fp (routing flips would change WHICH
        experts run, not just their arithmetic)."""
        if getattr(self.gate, "batch_coupled", False):
            raise NotImplementedError(
                f"MoEMLP.decode needs a per-token gate; "
                f"{type(self.gate).__name__} routes over the whole "
                "co-batched row set, so serving outputs would depend on "
                "which requests share the fused step and could never "
                "match one-shot generate")
        b, s, d = x.shape
        xf = x.reshape(b * s, d)
        idx, wgt, _ = self.gate(params["gate"], xf)
        dt = self.compute_dtype()
        xc = xf.astype(dt)

        def fp_lane(params, xc):
            wi = jnp.take(params["wi"], idx, axis=0).astype(dt)  # (T,k,d,H)
            h = jnp.einsum("td,tkdh->tkh", xc, wi)
            if self.gated:
                wg = jnp.take(params["wg"], idx, axis=0).astype(dt)
                g = jnp.einsum("td,tkdh->tkh", xc, wg)
                h = self.activation(g, h)
            else:
                h = self.activation(h)
            wo = jnp.take(params["wo"], idx, axis=0).astype(dt)  # (T,k,H,d)
            y = jnp.einsum("tkh,tkhd->tkd", h, wo)
            return jnp.sum(wgt[..., None] * y.astype(jnp.float32), axis=1)

        def q_lane(params, xc):
            from hetu_tpu.ops.quantization import quantize_int8
            xq, xs = quantize_int8(xc, axis=-1)          # (T,d), (T,1)

            def up(name):
                wq_e = jnp.take(wq[name]["q"], idx, axis=0)      # int8
                ws_e = jnp.take(wq[name]["scale"], idx, axis=0)  # (T,k,1,H)
                acc = jnp.einsum("td,tkdh->tkh", xq, wq_e,
                                 preferred_element_type=jnp.int32)
                return (acc.astype(jnp.float32)
                        * xs[:, :, None] * ws_e[:, :, 0, :])

            h = up("wi")
            if self.gated:
                h = self.activation(up("wg"), h)
            else:
                h = self.activation(h)
            hq, hs = quantize_int8(h, axis=-1)           # (T,k,H), (T,k,1)
            wo_q = jnp.take(wq["wo"]["q"], idx, axis=0)
            wo_s = jnp.take(wq["wo"]["scale"], idx, axis=0)  # (T,k,1,d)
            acc = jnp.einsum("tkh,tkhd->tkd", hq, wo_q,
                             preferred_element_type=jnp.int32)
            y = acc.astype(jnp.float32) * hs * wo_s[:, :, 0, :]
            return jnp.sum(wgt[..., None] * y, axis=1)

        if w8a8 is None or wq is None:
            out = fp_lane(params, xc)
        else:
            out = jax.lax.cond(
                w8a8, lambda p, v: q_lane(p, v), fp_lane, params, xc)
        return out.reshape(b, s, d).astype(x.dtype)

    # -- dense oracle (single device / no ep axis): every expert computes
    # every token, combine by gate weights — capacity-free ------------------
    def _dense_forward(self, params, xf, idx, wgt):
        xe = jnp.broadcast_to(xf[None], (self.num_experts, *xf.shape))
        ye = self._apply_experts(params, xe)         # (E, T, d)
        combine = jnp.zeros((xf.shape[0], self.num_experts), jnp.float32)
        for j in range(self.k):
            combine = combine + wgt[:, j, None] * jax.nn.one_hot(
                idx[:, j], self.num_experts, dtype=jnp.float32)
        return jnp.einsum("te,etd->td", combine, ye.astype(jnp.float32))

    # -- expert-parallel path: capacity buffers + all_to_all ----------------
    def _ep_forward(self, params, xf, idx, wgt, ctx, ep_axes, ep_deg):
        expert_params = self._expert_params(params)
        tok_spec = P(("dp",) + tuple(ep_axes))
        exp_spec = jax.tree.map(lambda _: P(tuple(ep_axes)),
                                expert_params)
        body = functools.partial(
            _ep_dispatch, ep=ep_deg,
            num_experts=self.num_experts, k=self.k,
            capacity_factor=self.capacity_factor,
            apply_experts=self._apply_experts, ep_axes=ep_axes,
            ep_overlap=getattr(ctx, "ep_overlap", "off"),
            ep_chunks=getattr(ctx, "ep_chunks", 2))

        fn = shard_map(
            body, mesh=ctx.mesh,
            in_specs=(tok_spec, tok_spec, tok_spec, exp_spec),
            out_specs=tok_spec, axis_names={"dp", *ep_axes},
            check_vma=False)
        return fn(xf, idx, wgt, expert_params)


def _bound_axis_size(name: str) -> int:
    """Size of a bound manual axis. ``jax.lax.axis_size`` only exists
    on jax >= 0.6 (the tree's target); under the 0.4.37 container the
    ``psum(1, axis)`` idiom returns the same static int — this gap made
    the factored-ep (multi-slice) path raise AttributeError until the
    ISSUE 9 quick-tier unit test caught it."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(name)
    return jax.lax.psum(1, name)


def hierarchical_all_to_all(buf, outer_axis: str, inner_axis: str):
    """Two-stage all_to_all over a FACTORED expert axis (ep = outer ×
    inner): exchange over the inner (intra-slice, ICI) axis first, then
    the outer (cross-slice, DCN) axis — so the DCN stage moves one large
    contiguous block per destination slice instead of ep small ones.

    Reference capability: the hierarchical a2a of HetuMoE
    (``hetu/v1/python/hetu/gpu_ops/AllToAll.py`` over grouped NCCL comms).
    ``buf``: (ep, ...) per-rank blocks, destination-major with rank
    r = outer * inner_size + inner. Returns the same shape with the
    leading dim indexing sources."""
    ep = buf.shape[0]
    O = _bound_axis_size(outer_axis)
    I = _bound_axis_size(inner_axis)
    assert O * I == ep, (O, I, ep)
    b = buf.reshape((O, I) + buf.shape[1:])
    # inner exchange delivers each (outer-dest, inner-dest) block to the
    # right inner rank within the source slice...
    b = jax.lax.all_to_all(b, inner_axis, split_axis=1, concat_axis=1)
    # ...then one aggregated block per destination slice rides DCN
    b = jax.lax.all_to_all(b, outer_axis, split_axis=0, concat_axis=0)
    return b.reshape((ep,) + buf.shape[1:])


@jax.custom_vjp
def _pin_buffer(x):
    """Differentiable ``optimization_barrier``: identity that stops XLA
    fusing/splitting ops across the pinned value (0.4.37 ships no
    differentiation rule for the primitive, hence the custom_vjp). The
    cotangent is pinned too, so the mirrored backward dots see the same
    materialized layout."""
    return jax.lax.optimization_barrier(x)


def _pin_fwd(x):
    return jax.lax.optimization_barrier(x), None


def _pin_bwd(_, ct):
    return (jax.lax.optimization_barrier(ct),)


_pin_buffer.defvjp(_pin_fwd, _pin_bwd)


def _ep_dispatch(x, idx, wgt, eparams, *, ep, num_experts, k,
                 capacity_factor, apply_experts, ep_axes=("ep",),
                 ep_overlap: str = "off", ep_chunks: int = 2):
    """Per-rank EP dispatch body: capacity scatter → all_to_all → local
    experts → all_to_all → weighted combine. Requires a bound manual
    ``"ep"`` axis (from ``_ep_forward``'s shard_map or the pipeline's
    manual region). ``ep_axes``: one axis name, or (outer, inner) for the
    hierarchical two-stage exchange on multi-slice meshes.

    ``ep_overlap="chunk"`` slices the capacity dim into ``ep_chunks``
    pieces and runs dispatch-a2a → FFN → combine-a2a per slice. Slices
    are disjoint and rows independent, so the re-concatenated combine
    buffer is bitwise-identical to the serialized path — but chunk
    *i+1*'s dispatch-a2a and chunk *i*'s combine-a2a share no data with
    chunk *i*'s expert matmul, so the scheduler overlaps them (the same
    no-data-dependency contract the tp/fsdp rings rely on). The backward
    inherits the chunk structure through linearization: the transpose of
    ``all_to_all`` is an ``all_to_all``, so the mirrored exchanges of
    chunk *i* overlap chunk *i±1*'s FFN backward the same way — no
    custom_vjp needed to keep the overlap shape."""

    def a2a(buf):
        if len(ep_axes) == 2:
            return hierarchical_all_to_all(buf, ep_axes[0], ep_axes[1])
        return jax.lax.all_to_all(buf, ep_axes[0], split_axis=0,
                                  concat_axis=0)

    E, El = num_experts, num_experts // ep
    T = x.shape[0]                       # local tokens
    C = max(1, math.ceil(capacity_factor * T * k / E))
    idx_f = idx.reshape(T * k)           # token-major, k inner
    oh = jax.nn.one_hot(idx_f, E, dtype=jnp.int32)      # (Tk, E)
    pos = (jnp.cumsum(oh, axis=0) - oh)[
        jnp.arange(T * k), idx_f]        # rank within expert
    keep = (pos < C).astype(jnp.float32)
    slot = idx_f * C + jnp.clip(pos, 0, C - 1)
    disp = jax.nn.one_hot(slot, E * C, dtype=jnp.float32) \
        * keep[:, None]                  # (Tk, E*C)
    xk = jnp.repeat(x, k, axis=0)        # (Tk, d) matches idx_f
    buf = jnp.einsum("ts,td->sd", disp,
                     xk.astype(jnp.float32))   # (E*C, d)
    buf = buf.reshape(ep, El, C, -1)
    n_chunks = min(int(ep_chunks), C) if ep_overlap == "chunk" else 1
    if ep > 1:
        # analytic ledger (trace time, like the tp/fsdp rings): two
        # a2as per forward, each moving the (ep-1)/ep remote share of
        # the local capacity buffer; the backward mirrors them (a2a
        # transposes to a2a) — accounted where the bwd traces
        from hetu_tpu.parallel.overlap import record_comm_bytes
        record_comm_bytes(
            "ep_a2a",
            2 * buf.size * buf.dtype.itemsize * (ep - 1) // ep,
            overlapped=n_chunks > 1)
    if n_chunks <= 1:
        # serialized: one dispatch exchange, all experts, one combine
        buf = a2a(buf)                             # (ep, El, C, d)
        xe = jnp.swapaxes(buf, 0, 1).reshape(El, ep * C, -1)
        ye = apply_experts(eparams, xe)            # (El, ep*C, d)
        ye = jnp.swapaxes(ye.reshape(El, ep, C, -1), 0, 1)
        ye = a2a(ye)                               # (ep, El, C, d)
    else:
        # pin the dispatch buffer before slicing: otherwise XLA fuses
        # the capacity slices back into the dispatch einsum and
        # computes each row subset with its own reduction blocking —
        # 1-ulp drift vs the serialized path's single full-buffer
        # einsum. Pinned, chunks are pure memory slices.
        buf = _pin_buffer(buf)
        bounds = [i * C // n_chunks for i in range(n_chunks + 1)]
        outs = []
        for lo, hi in zip(bounds[:-1], bounds[1:]):
            c = hi - lo
            bi = a2a(buf[:, :, lo:hi])             # (ep, El, c, d)
            xi = jnp.swapaxes(bi, 0, 1).reshape(El, ep * c, -1)
            yi = apply_experts(eparams, xi)
            yi = jnp.swapaxes(yi.reshape(El, ep, c, -1), 0, 1)
            outs.append(a2a(yi))
        ye = jnp.concatenate(outs, axis=2)         # (ep, El, C, d)
        # pin the re-concatenated buffer: without the barrier XLA
        # splits the combine dot across the concat (dot(disp, concat)
        # → Σ per-chunk partial dots), re-associating the s-reduction
        # by 1 ulp — the barrier makes the combine consume the same
        # materialized layout the serialized a2a output has, keeping
        # the bitwise contract while the chunk a2as still overlap
        ye = _pin_buffer(ye)
    ye = ye.reshape(E * C, -1)
    outk = jnp.einsum("ts,sd->td", disp,
                      ye.astype(jnp.float32))  # (Tk, d)
    w = (wgt.reshape(T * k) * keep)[:, None]
    return jnp.sum((outk * w).reshape(T, k, -1), axis=1)
