"""Tensor-parallel layers and the stacked-block transformer core.

TPU-native equivalent of the reference's multi-ds parallel layers
(``python/hetu/nn/modules/parallel_multi_ds.py``: ``HtMultiColumnParallelLinear``
:328, ``HtMultiRowParallelLinear`` :411, ``HtMultiQKVColumnParallelLinear``
:504 (GQA-aware), ``HtMultiVocabParallelEmbedding`` :268). The reference
threads per-strategy ``DistributedStates`` unions through every layer and a
C++ pass inserts comm ops; here layers declare *logical* axes on their params
("mlp", "heads", "kv_heads", "vocab", "embed", "layers") and call
``act_constrain`` at the canonical activation cut points — GSPMD then inserts
the same collectives ``SubstituteCommOp`` would (allreduce after row-parallel,
allgather on resharding, …).

``StackedBlocks`` is the scan-over-layers representation: every block param
gains a leading ``layers`` dim so (a) compile time is O(1) in depth, (b) the
pipeline executor can shard the ``layers`` axis over ``pp``
(``hetu_tpu.parallel.pipeline``), and (c) remat policy is applied per block
exactly like the reference's per-block recompute config
(``hetu/graph/recompute/recompute.h:12``).
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from jax import shard_map

from hetu_tpu.nn.module import Module, ParamSpec, normal_init, zeros_init
from hetu_tpu.ops import activations as act_ops
from hetu_tpu.ops import embedding as embed_ops
from hetu_tpu.ops.attention import attention_reference, flash_attention
from hetu_tpu.ops.rotary import rope_frequencies, apply_rotary
from hetu_tpu.parallel.sharding import (
    act_constrain, current_act_sharding, current_manual_axes,
)


def _ring_overlap_active(overlap: str) -> bool:
    """Resolve a layer's ``overlap`` mode against the ambient context:
    "ring" forces the decomposed collective matmul, "off" never uses it,
    "auto" (default) follows the Strategy's ``tp_overlap`` via the
    :class:`~hetu_tpu.parallel.sharding.ActivationSharding` context —
    so one Strategy flag flips every TP layer in the model."""
    if overlap == "off":
        return False
    ctx = current_act_sharding()
    if ctx is None:
        return False        # single device / manual pipeline region
    if overlap == "ring":
        return True
    return getattr(ctx, "tp_overlap", "off") == "ring"


class ColumnParallelLinear(Module):
    """Linear whose *output* features shard over tp (Y = XW, W: (in, out/tp)).

    Reference: ``HtMultiColumnParallelLinear`` (`parallel_multi_ds.py:328`).
    No gather is emitted here — the consumer is expected to be tp-local
    (attention heads, MLP hidden) until a RowParallelLinear reduces back.

    ``overlap="ring"`` (or "auto" + ``Strategy(tp_overlap="ring")``)
    decomposes the Megatron-SP all-gather→matmul pair into a ppermute
    ring of chunk matmuls (``parallel.overlap.ring_ag_matmul``) so each
    comm hop hides behind the previous chunk's compute. Without sp the
    column matmul has no gather to hide and the mode is a no-op.
    """

    def __init__(self, in_features: int, out_features: int, *,
                 bias: bool = True, init=None, axis: str = "mlp",
                 out_kind: str = "hidden", overlap: str = "auto"):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.use_bias = bias
        self.out_kind = out_kind
        self.overlap = overlap
        self.param("weight", (in_features, out_features),
                   init or normal_init(0.02), axes=("embed", axis))
        if bias:
            self.param("bias", (out_features,), zeros_init(), axes=(axis,))

    def __call__(self, params, x):
        dt = self.compute_dtype()
        x = x.astype(dt)
        w = params["weight"].astype(dt)
        if _ring_overlap_active(self.overlap):
            from hetu_tpu.parallel.overlap import (
                maybe_record_column_fallback, ring_ag_matmul,
                ring_column_applicable,
            )
            ctx = current_act_sharding()
            if ring_column_applicable(ctx, x.shape, w.shape):
                b = params["bias"].astype(dt) if self.use_bias else None
                y = ring_ag_matmul(x, w, b, ctx=ctx,
                                   out_kind=self.out_kind)
                return act_constrain(y, self.out_kind)
            maybe_record_column_fallback(ctx, x.shape, w.shape)
        y = jnp.matmul(x, w)
        if self.use_bias:
            y = y + params["bias"].astype(dt)
        return act_constrain(y, self.out_kind)


class RowParallelLinear(Module):
    """Linear whose *input* features shard over tp (W: (in/tp, out)).

    The contraction over the sharded dim leaves a partial sum; constraining
    the output to a tp-replicated spec makes GSPMD emit the allreduce — the
    same comm the reference deduces for ds ``-2`` partial states
    (`parallel_multi_ds.py:411`, ``distributed_states.h:133``).
    """

    def __init__(self, in_features: int, out_features: int, *,
                 bias: bool = True, init=None, axis: str = "mlp",
                 overlap: str = "auto"):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.use_bias = bias
        self.overlap = overlap
        self.param("weight", (in_features, out_features),
                   init or normal_init(0.02), axes=(axis, "embed"))
        if bias:
            self.param("bias", (out_features,), zeros_init(), axes=(None,))

    def __call__(self, params, x):
        dt = self.compute_dtype()
        x = x.astype(dt)
        w = params["weight"].astype(dt)
        if _ring_overlap_active(self.overlap):
            from hetu_tpu.parallel.overlap import (
                maybe_record_row_fallback, ring_matmul_rs,
                ring_row_applicable,
            )
            ctx = current_act_sharding()
            if ring_row_applicable(ctx, x.shape, w.shape):
                # the ring IS the reduce(-scatter): no act_constrain
                # needed to trigger the collective, the output already
                # carries the "tokens" layout
                y = ring_matmul_rs(x, w, ctx=ctx)
                if self.use_bias:
                    y = y + params["bias"].astype(dt)
                return y
            maybe_record_row_fallback(ctx, x.shape, w.shape)
        y = jnp.matmul(x, w)
        y = act_constrain(y, "tokens")
        if self.use_bias:
            y = y + params["bias"].astype(dt)
        return y


class VocabParallelEmbedding(Module):
    """Embedding with the vocabulary dim sharded over tp.

    Reference: ``HtMultiVocabParallelEmbedding`` (`parallel_multi_ds.py:268`)
    — masked local lookup + allreduce. When an ActivationSharding context with
    tp>1 is active the lookup runs under ``shard_map`` (local masked take +
    ``psum``), so no device materializes the full table; otherwise a plain
    take.
    """

    def __init__(self, num_embeddings: int, features: int, init=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.features = features
        self.param("weight", (num_embeddings, features),
                   init or normal_init(0.02), axes=("vocab", "embed"))

    def __call__(self, params, ids):
        w = params["weight"]
        ctx = current_act_sharding()
        if ctx is not None and isinstance(ctx.tp, str) \
                and ctx.mesh.shape[ctx.tp] > 1 \
                and self.num_embeddings % ctx.mesh.shape[ctx.tp] == 0:
            out = _vocab_parallel_lookup(w, ids, ctx)
        else:
            out = embed_ops.embedding_lookup(w, ids)
        return act_constrain(out.astype(self.compute_dtype()), "tokens")


def _vocab_parallel_lookup(weight, ids, ctx):
    tp = ctx.tp
    v_local = weight.shape[0] // ctx.mesh.shape[tp]
    # decide the table-grad formulation from the GLOBAL vocab (inside
    # shard_map w is the V/tp local shard, which would trip the measured
    # winner's vocab-distance guard at high tp even though per-shard
    # token count — the quantity the probe measured — is unchanged)
    bwd = embed_ops.preferred_embedding_bwd(weight.shape[0])

    @functools.partial(
        shard_map, mesh=ctx.mesh,
        in_specs=(P(tp, None), P(ctx.batch, ctx.seq)),
        out_specs=P(ctx.batch, ctx.seq, None), check_vma=False)
    def lookup(w, ids):
        start = jax.lax.axis_index(tp) * v_local
        local = ids - start
        ok = (local >= 0) & (local < v_local)
        # masked local take; the measured onehot-matmul formulation can
        # replace the scatter-add table grad on TPU
        emb = embed_ops.embedding_lookup(
            w, jnp.clip(local, 0, v_local - 1), bwd=bwd)
        emb = jnp.where(ok[..., None], emb, jnp.zeros([], emb.dtype))
        return jax.lax.psum(emb, tp)

    return lookup(weight, ids)


def lora_apply(lora, name, x, y):
    """Batched-gather LoRA (the Punica / S-LoRA "BGMV" shape): add the
    per-token adapter delta for projection ``name`` to its base output
    ``y``.

    ``lora`` is ``{"ids": (b, s) int32 arena pages, "pages": {name:
    {"A": (P, in, r), "B": (P, r, out)}}}`` — ONE layer's slice of the
    device-resident adapter arena (the stacked (L, P, ...) tree rides
    ``StackedBlocks.decode``'s scan as xs; the page ids close over the
    scan body).  Each token gathers its page's A/B slice and two
    batched einsums produce the delta; scaling is folded into B at
    registry load time so no per-adapter scalars ride the step.

    Page 0 is the base model's zero page: those tokens take ``y`` back
    through a masked select rather than ``y + 0.0``, so base-only
    tokens stay BITWISE identical to a build without the lane (``-0.0
    + 0.0`` would flip sign bits).  ``lora`` None/empty or a projection
    the arena does not carry returns ``y`` untouched — no extra ops.
    """
    if not lora or name not in lora["pages"]:
        return y
    ab = lora["pages"][name]
    ids = lora["ids"]                               # (b, s) pages
    a = ab["A"][ids]                                # (b, s, in, r)
    bm = ab["B"][ids]                               # (b, s, r, out)
    t = jnp.einsum("bsi,bsir->bsr", x.astype(a.dtype), a)
    d = jnp.einsum("bsr,bsro->bso", t, bm)
    return jnp.where((ids != 0)[..., None], y + d.astype(y.dtype), y)


class ParallelMLP(Module):
    """Transformer MLP: column-parallel up, row-parallel down.

    ``gated=True`` gives the Llama SwiGLU form (reference MLP
    `llama_model.py:292`, fused kernel ``impl/kernel/SwiGLU.cu``); otherwise
    GPT-2 GELU.
    """

    def __init__(self, features: int, hidden: int, *, bias: bool = True,
                 gated: bool = False, activation=None):
        super().__init__()
        self.gated = gated
        self.activation = activation or (act_ops.swiglu if gated
                                         else jax.nn.gelu)
        if gated:
            # separate gate/up projections: both column-sharded over tp, so
            # the elementwise gate never crosses a shard boundary (a fused
            # (E, 2H) kernel + split would force a per-layer reshard)
            self.gate_proj = ColumnParallelLinear(
                features, hidden, bias=bias, axis="mlp", out_kind="hidden")
            self.up_proj = ColumnParallelLinear(
                features, hidden, bias=bias, axis="mlp", out_kind="hidden")
        else:
            self.fc_in = ColumnParallelLinear(
                features, hidden, bias=bias, axis="mlp", out_kind="hidden")
        self.fc_out = RowParallelLinear(hidden, features, bias=bias,
                                        axis="mlp")

    def __call__(self, params, x, *, w8a8=None, w8a8_wq=None,
                 lora=None):
        """``w8a8`` (None | traced bool) selects the quantized-COMPUTE
        lane per call: activations quantize per token, weights per
        output channel, and both matmuls contract in int8 with one
        fused rescale (``ops.quantization.int8_w8a8_matmul``). A traced
        flag rides ``lax.cond`` so the serving engine can A/B the lane
        PER LAYER as data (``StackedBlocks.decode(w8a8_mask=)``);
        ``None`` (the default, and every training path) is exactly the
        historical fp lane — no cond, bit-for-bit unchanged.

        ``w8a8_wq`` (a :meth:`prequantize` tree for THIS layer) skips
        the per-call weight quantization: only the per-token activation
        quant remains on the hot path — the serving engine quantizes
        once at construction / weight swap.

        ``lora`` (a :func:`lora_apply` dict for THIS layer) adds the
        batched multi-adapter BGMV delta to every targeted projection;
        None is exactly the historical lane."""
        if w8a8 is None:
            return self._fp_lane(params, x, lora=lora)
        if w8a8_wq is None and lora is None:
            return jax.lax.cond(w8a8, self._w8a8_lane, self._fp_lane,
                                params, x)
        return jax.lax.cond(
            w8a8,
            lambda p, v: self._w8a8_lane(p, v, wq=w8a8_wq, lora=lora),
            lambda p, v: self._fp_lane(p, v, lora=lora),
            params, x)

    def prequantize(self, params, *, stacked: bool = False):
        """Quantize this MLP's weight matrices ONCE into the W8A8
        lane's ``{name: {"q": int8, "scale": fp32}}`` tree (per-output-
        channel scales over the contraction axis — ``axis=1`` for a
        ``StackedBlocks`` (L, in, out) param tree, ``axis=0`` for a
        single layer). Feed the result back via ``w8a8_wq=`` so the
        decode lane stops paying the per-step quantize of weights that
        never change between steps."""
        from hetu_tpu.ops.quantization import quantize_int8
        axis = 1 if stacked else 0
        names = (["gate_proj", "up_proj"] if self.gated
                 else ["fc_in"]) + ["fc_out"]
        return {
            name: dict(zip(("q", "scale"), quantize_int8(
                params[name]["weight"], axis=axis)))
            for name in names
        }

    def _fp_lane(self, params, x, lora=None):
        if self.gated:
            g = lora_apply(lora, "gate_proj", x,
                           self.gate_proj(params["gate_proj"], x))
            u = lora_apply(lora, "up_proj", x,
                           self.up_proj(params["up_proj"], x))
            h = self.activation(g, u)
        else:
            h = self.activation(lora_apply(
                lora, "fc_in", x, self.fc_in(params["fc_in"], x)))
        h = act_constrain(h, "hidden")
        return lora_apply(lora, "fc_out", h,
                          self.fc_out(params["fc_out"], h))

    def _w8a8_lane(self, params, x, wq=None, lora=None):
        """Both FFN matmuls in int8 (W8A8). Biases and the activation
        stay fp; the canonical activation cut points keep their
        ``act_constrain`` layouts so GSPMD shards the lane like the fp
        one. Weights quantize at trace time from the live fp params —
        or, when ``wq`` carries a :meth:`prequantize` tree, stream
        pre-quantized int8 weights straight into the contraction
        (halving the lane's weight reads: no fp load + int8 re-store
        per step)."""
        from hetu_tpu.ops.quantization import (
            int8_w8a8_matmul, int8_w8a8_matmul_prequant,
        )
        dt = self.compute_dtype()
        x = x.astype(dt)

        def mm(v, p, name):
            if wq is not None:
                return int8_w8a8_matmul_prequant(
                    v, wq[name]["q"], wq[name]["scale"], dtype=dt)
            return int8_w8a8_matmul(v, p["weight"].astype(dt), dtype=dt)

        def lin(mod, p, name):
            y = mm(x, p, name)
            if mod.use_bias:
                y = y + p["bias"].astype(dt)
            return act_constrain(lora_apply(lora, name, x, y), "hidden")

        if self.gated:
            h = self.activation(
                lin(self.gate_proj, params["gate_proj"], "gate_proj"),
                lin(self.up_proj, params["up_proj"], "up_proj"))
        else:
            h = self.activation(lin(self.fc_in, params["fc_in"], "fc_in"))
        h = act_constrain(h, "hidden")
        y = mm(h, params["fc_out"], "fc_out")
        y = act_constrain(y, "tokens")
        if self.fc_out.use_bias:
            y = y + params["fc_out"]["bias"].astype(dt)
        return lora_apply(lora, "fc_out", h, y)


class ParallelAttention(Module):
    """Multi-head attention with GQA, RoPE and flash-kernel dispatch, heads
    sharded over tp.

    Reference: ``HtMultiQKVColumnParallelLinear`` (`parallel_multi_ds.py:504`)
    + ``ParallelAttentionOp`` cp=1 path (`hetu/graph/ops/ParallelAttention.h:711`).
    Ring-attention CP wraps this at the op level
    (``hetu_tpu.parallel.ring_attention``) — this module stays cp-agnostic
    and only sees its local sequence chunk (positions/segment_ids make the
    causal mask correct for chunks).
    """

    def __init__(self, embed_dim: int, num_heads: int, *,
                 num_kv_heads: Optional[int] = None,
                 head_dim: Optional[int] = None,
                 bias: bool = True, causal: bool = True,
                 use_rope: bool = False, rope_theta: float = 10000.0,
                 max_positions: int = 4096, init=None):
        super().__init__()
        self.num_heads = num_heads
        self.num_kv_heads = num_kv_heads or num_heads
        if num_heads % self.num_kv_heads != 0:
            raise ValueError("num_heads must be divisible by num_kv_heads")
        self.head_dim = head_dim or embed_dim // num_heads
        self.causal = causal
        self.use_rope = use_rope
        init = init or normal_init(0.02)
        self.q_proj = ColumnParallelLinear(
            embed_dim, num_heads * self.head_dim, bias=bias, init=init,
            axis="heads", out_kind="hidden")
        self.k_proj = ColumnParallelLinear(
            embed_dim, self.num_kv_heads * self.head_dim, bias=bias,
            init=init, axis="kv_heads", out_kind="hidden")
        self.v_proj = ColumnParallelLinear(
            embed_dim, self.num_kv_heads * self.head_dim, bias=bias,
            init=init, axis="kv_heads", out_kind="hidden")
        self.out_proj = RowParallelLinear(
            num_heads * self.head_dim, embed_dim, bias=bias, init=init,
            axis="heads")
        if use_rope:
            self._rope = rope_frequencies(self.head_dim, max_positions,
                                          theta=rope_theta)
        else:
            self._rope = None

    def __call__(self, params, x, *, positions=None, segment_ids=None,
                 attn_impl: str = "auto", kv_cache=None, slot_mask=None,
                 block_tables=None, row_mask=None, attn_kernel="reference",
                 pack=None, dropout_rate: float = 0.0, dropout_key=None,
                 return_kv: bool = False, lora=None):
        """``return_kv=True`` (train path only) additionally returns the
        rotary-applied per-head ``(k, v)`` of this call — the exact
        values the decode path would have written to a KV cache — as
        ``(out, (k, v))``. The serving CP-prefill lane uses this to run
        a long prompt through the TRAINING forward (ring/ulysses over
        the cp axis) and scatter the resulting KV into the paged arena
        (``StackedBlocks.prefill``)."""
        if kv_cache is not None:
            if return_kv:
                raise ValueError(
                    "return_kv applies to the training forward only "
                    "(decode already threads its cache)")
            return self._decode(params, x, kv_cache, positions=positions,
                                slot_mask=slot_mask,
                                block_tables=block_tables,
                                row_mask=row_mask,
                                attn_kernel=attn_kernel, pack=pack,
                                lora=lora)
        b, s, _ = x.shape
        q = self.q_proj(params["q_proj"], x).reshape(
            b, s, self.num_heads, self.head_dim)
        k = self.k_proj(params["k_proj"], x).reshape(
            b, s, self.num_kv_heads, self.head_dim)
        v = self.v_proj(params["v_proj"], x).reshape(
            b, s, self.num_kv_heads, self.head_dim)
        if self._rope is not None:
            cos, sin = self._rope
            q = apply_rotary(q, cos, sin, positions=positions)
            k = apply_rotary(k, cos, sin, positions=positions)
        q = act_constrain(q, "heads")
        k = act_constrain(k, "heads")
        v = act_constrain(v, "heads")
        ctx = current_act_sharding()
        mctx = current_manual_axes()
        manual_cp = (ctx is None and mctx is not None
                     and "cp" in mctx.axes and mctx.mesh.shape["cp"] > 1)
        gspmd_cp = (ctx is not None and isinstance(ctx.seq, str)
                    and ctx.mesh.shape[ctx.seq] > 1)
        if manual_cp:
            # inside a manual region (pipeline executor) with cp bound:
            # run the cp attention core directly on the bound axis —
            # x/q/k/v here are the per-device local seq chunks
            if mctx.cp_impl == "ulysses":
                from hetu_tpu.parallel.ulysses import \
                    ulysses_attention_manual
                out = ulysses_attention_manual(
                    q, k, v, axis_name="cp", cp=mctx.mesh.shape["cp"],
                    tp=mctx.mesh.shape.get("tp", 1), causal=self.causal,
                    segment_ids=segment_ids, impl=attn_impl,
                    dropout_rate=dropout_rate, dropout_key=dropout_key)
            else:
                from hetu_tpu.parallel.ring_attention import \
                    ring_attention_manual
                out = ring_attention_manual(
                    q, k, v, axis_name="cp", cp=mctx.mesh.shape["cp"],
                    causal=self.causal, segment_ids=segment_ids,
                    impl=attn_impl, layout=mctx.cp_layout,
                    dropout_rate=dropout_rate, dropout_key=dropout_key)
        elif gspmd_cp:
            # context parallelism: seq dim is sharded — KV ring
            # (reference: ParallelAttentionOp → AttnCommRing) or the
            # beyond-reference Ulysses all_to_all head scatter
            if getattr(ctx, "cp_impl", "ring") == "ulysses":
                from hetu_tpu.parallel.ulysses import ulysses_attention
                out = ulysses_attention(q, k, v, ctx=ctx,
                                        causal=self.causal,
                                        segment_ids=segment_ids,
                                        impl=attn_impl,
                                        dropout_rate=dropout_rate,
                                        dropout_key=dropout_key)
            else:
                from hetu_tpu.parallel.ring_attention import ring_attention
                out = ring_attention(q, k, v, ctx=ctx, causal=self.causal,
                                     segment_ids=segment_ids,
                                     impl=attn_impl,
                                     dropout_rate=dropout_rate,
                                     dropout_key=dropout_key)
        else:
            out = flash_attention(q, k, v, causal=self.causal,
                                  segment_ids=segment_ids, impl=attn_impl,
                                  dropout_rate=dropout_rate,
                                  dropout_key=dropout_key)
        out = act_constrain(out, "heads")
        out = out.reshape(b, s, self.num_heads * self.head_dim)
        out = self.out_proj(params["out_proj"], out)
        if return_kv:
            return out, (k, v)
        return out

    def _decode(self, params, x, kv_cache, *, positions=None,
                slot_mask=None, block_tables=None, row_mask=None,
                attn_kernel: str = "reference", pack=None, lora=None):
        """Incremental decoding with a KV cache.

        ``kv_cache``: (k_buf, v_buf) of shape (b, max_len, hkv, d); the
        write ``index`` arrives via ``positions[:, 0]``-style absolute
        positions (all rows share the index — batched decode). Replaces
        the reference's dynamic-concat KV append op (inference path of
        ``graph/ops``: dynamic concat).

        ``kv_cache``: (k_buf, v_buf) of shape (b, max_len, hkv, d), or
        the QUANTIZED 4-tuple (k int8, k scales, v int8, v scales) with
        (b, max_len, hkv, 1) fp32 scales (``generation.init_kv_caches``
        with dtype=jnp.int8) — new rows quantize on write, the read
        dequant fuses into the attention einsum.

        ``slot_mask`` switches to PER-ROW decode (the serving engine's
        slot-pooled path): every batch row writes at its own
        ``positions[:, 0]`` index and the causal mask uses per-row
        offsets, so requests at different depths decode in one batched
        call. Rows with ``slot_mask=False`` (free / prefilling slots)
        leave their cache rows untouched (their compute is discarded by
        the caller).

        ``block_tables`` (b, W) switches the cache to the PAGED layout:
        leaves are ``(n_blocks, block_size, hkv, d)`` arenas shared by
        every row, and row ``r``'s position ``p`` lives at arena row
        ``block_tables[r, p // bs] * bs + p % bs``. Writes become flat
        scatters (rows with ``slot_mask=False`` scatter out of bounds
        and are dropped), reads gather through the table
        (:func:`~hetu_tpu.ops.attention.gather_block_rows`). Requires
        ``slot_mask`` (per-row positions are the only meaningful paged
        mode).

        ``row_mask`` (b, s) bool refines ``slot_mask`` WITHIN a row's
        ``s`` positions: only masked-true cells write their KV (the
        rest scatter out of bounds and drop). The speculative-decoding
        verify lane needs this — a slot verifying fewer than the step's
        max draft depth must not write the unused trailing rows, whose
        positions could land beyond the blocks its table owns (a
        clamped scatter there would corrupt a live block). Paged mode
        only.

        ``attn_kernel`` ("reference" | "paged", paged mode only)
        selects HOW the attention reads the arena: "reference" is the
        XLA-gather path (materializes each row's full table view —
        :func:`~hetu_tpu.ops.attention.gather_block_rows`, the
        CPU/0.4.37 fallback), "paged" streams KV tiles through the
        block tables inside the Pallas kernel
        (:func:`~hetu_tpu.ops.paged_pallas.paged_attention_pallas` —
        no materialized gather, cost ∝ live context). Resolve requests
        with :func:`~hetu_tpu.ops.attention.resolve_decode_kernel`.

        ``pack`` switches to the PACKED-PREFILL flash mode
        (:meth:`_decode_packed`): ``x`` is one ``(1, C, embed)`` row of
        C pack tokens from many requests, with per-token
        ``block_tables`` (C, W) / ``positions`` (1, C) and pack dict
        keys ``segment_ids`` (1, C), ``hist`` (C,), ``valid`` (C,),
        ``impl``."""
        if pack is not None:
            return self._decode_packed(params, x, kv_cache,
                                       positions=positions,
                                       block_tables=block_tables,
                                       pack=pack,
                                       attn_kernel=attn_kernel,
                                       lora=lora)
        quant = len(kv_cache) == 4
        b, s, _ = x.shape
        per_row = slot_mask is not None
        paged = block_tables is not None
        if paged and not per_row:
            raise ValueError("block_tables requires slot_mask "
                             "(per-row paged decode)")
        if row_mask is not None and not paged:
            raise ValueError("row_mask requires block_tables (the "
                             "dense cache writes contiguous rows)")
        if per_row:
            index = positions[:, 0]                     # (b,) per-slot
        else:
            index = positions[0, 0] if positions is not None else 0
        q = lora_apply(lora, "q_proj", x,
                       self.q_proj(params["q_proj"], x)).reshape(
            b, s, self.num_heads, self.head_dim)
        k = lora_apply(lora, "k_proj", x,
                       self.k_proj(params["k_proj"], x)).reshape(
            b, s, self.num_kv_heads, self.head_dim)
        v = lora_apply(lora, "v_proj", x,
                       self.v_proj(params["v_proj"], x)).reshape(
            b, s, self.num_kv_heads, self.head_dim)
        if self._rope is not None:
            cos, sin = self._rope
            pos = positions if positions is not None \
                else jnp.arange(s)[None, :]
            q = apply_rotary(q, cos, sin, positions=pos)
            k = apply_rotary(k, cos, sin, positions=pos)

        if paged:
            n_blk, blk = kv_cache[0].shape[0], kv_cache[0].shape[1]
            pos_rows = index[:, None] + jnp.arange(s)[None, :]  # (b, s)
            blk_ids = jnp.take_along_axis(block_tables,
                                          pos_rows // blk, axis=1)
            rows = blk_ids * blk + pos_rows % blk
            # masked-off rows scatter out of bounds → dropped (the
            # paged analogue of the jnp.where keep-mask below)
            keep = slot_mask[:, None]
            if row_mask is not None:
                keep = keep & row_mask
            rows = jnp.where(keep, rows, n_blk * blk).reshape(-1)

        def upd(buf, new):
            if paged:
                flat = buf.reshape((n_blk * blk,) + buf.shape[2:])
                flat = flat.at[rows].set(
                    new.reshape((-1,) + new.shape[2:]).astype(buf.dtype),
                    mode="drop")
                return flat.reshape(buf.shape)
            if per_row:
                # per-slot scatter: row r writes its s new entries at
                # index[r]; inactive slots select their old rows back
                written = jax.vmap(
                    lambda bb, nn, ii: jax.lax.dynamic_update_slice_in_dim(
                        bb, nn, ii, axis=0))(buf, new.astype(buf.dtype),
                                             index)
                keep = slot_mask.reshape((b,) + (1,) * (buf.ndim - 1))
                return jnp.where(keep, written, buf)
            return jax.lax.dynamic_update_slice_in_dim(
                buf, new.astype(buf.dtype), index, axis=1)

        if quant:
            # int8 KV cache: decode is HBM-bound on the cache read, so
            # 1 byte/elem halves the bandwidth vs bf16 (and 4x vs fp32);
            # XLA fuses the dequant into the attention einsum's operand
            # stream (compiler-verified: workloads/quant_bench.py --aot).
            # Per-(position, head) symmetric scales over head_dim; zero
            # scales on never-written slots dequantize to exact 0, like
            # the fp cache's zeros.
            from hetu_tpu.ops.quantization import (dequantize_int8,
                                                   quantize_int8)
            kq_b, ks_b, vq_b, vs_b = kv_cache
            knew_q, knew_s = quantize_int8(k, axis=-1)
            vnew_q, vnew_s = quantize_int8(v, axis=-1)
            kq_b, ks_b = upd(kq_b, knew_q), upd(ks_b, knew_s)
            vq_b, vs_b = upd(vq_b, vnew_q), upd(vs_b, vnew_s)
            new_cache = (kq_b, ks_b, vq_b, vs_b)
        else:
            k_buf, v_buf = kv_cache
            k_buf, v_buf = upd(k_buf, k), upd(v_buf, v)
            new_cache = (k_buf, v_buf)

        if paged and attn_kernel == "paged" and self.causal:
            # the Pallas kernel streams arena tiles through the block
            # tables — no materialized gather, dead lanes skipped, int8
            # pages dequantized per tile in VMEM; the _auto wrapper
            # shard_maps the call over a tp-sharded plan's head axis
            # (Mosaic kernels cannot be GSPMD-auto-partitioned)
            from hetu_tpu.ops.paged_pallas import paged_attention_auto
            if quant:
                out = paged_attention_auto(
                    q, kq_b, vq_b, block_tables, index,
                    k_scale=ks_b, v_scale=vs_b)
            else:
                out = paged_attention_auto(
                    q, k_buf, v_buf, block_tables, index)
        elif paged:
            if attn_kernel == "paged":
                from hetu_tpu.ops.attention import record_kernel_fallback
                record_kernel_fallback(
                    "decode_non_causal",
                    "the paged kernel implements causal decode only")
            # the XLA-gather twin (int8 arenas gather quantized rows +
            # scales — 1/4 the bytes — and dequantize after); causal
            # offsets mask both the future and never-written slots
            from hetu_tpu.ops.paged_pallas import \
                paged_attention_reference
            if quant:
                out = paged_attention_reference(
                    q, kq_b, vq_b, block_tables, index,
                    k_scale=ks_b, v_scale=vs_b, causal=self.causal)
            else:
                out = paged_attention_reference(
                    q, k_buf, v_buf, block_tables, index,
                    causal=self.causal)
        else:
            if quant:
                from hetu_tpu.ops.quantization import dequantize_int8
                k_buf = dequantize_int8(kq_b, ks_b, q.dtype)
                v_buf = dequantize_int8(vq_b, vs_b, q.dtype)
            out = attention_reference(
                q, k_buf, v_buf, causal=self.causal,
                q_offset=index, kv_offset=0)
        out = out.reshape(b, s, self.num_heads * self.head_dim)
        return lora_apply(lora, "out_proj", out,
                          self.out_proj(params["out_proj"], out)), \
            new_cache

    def _decode_packed(self, params, x, kv_cache, *, positions,
                       block_tables, pack, attn_kernel, lora=None):
        """Packed-prefill FLASH mode: the serving engine's prefill pack
        as ONE ``(1, C, embed)`` row instead of C one-token batch rows.

        The C tokens belong to many requests (``pack["segment_ids"]``,
        -1 on pad lanes); each token's attention decomposes into two
        DISJOINT parts that LSE-combine exactly
        (``ops.paged_pallas.combine_attention_lse``):

        - **intra-pack**: flash attention over the pack itself with
          segment isolation + causal masking — within one request's
          contiguous run positions ascend with pack index, so
          index-causality IS position-causality, and segment ids stop
          any cross-request (or cross-document) leakage;
        - **arena history**: each token attends its request's
          already-resident KV — earlier chunks of a multi-chunk
          prompt, prefix-cache hits — through its block table, masked
          to positions ``< pack["hist"][t]`` (the token's chunk-start
          offset, so the rows this very pack just scattered are
          excluded: the intra part owns them).

        KV writes stay per-token scatters through the tables (pads drop
        out of bounds), bit-identical to the per-token reference lane —
        only the attention READ changes formulation."""
        if not self.causal:
            raise ValueError(
                "the packed-prefill flash lane requires causal "
                "attention: its intra-pack/arena-history split relies "
                "on the causal position mask to keep the two KV sets "
                "disjoint (use prefill_attn='reference')")
        quant = len(kv_cache) == 4
        b, C, _ = x.shape
        n_blk, blk = kv_cache[0].shape[0], kv_cache[0].shape[1]
        q = lora_apply(lora, "q_proj", x,
                       self.q_proj(params["q_proj"], x)).reshape(
            b, C, self.num_heads, self.head_dim)
        k = lora_apply(lora, "k_proj", x,
                       self.k_proj(params["k_proj"], x)).reshape(
            b, C, self.num_kv_heads, self.head_dim)
        v = lora_apply(lora, "v_proj", x,
                       self.v_proj(params["v_proj"], x)).reshape(
            b, C, self.num_kv_heads, self.head_dim)
        if self._rope is not None:
            cos, sin = self._rope
            q = apply_rotary(q, cos, sin, positions=positions)
            k = apply_rotary(k, cos, sin, positions=positions)
        pos = positions[0]                               # (C,)
        blk_ids = jnp.take_along_axis(block_tables,
                                      (pos // blk)[:, None], axis=1)[:, 0]
        rows = jnp.where(pack["valid"], blk_ids * blk + pos % blk,
                         n_blk * blk)                    # pad → dropped

        def upd(buf, new):
            flat = buf.reshape((n_blk * blk,) + buf.shape[2:])
            flat = flat.at[rows].set(new[0].astype(buf.dtype),
                                     mode="drop")
            return flat.reshape(buf.shape)

        if quant:
            from hetu_tpu.ops.quantization import (dequantize_int8,
                                                   quantize_int8)
            kq_b, ks_b, vq_b, vs_b = kv_cache
            knew_q, knew_s = quantize_int8(k, axis=-1)
            vnew_q, vnew_s = quantize_int8(v, axis=-1)
            kq_b, ks_b = upd(kq_b, knew_q), upd(ks_b, knew_s)
            vq_b, vs_b = upd(vq_b, vnew_q), upd(vs_b, vnew_s)
            new_cache = (kq_b, ks_b, vq_b, vs_b)
            # the reference per-token lane attends the arena's
            # ROUND-TRIPPED int8 values for in-pack rows — match it
            k = dequantize_int8(knew_q, knew_s, q.dtype)
            v = dequantize_int8(vnew_q, vnew_s, q.dtype)
        else:
            k_b, v_b = kv_cache
            k_b, v_b = upd(k_b, k), upd(v_b, v)
            new_cache = (k_b, v_b)

        from hetu_tpu.ops.attention import attention_with_lse
        from hetu_tpu.ops.paged_pallas import (
            combine_attention_lse, paged_attention_auto,
            paged_attention_reference,
        )
        intra, lse_i = attention_with_lse(
            q, k, v, causal=self.causal,
            segment_ids=pack["segment_ids"], impl=pack["impl"])

        qh = q[0][:, None]                       # (C, 1, hq, d) rows
        hist_off = pack["hist"].astype(jnp.int32) - 1   # kpos <= hist-1
        if quant:
            arena = dict(k_scale=ks_b, v_scale=vs_b)
            ka, va = kq_b, vq_b
        else:
            arena = {}
            ka, va = k_b, v_b
        if attn_kernel == "paged":
            hist, lse_h = paged_attention_auto(
                qh, ka, va, block_tables, hist_off, return_lse=True,
                **arena)
        else:
            hist, lse_h = paged_attention_reference(
                qh, ka, va, block_tables, hist_off, return_lse=True,
                **arena)
        hist = hist[:, 0][None]                  # (1, C, hq, d)
        lse_h = lse_h[:, :, 0].T[None]           # (C, hq, 1) → (1, hq, C)
        out = combine_attention_lse(intra, lse_i, hist, lse_h)
        out = out.reshape(b, C, self.num_heads * self.head_dim)
        return lora_apply(lora, "out_proj", out,
                          self.out_proj(params["out_proj"], out)), \
            new_cache


def remat_policy(name: str):
    """Map a Strategy remat/offload name to a ``jax.checkpoint`` policy.

    Reference equivalents: recompute pass (``recompute/recompute.h:12``) and
    activation CPU offload pass (``offload/activation_cpu_offload.h:11``).
    """
    if name == "none":
        return None
    if name == "full":
        return jax.checkpoint_policies.nothing_saveable
    if name == "selective":
        # dots + the flash-attention kernel residuals (tagged in
        # ``ops.flash_pallas._flash_core_fwd``): saving out/lse means the
        # backward runs only the flash bwd kernels, not fwd again
        return jax.checkpoint_policies.save_from_both_policies(
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            jax.checkpoint_policies.save_only_these_names(
                "flash_out", "flash_lse"))
    if name == "offload":
        make = getattr(jax.checkpoint_policies,
                       "offload_dot_with_no_batch_dims", None)
        # host offload needs the TPU runtime's annotate_device_placement;
        # the CPU backend has no implementation (and GSPMD on CPU chokes
        # on the unsharded side-effect custom call) — degrade to full
        # remat there so offload strategies stay runnable in simulation
        if make is None or jax.default_backend() != "tpu":
            return jax.checkpoint_policies.nothing_saveable
        return make("device", "pinned_host")
    raise ValueError(
        f"remat must be none|full|selective|offload, got {name!r}")


class StackedBlocks(Module):
    """N identical blocks as one scan, params stacked on a leading ``layers``
    dim.

    The reference represents depth as N distinct subgraphs with per-block
    recompute/offload flags (`llama_model.py:342`); on TPU the idiomatic form
    is a single block traced once and scanned, with the stacked ``layers``
    axis available to the pipeline executor (axis rule ``"layers" → "pp"``)
    and ``jax.checkpoint`` applied per block for recompute parity.
    """

    def __init__(self, make_block: Callable[[], Module], num_layers: int):
        super().__init__()
        self.num_layers = num_layers
        self._block = make_block()  # underscore: excluded from children()

    @property
    def block(self) -> Module:
        return self._block

    def children(self):
        # expose the template so module-tree walks (named_modules, LoRA
        # injection) reach the per-layer submodules; abstract_specs is
        # overridden so this never double-counts params
        return {"block": self._block}

    def abstract_specs(self) -> dict:
        inner = self._block.abstract_specs()
        L = self.num_layers

        def wrap(spec: ParamSpec) -> ParamSpec:
            def init(key, shape, dtype, _orig=spec):
                keys = jax.random.split(key, shape[0])
                return jax.vmap(
                    lambda k: _orig.init(k, _orig.shape, dtype))(keys)
            axes = spec.axes if spec.axes is not None \
                else (None,) * len(spec.shape)
            return ParamSpec((L,) + spec.shape, init, spec.dtype,
                             ("layers",) + axes)

        return jax.tree.map(wrap, inner,
                            is_leaf=lambda x: isinstance(x, ParamSpec))

    @property
    def returns_aux(self):
        return self._block.returns_aux

    def __call__(self, params, x, *, remat: str = "none",
                 remat_mask: Optional[Sequence[bool]] = None,
                 unroll: bool = False, **kwargs):
        """``remat_mask``: per-layer recompute flags (the reference's
        per-block recompute config, ``recompute.h:12`` via ds-config
        ``recompute_config``; emitted by ``search_layerwise``). Layers are
        grouped into consecutive runs, one scan per run, remat applied to
        the True runs (policy = ``remat`` or "full" when remat is none).

        ``unroll`` unrolls the layer scan into straight-line code: XLA
        then schedules across layer boundaries and drops the per-layer
        dynamic-update-slice residual stacking (measurably faster on a
        single chip; costs compile time ∝ layers)."""
        # layer count comes from the params actually passed — pipeline /
        # hetero executors call this with a per-stage CHUNK whose leading
        # axis is shorter than the full model's num_layers
        n_layers = jax.tree.leaves(params)[0].shape[0]
        unroll_n = n_layers if unroll else 1
        # per-layer dropout keys ride the scan as xs (None = deterministic)
        dropout_key = kwargs.pop("dropout_key", None)
        layer_keys = None if dropout_key is None \
            else jax.random.split(dropout_key, n_layers)

        def call_block(layer_params, h, xs_key):
            if xs_key is not None:
                return self._block(layer_params, h, dropout_key=xs_key,
                                   **kwargs)
            return self._block(layer_params, h, **kwargs)

        # per-layer ZeRO-3 gather ring (Strategy(fsdp_overlap="ring")):
        # block params arrive dp-sharded on inner dims and each layer is
        # gathered explicitly — block k+1's gather prefetched under
        # block k's compute — instead of GSPMD's monolithic all-gather
        ctx = current_act_sharding()
        if (ctx is not None
                and getattr(ctx, "fsdp_overlap", "off") == "ring"
                and getattr(ctx, "fsdp_specs", None) is not None
                and ctx.mesh.shape.get("dp", 1) > 1):
            return self._fsdp_ring_scan(
                params, x, ctx, remat=remat, remat_mask=remat_mask,
                unroll=unroll, n_layers=n_layers, layer_keys=layer_keys,
                call_block=call_block)

        if self._block.returns_aux:
            def body(carry, xs):
                layer_params, xs_key = xs
                h, aux = carry
                h, a = call_block(layer_params, h, xs_key)
                return (h, aux + a), None
        else:
            def body(carry, xs):
                layer_params, xs_key = xs
                return call_block(layer_params, carry, xs_key), None

        def rematted(b, policy_name):
            return jax.checkpoint(b, policy=remat_policy(policy_name),
                                  prevent_cse=False)

        aux0 = jnp.zeros([], jnp.float32)
        carry0 = (x, aux0) if self._block.returns_aux else x

        if remat_mask is not None:
            if len(remat_mask) != n_layers:
                raise ValueError(
                    f"remat_mask has {len(remat_mask)} entries for "
                    f"{n_layers} layers")
            policy_name = remat if remat != "none" else "full"
            runs = []  # (start, stop, flag) consecutive same-flag runs
            start = 0
            for i in range(1, n_layers + 1):
                if i == n_layers \
                        or bool(remat_mask[i]) != bool(remat_mask[start]):
                    runs.append((start, i, bool(remat_mask[start])))
                    start = i
            carry = carry0
            for lo, hi, flag in runs:
                seg = jax.tree.map(lambda p: p[lo:hi], params)
                seg_keys = None if layer_keys is None else layer_keys[lo:hi]
                b = rematted(body, policy_name) if flag else body
                carry, _ = jax.lax.scan(b, carry, (seg, seg_keys),
                                        unroll=hi - lo if unroll else 1)
            if self._block.returns_aux:
                return carry
            return carry

        if remat != "none":
            body = rematted(body, remat)
        if self._block.returns_aux:
            (x, aux), _ = jax.lax.scan(body, carry0, (params, layer_keys),
                                       unroll=unroll_n)
            return x, aux
        x, _ = jax.lax.scan(body, x, (params, layer_keys), unroll=unroll_n)
        return x

    def _fsdp_ring_scan(self, params, x, ctx, *, remat, remat_mask,
                        unroll, n_layers, layer_keys, call_block):
        """ZeRO-3 per-block execution: every layer's dp-sharded params
        ring-gather (``parallel.overlap.ring_gather_block_params``)
        instead of riding one monolithic GSPMD all-gather.

        Two scan shapes, chosen per remat mode:

        - no remat → **prefetch-by-one**: the gathered params of layer
          *k* ride the scan carry while layer *k+1*'s gather is issued at
          the top of the body — the ring hops share no data with the
          block matmuls, so the scheduler overlaps them (ZeRO SC'20 §5.3
          prefetch);
        - remat → **gather inside the checkpointed region**: the saved
          residuals are the 1/ndp local shards, so the backward
          REGATHERS each block instead of pinning full replicated layer
          params (prefetch-by-one would make the gathered carry a saved
          checkpoint input, defeating ZeRO-3's memory point).
        """
        from hetu_tpu.parallel.overlap import (
            record_fsdp_gather_bytes, ring_gather_block_params,
        )
        mesh, specs = ctx.mesh, ctx.fsdp_specs
        ndp = mesh.shape["dp"]
        # analytic trace-time accounting: stacked leaf sizes already
        # cover every layer, and the ring is an overlapping path.
        # Rematted layers gather TWICE per step (the backward regathers
        # inside the checkpointed region) — scale their share.
        if remat_mask is not None:
            n_regather = sum(bool(f) for f in remat_mask)
        elif remat != "none":
            n_regather = n_layers
        else:
            n_regather = 0
        record_fsdp_gather_bytes(
            params, specs, ndp,
            n_layers=(n_layers + n_regather) / n_layers, overlapped=True)

        def gather(layer_params):
            return ring_gather_block_params(layer_params, specs,
                                            mesh=mesh)

        aux_mode = self._block.returns_aux

        def compute(g_params, carry, xs_key):
            if aux_mode:
                h, aux = carry
                h2, a = call_block(g_params, h, xs_key)
                return (h2, aux + a)
            return call_block(g_params, carry, xs_key)

        def seg_prefetch(carry, lo, hi):
            g0 = gather(jax.tree.map(lambda p: p[lo], params))
            idxs = jnp.arange(lo, hi)
            keys = None if layer_keys is None else layer_keys[lo:hi]

            def body(c, xs):
                i, xs_key = xs
                inner, g_cur = c
                # issue layer i+1's gather BEFORE layer i's compute —
                # the two share no data, XLA overlaps them (the last
                # iteration regathers hi-1; its result is discarded)
                nxt = jnp.minimum(i + 1, hi - 1)
                p_next = jax.tree.map(
                    lambda p: jax.lax.dynamic_index_in_dim(
                        p, nxt, 0, keepdims=False), params)
                g_next = gather(p_next)
                return (compute(g_cur, inner, xs_key), g_next), None

            (carry, _), _ = jax.lax.scan(
                body, (carry, g0), (idxs, keys),
                unroll=(hi - lo) if unroll else 1)
            return carry

        def seg_remat(carry, lo, hi, policy_name):
            seg = jax.tree.map(lambda p: p[lo:hi], params)
            keys = None if layer_keys is None else layer_keys[lo:hi]

            def body(c, xs):
                lp, xs_key = xs
                return compute(gather(lp), c, xs_key), None

            b = jax.checkpoint(body, policy=remat_policy(policy_name),
                               prevent_cse=False)
            carry, _ = jax.lax.scan(
                b, carry, (seg, keys),
                unroll=(hi - lo) if unroll else 1)
            return carry

        carry = (x, jnp.zeros([], jnp.float32)) if aux_mode else x
        if remat_mask is not None:
            if len(remat_mask) != n_layers:
                raise ValueError(
                    f"remat_mask has {len(remat_mask)} entries for "
                    f"{n_layers} layers")
            policy_name = remat if remat != "none" else "full"
            runs = []
            start = 0
            for i in range(1, n_layers + 1):
                if i == n_layers \
                        or bool(remat_mask[i]) != bool(remat_mask[start]):
                    runs.append((start, i, bool(remat_mask[start])))
                    start = i
            for lo, hi, flag in runs:
                carry = seg_remat(carry, lo, hi, policy_name) if flag \
                    else seg_prefetch(carry, lo, hi)
        elif remat != "none":
            carry = seg_remat(carry, 0, n_layers, remat)
        else:
            carry = seg_prefetch(carry, 0, n_layers)
        return carry

    def decode(self, params, x, caches, *, w8a8_mask=None,
               w8a8_wq=None, lora=None, **kwargs):
        """Incremental decoding: scan layers threading per-layer KV caches
        (leaves shaped (layers, b, max_len, hkv, d)).

        ``w8a8_mask`` ((layers,) bool, optional) rides the scan as xs:
        layer ``l``'s decode FFN takes the W8A8 int8 lane iff
        ``w8a8_mask[l]`` (``ParallelMLP.__call__(w8a8=...)``) — the
        per-layer A/B knob for quantized decode compute. ``None`` (the
        default) never touches the flag and stays bit-identical to the
        historical path. ``w8a8_wq`` (optional, a stacked
        ``prequantize`` tree with (layers, ...) leaves) also rides the
        scan as xs so each layer streams its pre-quantized int8
        weights instead of re-quantizing per step.

        ``lora`` (optional) is the multi-tenant adapter arena:
        ``{"ids": (b, s) int32 pages, "pages": {proj: {"A": (L, P, in,
        r), "B": (L, P, r, out)}}}``. The stacked pages ride the scan
        as xs (each layer sees its (P, ...) slice) while the per-token
        page ids close over the body; each layer's targeted
        projections add the :func:`lora_apply` BGMV delta."""
        xs = {"p": params, "c": caches}
        lora_ids = None
        if w8a8_mask is not None:
            xs["w8a8"] = jnp.asarray(w8a8_mask, bool)
            if w8a8_wq is not None:
                xs["wq"] = w8a8_wq
        if lora:
            xs["lora"] = lora["pages"]
            lora_ids = lora["ids"]

        def body(h, inputs):
            kw = dict(kwargs)
            if "w8a8" in inputs:
                kw["w8a8"] = inputs["w8a8"]
            if "wq" in inputs:
                kw["w8a8_wq"] = inputs["wq"]
            if "lora" in inputs:
                kw["lora"] = {"ids": lora_ids, "pages": inputs["lora"]}
            h, new_cache = self._block(inputs["p"], h,
                                       kv_cache=inputs["c"], **kw)
            return h, new_cache

        x, new_caches = jax.lax.scan(body, x, xs)
        return x, new_caches

    def prefill(self, params, x, *, positions=None, segment_ids=None,
                attn_impl: str = "auto"):
        """Training-mode forward that ALSO returns every layer's
        rotary-applied ``(k, v)``: ``(h, (k, v))`` with k/v shaped
        ``(layers, b, s, hkv, d)``.

        The serving CP-prefill lane's core: a long prompt runs through
        the SAME attention path training uses — under a cp-sharded
        activation context that means ring/ulysses attention over the
        mesh's cp axis — and the stacked KV is what the caller scatters
        into the paged serving arena. Inference-only by construction
        (no dropout, no remat; MoE aux losses are discarded)."""
        def body(h, layer_params):
            out = self._block(layer_params, h, positions=positions,
                              segment_ids=segment_ids,
                              attn_impl=attn_impl, return_kv=True)
            out, kv = out
            if self._block.returns_aux:
                out, _ = out
            return out, kv

        x, kvs = jax.lax.scan(body, x, params)
        return x, kvs
