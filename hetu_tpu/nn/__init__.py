from hetu_tpu.nn.module import (
    Module, ParamSpec, Sequential,
    zeros_init, ones_init, constant_init, normal_init, uniform_init,
    xavier_uniform_init, kaiming_uniform_init,
)
from hetu_tpu.nn.layers import (
    Linear, Embedding, LayerNorm, RMSNorm, Dropout, MLP,
)

__all__ = [
    "Module", "ParamSpec", "Sequential",
    "zeros_init", "ones_init", "constant_init", "normal_init",
    "uniform_init", "xavier_uniform_init", "kaiming_uniform_init",
    "Linear", "Embedding", "LayerNorm", "RMSNorm", "Dropout", "MLP",
]
