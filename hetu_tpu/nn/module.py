"""Functional Module system.

The reference builds an ``nn.Module`` tree whose ``forward`` emits graph ops
into a C++ static graph (``python/hetu/nn/modules/module.py`` →
``Graph::MakeOp``, SURVEY §3.2). On TPU the graph *is* the jaxpr: modules here
are plain Python objects that (a) declare parameters with shapes, initializers
and **logical sharding axes**, (b) build a nested-dict param pytree in
``init``, and (c) apply pure functions in ``__call__(params, ...)``. The
logical axes are what the strategy compiler (``hetu_tpu.parallel.sharding``)
maps onto mesh axes — the equivalent of the reference's per-tensor
``DistributedStates`` annotation (``hetu/graph/distributed_states.h:13``),
but declared once per parameter instead of propagated through a C++ pass.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Mapping, Optional, Sequence

import jax
import jax.numpy as jnp

from hetu_tpu.core.dtypes import current_policy

Initializer = Callable[[jax.Array, Sequence[int], Any], jax.Array]


@dataclasses.dataclass
class ParamSpec:
    """Declaration of one parameter.

    ``axes`` holds one *logical axis name* (or None) per dimension, e.g. a
    column-parallel kernel is ``("embed", "tp")``. The sharding compiler turns
    these into a ``PartitionSpec`` under the active strategy.
    """

    shape: tuple[int, ...]
    init: Initializer
    dtype: Any = None  # defaults to policy param_dtype at init time
    axes: tuple[Optional[str], ...] | None = None

    def instantiate(self, key: jax.Array, dtype=None) -> jax.Array:
        dtype = self.dtype or dtype or current_policy().param_dtype
        return self.init(key, self.shape, dtype)


class Module:
    """Base class. Subclasses declare params with :meth:`param` in
    ``__init__`` and implement ``__call__(self, params, *args, **kwargs)``.

    Child modules are discovered from instance attributes (including lists /
    tuples / dicts of modules), so the param pytree mirrors the attribute
    tree — the analogue of the reference's subgraph module tree
    (``hetu/graph/subgraph.h:36``).
    """

    #: modules whose __call__ returns (output, aux_loss) — e.g. MoE layers
    #: with a load-balance term — set this True so containers (Sequential,
    #: StackedBlocks, the pipeline executor) thread the aux accumulation.
    returns_aux: bool = False

    def __init__(self):
        self._param_specs: dict[str, ParamSpec] = {}

    # -- declaration -------------------------------------------------------
    def param(self, name: str, shape: Sequence[int], init: Initializer,
              dtype: Any = None, axes: Sequence[Optional[str]] | None = None):
        if not hasattr(self, "_param_specs"):
            self._param_specs = {}
        axes_t = tuple(axes) if axes is not None else None
        if axes_t is not None and len(axes_t) != len(tuple(shape)):
            raise ValueError(
                f"param {name}: axes {axes_t} rank != shape {tuple(shape)} rank")
        self._param_specs[name] = ParamSpec(tuple(shape), init, dtype, axes_t)

    # -- structure ---------------------------------------------------------
    def children(self) -> dict[str, "Module | list | dict"]:
        out = {}
        for k, v in vars(self).items():
            if k.startswith("_"):
                continue
            if isinstance(v, Module):
                out[k] = v
            elif isinstance(v, (list, tuple)) and v and all(
                    isinstance(e, Module) for e in v):
                out[k] = list(v)
            elif isinstance(v, dict) and v and all(
                    isinstance(e, Module) for e in v.values()):
                out[k] = v
        return out

    def named_modules(self, prefix: str = ""):
        """Yield ``(dotted_path, module)`` over the subtree, self first."""
        yield prefix, self
        for name, child in self.children().items():
            base = f"{prefix}.{name}" if prefix else name
            if isinstance(child, Module):
                yield from child.named_modules(base)
            elif isinstance(child, list):
                for i, m in enumerate(child):
                    yield from m.named_modules(f"{base}.{i}")
            else:
                for k, m in child.items():
                    yield from m.named_modules(f"{base}.{k}")

    # -- init --------------------------------------------------------------
    def init(self, key: jax.Array, dtype=None) -> dict:
        """Materialize the param pytree (nested dicts).

        The pytree structurally mirrors the module tree, including empty
        subtrees for param-less modules (Dropout, activations), so containers
        can always index ``params[child_name]``.
        """
        dtype = dtype or current_policy().param_dtype
        specs = self.abstract_specs()
        flat = _flatten_specs(specs)
        keys = jax.random.split(key, max(len(flat), 1))
        keymap = dict(zip(flat.keys(), keys))

        def build(tree: Mapping, prefix: str = "") -> dict:
            out = {}
            for k, v in tree.items():
                path = f"{prefix}.{k}" if prefix else str(k)
                if isinstance(v, ParamSpec):
                    out[k] = v.instantiate(keymap[path], dtype)
                else:
                    out[k] = build(v, path)
            return out

        return build(specs)

    def abstract_specs(self) -> dict:
        """Nested dict of ParamSpec mirroring the module tree structure.

        Param-less children contribute empty dicts (NOT pruned) so the param
        pytree always has the same structure as the module tree.
        """
        out: dict[str, Any] = dict(getattr(self, "_param_specs", {}))
        for name, child in self.children().items():
            if isinstance(child, Module):
                out[name] = child.abstract_specs()
            elif isinstance(child, list):
                out[name] = {str(i): m.abstract_specs()
                             for i, m in enumerate(child)}
            else:
                out[name] = {k: m.abstract_specs()
                             for k, m in child.items()}
        return out

    def abstract_params(self, dtype=None) -> dict:
        """ShapeDtypeStruct pytree — for sharding planning / eval_shape."""
        dtype = dtype or current_policy().param_dtype
        return jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype or dtype),
            self.abstract_specs(),
            is_leaf=lambda x: isinstance(x, ParamSpec))

    def param_axes(self) -> dict:
        """Pytree of logical-axes tuples matching the param structure."""
        return jax.tree.map(
            lambda s: s.axes if s.axes is not None else (None,) * len(s.shape),
            self.abstract_specs(),
            is_leaf=lambda x: isinstance(x, ParamSpec))

    # -- application -------------------------------------------------------
    def __call__(self, params, *args, **kwargs):
        raise NotImplementedError

    def compute_dtype(self):
        return current_policy().compute_dtype


def _flatten_specs(tree: Mapping, prefix: str = "") -> dict[str, ParamSpec]:
    out = {}
    for k, v in tree.items():
        path = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, ParamSpec):
            out[path] = v
        else:
            out.update(_flatten_specs(v, path))
    return out


def _unflatten(flat: Mapping[str, Any]) -> dict:
    root: dict = {}
    for path, leaf in flat.items():
        parts = path.split(".")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = leaf
    return root


class Sequential(Module):
    """Apply modules in order; params keyed by index."""

    def __init__(self, *mods: Module):
        super().__init__()
        self.layers = list(mods)

    def __call__(self, params, x, **kwargs):
        for i, m in enumerate(self.layers):
            x = m(params["layers"][str(i)], x, **kwargs)
        return x


# -- initializers ----------------------------------------------------------
def zeros_init():
    return lambda key, shape, dtype: jnp.zeros(shape, dtype)


def ones_init():
    return lambda key, shape, dtype: jnp.ones(shape, dtype)


def constant_init(v):
    return lambda key, shape, dtype: jnp.full(shape, v, dtype)


def normal_init(stddev=0.02):
    def f(key, shape, dtype):
        return (jax.random.normal(key, shape, jnp.float32) * stddev).astype(dtype)
    return f


def uniform_init(scale=0.01):
    def f(key, shape, dtype):
        return jax.random.uniform(
            key, shape, jnp.float32, -scale, scale).astype(dtype)
    return f


def xavier_uniform_init(in_axis=-2, out_axis=-1):
    def f(key, shape, dtype):
        fan_in = shape[in_axis] if len(shape) > 1 else shape[0]
        fan_out = shape[out_axis] if len(shape) > 1 else shape[0]
        limit = jnp.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(
            key, shape, jnp.float32, -limit, limit).astype(dtype)
    return f


def kaiming_uniform_init(in_axis=-2):
    def f(key, shape, dtype):
        fan_in = shape[in_axis] if len(shape) > 1 else shape[0]
        limit = jnp.sqrt(3.0 / fan_in)
        return jax.random.uniform(
            key, shape, jnp.float32, -limit, limit).astype(dtype)
    return f
