"""Basic layers: Linear, Embedding, norms, Dropout, MLP.

Covers the dense end of the reference's op library (``hetu/graph/ops/``:
Linear/MatMul, LayerNorm/RMSNorm via fused kernels ``impl/kernel/RMSNorm.cu``,
``FusedLayerNorm.cu``, embedding lookup) as idiomatic JAX modules. Norms call
into ``hetu_tpu.ops.normalization`` so a fused Pallas path can slot in
underneath without touching model code.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from hetu_tpu.nn.module import (
    Module, normal_init, zeros_init, ones_init, kaiming_uniform_init,
)
from hetu_tpu.ops import embedding as embed_ops
from hetu_tpu.ops import normalization as norm_ops


class Linear(Module):
    def __init__(self, in_features: int, out_features: int, bias: bool = True,
                 init=None, axes: Sequence[Optional[str]] = (None, None)):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.use_bias = bias
        self.param("weight", (in_features, out_features),
                   init or kaiming_uniform_init(), axes=axes)
        if bias:
            self.param("bias", (out_features,), zeros_init(), axes=(axes[1],))

    def __call__(self, params, x):
        dt = self.compute_dtype()
        y = jnp.matmul(x.astype(dt), params["weight"].astype(dt))
        if self.use_bias:
            y = y + params["bias"].astype(dt)
        return y


class Embedding(Module):
    """``bwd`` selects the gradient formulation for the table update:
    "auto" uses the scatter-vs-onehot winner measured on this chip by
    ``workloads/embed_probe.py`` (see ``ops/embedding.py``)."""

    def __init__(self, num_embeddings: int, features: int, init=None,
                 axes: Sequence[Optional[str]] = (None, None),
                 bwd: str = "auto"):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.features = features
        self.bwd = bwd
        self.param("weight", (num_embeddings, features),
                   init or normal_init(0.02), axes=axes)

    def __call__(self, params, ids):
        return embed_ops.embedding_lookup(
            params["weight"], ids, bwd=self.bwd).astype(
            self.compute_dtype())


class LayerNorm(Module):
    def __init__(self, features: int, eps: float = 1e-5,
                 use_bias: bool = True, use_scale: bool = True,
                 axes: Sequence[Optional[str]] = (None,)):
        super().__init__()
        self.features = features
        self.eps = eps
        self.use_bias = use_bias
        self.use_scale = use_scale
        if use_scale:
            self.param("scale", (features,), ones_init(), axes=axes)
        if use_bias:
            self.param("bias", (features,), zeros_init(), axes=axes)

    def __call__(self, params, x):
        scale = params["scale"] if self.use_scale else None
        bias = params["bias"] if self.use_bias else None
        return norm_ops.layer_norm(x, scale, bias, eps=self.eps).astype(
            self.compute_dtype())


class RMSNorm(Module):
    def __init__(self, features: int, eps: float = 1e-6,
                 axes: Sequence[Optional[str]] = (None,)):
        super().__init__()
        self.features = features
        self.eps = eps
        self.param("scale", (features,), ones_init(), axes=axes)

    def __call__(self, params, x):
        return norm_ops.rms_norm(x, params["scale"], eps=self.eps).astype(
            self.compute_dtype())


class Dropout(Module):
    def __init__(self, rate: float):
        super().__init__()
        self.rate = rate

    def __call__(self, params, x, *, rng: Optional[jax.Array] = None,
                 deterministic: bool = True):
        if deterministic or self.rate == 0.0:
            return x
        if rng is None:
            raise ValueError("Dropout needs an rng when not deterministic")
        keep = 1.0 - self.rate
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


class MLP(Module):
    """Plain 2-layer MLP (GELU) — GPT-2 style."""

    def __init__(self, features: int, hidden: int, bias: bool = True,
                 activation=jax.nn.gelu):
        super().__init__()
        self.fc_in = Linear(features, hidden, bias=bias,
                            init=normal_init(0.02), axes=("embed", "mlp"))
        self.fc_out = Linear(hidden, features, bias=bias,
                             init=normal_init(0.02), axes=("mlp", "embed"))
        self.activation = activation

    def __call__(self, params, x):
        h = self.activation(self.fc_in(params["fc_in"], x))
        return self.fc_out(params["fc_out"], h)


class Conv2D(Module):
    """2-D convolution (NHWC), lowered to ``lax.conv_general_dilated``
    (XLA tiles it onto the MXU). Reference kernels: ``impl/kernel``
    Conv2d CPU/CUDA pair driven by ``tests/test_cifar10.py``."""

    def __init__(self, in_channels: int, out_channels: int,
                 kernel_size: int = 3, stride: int = 1,
                 padding: str = "SAME", bias: bool = True, init=None):
        super().__init__()
        self.stride = (stride, stride)
        self.padding = padding
        init = init or normal_init(0.02)
        self.param("kernel",
                   (kernel_size, kernel_size, in_channels, out_channels),
                   init, axes=(None, None, None, "mlp"))
        if bias:
            self.param("bias", (out_channels,), zeros_init(),
                       axes=("mlp",))

    def __call__(self, params, x):
        dt = self.compute_dtype()
        y = jax.lax.conv_general_dilated(
            x.astype(dt), params["kernel"].astype(dt),
            window_strides=self.stride, padding=self.padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if "bias" in params:
            y = y + params["bias"].astype(dt)
        return y


def max_pool2d(x, window: int = 2, stride: int = 2):
    return jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, window, window, 1),
        (1, stride, stride, 1), "VALID")


def avg_pool2d(x, window: int = 2, stride: int = 2):
    s = jax.lax.reduce_window(
        x, 0.0, jax.lax.add, (1, window, window, 1),
        (1, stride, stride, 1), "VALID")
    return s / (window * window)
