"""Serving front end over the coordinator line protocol.

The cluster already speaks one wire format — the newline-delimited
command protocol of ``rpc/py_server.py`` / ``csrc/coordinator.cpp`` —
so the serving plane rides it instead of inventing a second server:
three commands (SUBMIT / RESULT / GENERATE) carry URL-quoted compact
JSON payloads, which keeps every payload a single space-free token in
the line protocol and survives any tokenizer's ids.

``ServingServer`` is the convenience bundle: engine background loop +
coordinator with the engine attached. ``CoordinatorClient`` grows the
matching ``serving_*`` calls in ``rpc/client.py``.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.parse
from typing import Optional

from hetu_tpu.serving.engine import ServingEngine
from hetu_tpu.serving.scheduler import Request, SamplingParams


def encode_payload(obj: dict) -> str:
    """dict → one URL-quoted, space-free line-protocol token."""
    return urllib.parse.quote(
        json.dumps(obj, separators=(",", ":")), safe="")


def decode_payload(tok: str) -> dict:
    return json.loads(urllib.parse.unquote(tok))


def sampling_from_payload(p: dict) -> SamplingParams:
    return SamplingParams(
        temperature=float(p.get("temperature", 0.0)),
        top_k=int(p.get("top_k", 0)),
        top_p=float(p.get("top_p", 0.0)),
        eos_id=None if p.get("eos_id") is None else int(p["eos_id"]),
        max_tokens=int(p.get("max_tokens", 16)),
        priority=int(p.get("priority", 1)),
        tenant=p.get("tenant"),
        adapter=p.get("adapter"))


def submit_payload(engine: ServingEngine, tok: str) -> Request:
    """SUBMIT handler: decode one request payload and queue it."""
    p = decode_payload(tok)
    return engine.submit(p["prompt"], sampling_from_payload(p))


class ServingServer:
    """Engine loop + coordinator in one lifecycle.

    The coordinator keeps its full role (RANK/KV/BARRIER for the
    training fleet); the serving commands only light up when an engine
    is attached — one process can coordinate training AND serve.
    """

    def __init__(self, engine: ServingEngine, port: int,
                 bind: str = "127.0.0.1", token: str = ""):
        from hetu_tpu.rpc.py_server import PyCoordinatorServer
        self.engine = engine
        self.coordinator = PyCoordinatorServer(port, bind=bind,
                                               token=token,
                                               serving=engine)

    def start(self) -> None:
        self.engine.start()
        self.coordinator.start()

    def wait_ready(self, timeout: float = 10.0) -> None:
        self.coordinator.wait_ready(timeout)

    def stop(self) -> None:
        self.coordinator.stop()
        self.engine.stop()


#: SUBMITted-but-never-polled requests must not leak in a long-running
#: server: beyond this many live entries, FINISHED requests are evicted
#: oldest-first (in-flight ones are always kept — their slots are real)
_REQUEST_MAP_CAP = 4096


def _prune_request_map(m: dict) -> None:
    if len(m) <= _REQUEST_MAP_CAP:
        return
    for rid in [rid for rid, r in m.items()
                if r.done.is_set()][:len(m) - _REQUEST_MAP_CAP]:
        m.pop(rid, None)


#: serving verbs the coordinator forwards here. SUBMIT/RESULT/GENERATE
#: accept EITHER a ServingEngine or a fleet Router (same duck-typed
#: surface: submit()/result()/_requests_by_id); FLEET/DRAIN/RESUME are
#: router-only (fleet lifecycle over the wire); ESTATUS/CANCELQ/EVICT/
#: PREFILL/SWAPWEIGHTS/STOPENGINE are the engine-process verbs the
#: fleet's RemoteEngineProxy drives (docs/SERVING.md "Disaggregated
#: fleet"); DUMPOBS ships this process's observability bundle (chrome
#: trace + flight ring) to ``tools/fleet_trace.py`` and FLEETMETRICS
#: serves the router's federated Prometheus page (ISSUE 16).
#: ``rpc/py_server.py`` mirrors this tuple (it must stay importable
#: without jax) — a quick-tier test keeps them in sync.
#: KVEXPORT/KVIMPORT move whole-block prefix KV between replicas for
#: the fleet prefix directory; KVREPL/KVFETCH/KVBUDDY are the
#: decode-KV replication lane (ship to buddy / assemble for recovery /
#: point the stream) — all ISSUE 18, docs/SERVING.md "Fleet-global KV".
SERVING_COMMANDS = ("SUBMIT", "RESULT", "GENERATE",
                    "FLEET", "DRAIN", "RESUME",
                    "ESTATUS", "CANCELQ", "EVICT", "PREFILL",
                    "SWAPWEIGHTS", "STOPENGINE",
                    "DUMPOBS", "FLEETMETRICS",
                    "KVEXPORT", "KVIMPORT", "KVREPL", "KVFETCH",
                    "KVBUDDY")


_idem_init_lock = threading.Lock()

#: dedup-window TTL: a finished entry older than this can no longer be
#: a retry-after-timeout join (clients give up in seconds) — evicting
#: it bounds a long soak's memory instead of growing forever (ISSUE 19)
_IDEM_TTL_S = 900.0


class IdemMap:
    """Bounded idempotency-key → request map with TTL + LRU eviction.

    SUBMIT/GENERATE/STREAM payloads carry an ``idem`` key; a duplicate
    delivery — the client retrying after a response timeout, or two
    front ends racing one logical request — joins the ORIGINAL request
    instead of queueing a second generation. PR 15's unbounded dict is
    replaced by this structure: every hit refreshes recency, FINISHED
    entries expire after ``ttl_s`` (the dedup window a retry could
    still arrive in), and past ``max_entries`` the least-recently-used
    entry goes — done entries first, in-flight ones only when nothing
    else is left. Evictions are counted
    (``serving_idem_evictions_total``). ``lock`` makes check-and-insert
    atomic across the coordinator's handler threads; callers hold it
    around get/put."""

    def __init__(self, max_entries: int = _REQUEST_MAP_CAP,
                 ttl_s: float = _IDEM_TTL_S):
        self.lock = threading.Lock()
        self.max_entries = int(max_entries)
        self.ttl_s = float(ttl_s)
        self._m: "dict[str, list]" = {}    # key -> [req, deadline]

    def __len__(self) -> int:
        return len(self._m)

    @staticmethod
    def _count_evict(reason: str, n: int = 1) -> None:
        if not n:
            return
        from hetu_tpu import telemetry
        telemetry.get_registry().counter(
            "serving_idem_evictions_total",
            "idempotency-map entries evicted (ttl: dedup window "
            "expired; cap: LRU past max_entries) — the long-soak "
            "growth bound, ISSUE 19").inc(n, reason=reason)

    def get(self, key: str, now: Optional[float] = None):
        ent = self._m.get(key)
        if ent is None:
            return None
        now = time.monotonic() if now is None else now
        ent[1] = now + self.ttl_s
        # refresh recency: re-insert at the back of the dict's
        # insertion order (the LRU order the cap eviction walks)
        self._m[key] = self._m.pop(key)
        return ent[0]

    def put(self, key: str, req, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        self._m.pop(key, None)
        self._m[key] = [req, now + self.ttl_s]
        self.prune(now)

    def prune(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        stale = [k for k, (r, dl) in self._m.items()
                 if dl <= now and r.done.is_set()]
        for k in stale:
            del self._m[k]
        try:
            self._count_evict("ttl", len(stale))
        except Exception:                             # noqa: BLE001
            pass
        dropped = 0
        while len(self._m) > self.max_entries:
            victim = next((k for k, (r, _dl) in self._m.items()
                           if r.done.is_set()), None)
            if victim is None:
                victim = next(iter(self._m))
            del self._m[victim]
            dropped += 1
        try:
            self._count_evict("cap", dropped)
        except Exception:                             # noqa: BLE001
            pass


def _idem_map(engine) -> IdemMap:
    """Per-server :class:`IdemMap` (attached to the engine/router
    object the coordinator serves)."""
    m = getattr(engine, "_idem_requests", None)
    if m is None:
        with _idem_init_lock:
            m = getattr(engine, "_idem_requests", None)
            if m is None:
                m = IdemMap()
                engine._idem_requests = m
    return m


def _count_dedup(verb: str) -> None:
    from hetu_tpu import telemetry
    telemetry.get_registry().counter(
        "serving_idem_dedup_total",
        "duplicate SUBMIT/GENERATE deliveries suppressed by "
        "idempotency key (client retry-after-timeout joined the "
        "original request)").inc(verb=verb)


def _submit_from_payload(engine, p: dict):
    """Decode one SUBMIT/GENERATE/PREFILL payload and queue it —
    wire-format KV spills (``resume``) ride along for the fleet's
    cross-process resumable requeue."""
    kw = {}
    if p.get("resume") is not None:
        from hetu_tpu.serving.fleet import spill_from_wire
        kw["resume"] = spill_from_wire(p["resume"])
    if p.get("traceparent"):
        kw["traceparent"] = p["traceparent"]
    return engine.submit(p["prompt"], sampling_from_payload(p), **kw)


def _submit_with_idem(engine, p: dict, verb: str):
    """The one idempotency-keyed submit path SUBMIT / GENERATE / the
    stream frames all share: an ``idem``-keyed duplicate delivery joins
    the original request, everything else queues fresh."""
    key = p.get("idem")
    if not key:
        return _submit_from_payload(engine, p)
    m = _idem_map(engine)
    with m.lock:                        # atomic check-and-queue
        req = m.get(key)
        if req is not None:
            _count_dedup(verb)
            return req
        req = _submit_from_payload(engine, p)
        if req.status != "rejected":
            m.put(key, req)
    return req


def handle_stream_submit(serving, payload: str):
    """The ``stream`` frame's submit half (SUBMIT semantics — same
    payload format, idempotency key and traceparent included).
    Returns ``(request, None)`` or ``(None, "ERR ...")``; the caller
    (``py_server._stream_submit``) acks and subscribes."""
    try:
        p = decode_payload(payload)
        req = _submit_with_idem(serving, p, "STREAM")
    except Exception as e:                            # noqa: BLE001
        return None, f"ERR {type(e).__name__}: {e}"
    if req.status == "rejected":
        return None, f"ERR rejected: {req.error}"
    serving._requests_by_id[req.id] = req
    _prune_request_map(serving._requests_by_id)
    return req, None


def handle_serving_command(engine: Optional[ServingEngine], cmd: str,
                           args: list) -> Optional[str]:
    """Dispatch one serving line-protocol command; None = not ours.

    Kept here (not in ``py_server``) so the coordinator stays
    importable without jax — it only calls in when an engine was
    attached and a serving verb arrives.
    """
    if cmd not in SERVING_COMMANDS:
        return None
    if engine is None:
        return "ERR serving disabled"
    if cmd in ("FLEET", "DRAIN", "RESUME", "FLEETMETRICS"):
        if not hasattr(engine, "fleet_status"):
            return "ERR not a fleet (attach a serving.router.Router)"
        try:
            if cmd == "FLEET":
                return f"VAL {encode_payload(engine.fleet_status())}"
            if cmd == "FLEETMETRICS":
                # federated Prometheus text (replica-labeled + _fleet
                # aggregates) — URL-quoted, like METRICS/HEALTHZ
                return "VAL " + urllib.parse.quote(
                    engine.fleet_metrics_text(), safe="")
            if cmd == "DRAIN":
                n = engine.drain(args[0])
                return f"VAL {encode_payload({'requeued': n})}"
            engine.resume(args[0])
            return "OK"
        except Exception as e:                    # noqa: BLE001
            return f"ERR {type(e).__name__}: {e}"
    try:
        if cmd == "SUBMIT":
            p = decode_payload(args[0])
            req = _submit_with_idem(engine, p, "SUBMIT")
            if req.status == "rejected":
                return f"ERR rejected: {req.error}"
            engine._requests_by_id[req.id] = req
            _prune_request_map(engine._requests_by_id)
            # id + trace_id: the trace id keys the request's Perfetto
            # track and the RESULT timing breakdown (docs/SERVING.md);
            # the trailing R acknowledges an accepted KV resume
            tail = " R" if p.get("resume") is not None \
                and req.spill is not None else ""
            return f"ID {req.id} {req.trace_id}{tail}"
        if cmd == "RESULT":
            req = engine._requests_by_id.get(int(args[0]))
            if req is None:
                return "ERR unknown request id"
            timeout_ms = int(args[1]) if len(args) > 1 else 0
            r = engine.result(req, timeout=timeout_ms / 1e3)
            if r is None:
                return "PEND"
            engine._requests_by_id.pop(req.id, None)
            return f"VAL {encode_payload(r)}"
        if cmd == "GENERATE":
            # blocking submit + wait (the engine loop must be running —
            # ServingServer.start does that)
            p = decode_payload(args[0])
            req = _submit_with_idem(engine, p, "GENERATE")
            r = req.result() if req.status == "rejected" \
                else engine.result(req, timeout=None)
            return f"VAL {encode_payload(r)}"
        return _handle_engine_command(engine, cmd, args)
    except Exception as e:                        # noqa: BLE001
        return f"ERR {type(e).__name__}: {e}"


def _handle_engine_command(engine, cmd: str, args: list) -> str:
    """The engine-process verbs behind the fleet's RemoteEngineProxy
    (ESTATUS/CANCELQ/EVICT/PREFILL/SWAPWEIGHTS/STOPENGINE). Duck-typed
    defensively: a Router front door answers ESTATUS with what it has
    and refuses the engine-only verbs loudly."""
    from hetu_tpu.serving.fleet import spill_to_wire
    if cmd == "ESTATUS":
        doc = {"load": getattr(engine, "load", 0),
               "weight_version": getattr(engine, "weight_version", 0),
               "has_work": engine.has_work()
               if hasattr(engine, "has_work") else False,
               # wall-clock stamp mid-RTT: the caller's NTP-style
               # offset handshake (fleet clock alignment, ISSUE 16)
               "ts_unix": round(time.time(), 6)}
        sched = getattr(engine, "scheduler", None)
        doc["depth"] = getattr(sched, "depth", 0) if sched else 0
        doc["occupancy"] = round(getattr(sched, "occupancy", 0.0), 4) \
            if sched else 0.0
        # arena granularity: the router's prefix directory hashes
        # whole-block prefixes at this replica's block size (ISSUE 18)
        doc["block_size"] = int(getattr(
            getattr(engine, "pool", None), "block_size", 0) or 0)
        return f"VAL {encode_payload(doc)}"
    if cmd == "DUMPOBS":
        # this process's observability bundle — local chrome trace +
        # flight ring + identity; fleet_trace.py merges bundles from
        # every process into one clock-aligned Perfetto trace
        from hetu_tpu import telemetry
        rec = telemetry.get_flight_recorder()
        tracer = telemetry.get_tracer()
        doc = {"pid": os.getpid(), "ts_unix": round(time.time(), 6),
               "rank": rec.rank, "replica": rec.replica,
               "role": rec.role,
               "epoch_unix": round(tracer.epoch_unix, 6),
               "chrome": tracer.to_chrome(),
               "flight": rec.events()}
        return f"VAL {encode_payload(doc)}"
    if cmd == "STOPENGINE":
        engine.stop()
        return "OK"
    if cmd == "CANCELQ":
        if not hasattr(engine, "cancel_queued"):
            return "ERR not an engine"
        p = decode_payload(args[0])
        moved = engine.cancel_queued({int(i) for i in p["ids"]})
        out = []
        for r in moved:
            engine._requests_by_id.pop(r.id, None)
            r.status = "cancelled"
            if hasattr(engine, "_stream_interrupt"):
                engine._stream_interrupt(r)   # subscribers fall back
            out.append({"id": r.id,
                        "spill": spill_to_wire(r.spill)
                        if r.spill is not None else None})
        return f"VAL {encode_payload({'cancelled': out})}"
    if cmd == "EVICT":
        p = decode_payload(args[0])
        req = engine._requests_by_id.get(int(p["id"]))
        if req is None:
            return "ERR unknown request id"
        if p.get("traceparent") and \
                getattr(req, "traceparent", None) is None:
            # a request submitted before tracing reached it still gets
            # its spill stamped with the router's context
            req.traceparent = p["traceparent"]
        entry = engine.evict_request(
            req, lock_timeout_s=p.get("lock_timeout_s"))
        if req.status == "evicted":
            engine._requests_by_id.pop(req.id, None)
        return f"VAL {encode_payload({'status': req.status, 'spill': spill_to_wire(entry) if entry is not None else None})}"
    if cmd == "PREFILL":
        if not hasattr(engine, "prefill_only"):
            return "ERR not an engine"
        p = decode_payload(args[0])
        req, entry = engine.prefill_only(p["prompt"],
                                         sampling_from_payload(p),
                                         traceparent=p.get("traceparent"))
        if req.status == "rejected":
            return f"ERR rejected: {req.error}"
        if entry is None:
            return f"VAL {encode_payload({'done': True, 'id': req.id, 'trace_id': req.trace_id, 'result': req.result()})}"
        doc = {"done": False, "id": req.id, "trace_id": req.trace_id,
               "tokens": [int(t) for t in req.tokens],
               "weight_version": req.weight_version,
               "spill": spill_to_wire(entry)}
        return f"VAL {encode_payload(doc)}"
    if cmd == "KVEXPORT":
        if not hasattr(engine, "export_prefix"):
            return "ERR not an engine"
        p = decode_payload(args[0])
        entry = engine.export_prefix(p["tokens"])
        return f"VAL {encode_payload({'spill': spill_to_wire(entry) if entry is not None else None})}"
    if cmd == "KVIMPORT":
        if not hasattr(engine, "import_prefix"):
            return "ERR not an engine"
        from hetu_tpu.serving.fleet import spill_from_wire
        p = decode_payload(args[0])
        ok = engine.import_prefix(spill_from_wire(p["spill"]))
        return f"VAL {encode_payload({'ok': bool(ok)})}"
    if cmd == "KVREPL":
        store = getattr(engine, "kv_replica_store", None)
        if store is None:
            return "ERR no replica store"
        store.put(decode_payload(args[0]))
        return "OK"
    if cmd == "KVFETCH":
        store = getattr(engine, "kv_replica_store", None)
        if store is None:
            return "ERR no replica store"
        p = decode_payload(args[0])
        entry = store.fetch(p["trace_id"])
        return f"VAL {encode_payload({'spill': spill_to_wire(entry) if entry is not None else None})}"
    if cmd == "KVBUDDY":
        if not hasattr(engine, "configure_replication"):
            return "ERR not an engine"
        p = decode_payload(args[0])
        host = p.get("host")
        if not host:
            engine.configure_replication(None)
            return "OK"
        from hetu_tpu.rpc.client import CoordinatorClient
        cli_box = {}

        def sink(doc, _p=p, _box=cli_box):
            # lazy, sticky connection owned by the replication thread;
            # dropped on any failure so the next cadence reconnects
            cli = _box.get("cli")
            if cli is None:
                cli = CoordinatorClient(int(_p["port"]), host=_p["host"],
                                        token=_p.get("token") or None,
                                        timeout=5.0, retries=1)
                _box["cli"] = cli
            try:
                cli.serving_kv_put(doc)
            except Exception:
                _box.pop("cli", None)
                try:
                    cli.close()
                except OSError:
                    pass
                raise
        engine.configure_replication(
            sink, origin=p.get("origin", ""),
            cadence_s=float(p.get("cadence_s", 0.02)))
        return "OK"
    if cmd == "SWAPWEIGHTS":
        p = decode_payload(args[0])
        from hetu_tpu import telemetry
        from hetu_tpu.utils.dist_checkpoint import (
            load_params_distributed,
        )
        # activate the push's trace for the swap's duration: flight
        # events recorded meanwhile (incl. a chaos kill) can stamp it
        with telemetry.use_trace(p.get("traceparent")):
            params = load_params_distributed(p["path"], engine.model,
                                             plan=engine._plan)
            info = engine.swap_params(params, version=p.get("version"))
        return f"VAL {encode_payload(info)}"
    return "ERR unknown command"
