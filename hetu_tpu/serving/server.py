"""Serving front end over the coordinator line protocol.

The cluster already speaks one wire format — the newline-delimited
command protocol of ``rpc/py_server.py`` / ``csrc/coordinator.cpp`` —
so the serving plane rides it instead of inventing a second server:
three commands (SUBMIT / RESULT / GENERATE) carry URL-quoted compact
JSON payloads, which keeps every payload a single space-free token in
the line protocol and survives any tokenizer's ids.

``ServingServer`` is the convenience bundle: engine background loop +
coordinator with the engine attached. ``CoordinatorClient`` grows the
matching ``serving_*`` calls in ``rpc/client.py``.
"""

from __future__ import annotations

import json
import urllib.parse
from typing import Optional

from hetu_tpu.serving.engine import ServingEngine
from hetu_tpu.serving.scheduler import Request, SamplingParams


def encode_payload(obj: dict) -> str:
    """dict → one URL-quoted, space-free line-protocol token."""
    return urllib.parse.quote(
        json.dumps(obj, separators=(",", ":")), safe="")


def decode_payload(tok: str) -> dict:
    return json.loads(urllib.parse.unquote(tok))


def sampling_from_payload(p: dict) -> SamplingParams:
    return SamplingParams(
        temperature=float(p.get("temperature", 0.0)),
        top_k=int(p.get("top_k", 0)),
        top_p=float(p.get("top_p", 0.0)),
        eos_id=None if p.get("eos_id") is None else int(p["eos_id"]),
        max_tokens=int(p.get("max_tokens", 16)),
        priority=int(p.get("priority", 1)))


def submit_payload(engine: ServingEngine, tok: str) -> Request:
    """SUBMIT handler: decode one request payload and queue it."""
    p = decode_payload(tok)
    return engine.submit(p["prompt"], sampling_from_payload(p))


class ServingServer:
    """Engine loop + coordinator in one lifecycle.

    The coordinator keeps its full role (RANK/KV/BARRIER for the
    training fleet); the serving commands only light up when an engine
    is attached — one process can coordinate training AND serve.
    """

    def __init__(self, engine: ServingEngine, port: int,
                 bind: str = "127.0.0.1", token: str = ""):
        from hetu_tpu.rpc.py_server import PyCoordinatorServer
        self.engine = engine
        self.coordinator = PyCoordinatorServer(port, bind=bind,
                                               token=token,
                                               serving=engine)

    def start(self) -> None:
        self.engine.start()
        self.coordinator.start()

    def wait_ready(self, timeout: float = 10.0) -> None:
        self.coordinator.wait_ready(timeout)

    def stop(self) -> None:
        self.coordinator.stop()
        self.engine.stop()


#: SUBMITted-but-never-polled requests must not leak in a long-running
#: server: beyond this many live entries, FINISHED requests are evicted
#: oldest-first (in-flight ones are always kept — their slots are real)
_REQUEST_MAP_CAP = 4096


def _prune_request_map(m: dict) -> None:
    if len(m) <= _REQUEST_MAP_CAP:
        return
    for rid in [rid for rid, r in m.items()
                if r.done.is_set()][:len(m) - _REQUEST_MAP_CAP]:
        m.pop(rid, None)


#: serving verbs the coordinator forwards here. SUBMIT/RESULT/GENERATE
#: accept EITHER a ServingEngine or a fleet Router (same duck-typed
#: surface: submit()/result()/_requests_by_id); FLEET/DRAIN/RESUME are
#: router-only (fleet lifecycle over the wire).
SERVING_COMMANDS = ("SUBMIT", "RESULT", "GENERATE",
                    "FLEET", "DRAIN", "RESUME")


def handle_serving_command(engine: Optional[ServingEngine], cmd: str,
                           args: list) -> Optional[str]:
    """Dispatch one serving line-protocol command; None = not ours.

    Kept here (not in ``py_server``) so the coordinator stays
    importable without jax — it only calls in when an engine was
    attached and a serving verb arrives.
    """
    if cmd not in SERVING_COMMANDS:
        return None
    if engine is None:
        return "ERR serving disabled"
    if cmd in ("FLEET", "DRAIN", "RESUME"):
        if not hasattr(engine, "fleet_status"):
            return "ERR not a fleet (attach a serving.router.Router)"
        try:
            if cmd == "FLEET":
                return f"VAL {encode_payload(engine.fleet_status())}"
            if cmd == "DRAIN":
                n = engine.drain(args[0])
                return f"VAL {encode_payload({'requeued': n})}"
            engine.resume(args[0])
            return "OK"
        except Exception as e:                    # noqa: BLE001
            return f"ERR {type(e).__name__}: {e}"
    try:
        if cmd == "SUBMIT":
            req = submit_payload(engine, args[0])
            if req.status == "rejected":
                return f"ERR rejected: {req.error}"
            engine._requests_by_id[req.id] = req
            _prune_request_map(engine._requests_by_id)
            # id + trace_id: the trace id keys the request's Perfetto
            # track and the RESULT timing breakdown (docs/SERVING.md)
            return f"ID {req.id} {req.trace_id}"
        if cmd == "RESULT":
            req = engine._requests_by_id.get(int(args[0]))
            if req is None:
                return "ERR unknown request id"
            timeout_ms = int(args[1]) if len(args) > 1 else 0
            r = engine.result(req, timeout=timeout_ms / 1e3)
            if r is None:
                return "PEND"
            engine._requests_by_id.pop(req.id, None)
            return f"VAL {encode_payload(r)}"
        # GENERATE: blocking submit + wait (the engine loop must be
        # running — ServingServer.start does that)
        req = submit_payload(engine, args[0])
        r = req.result() if req.status == "rejected" \
            else engine.result(req, timeout=None)
        return f"VAL {encode_payload(r)}"
    except Exception as e:                        # noqa: BLE001
        return f"ERR {type(e).__name__}: {e}"
