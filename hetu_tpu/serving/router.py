"""Serving fleet plane: a router over N ServingEngine replicas, plus
the live train→serve weight-push path.

One :class:`~hetu_tpu.serving.engine.ServingEngine` replica is a
correct serving plane — it is not a FLEET. The ROADMAP's north star
(heavy traffic from millions of users) needs N replicas behind one
front door, and the reference's signature capability (SOSP'24 HotSPa
hot parameter switching, SURVEY §3.4) needs a path for a live Trainer
to push new weights INTO that fleet without dropping a request. This
module is both, composed from machinery earlier PRs built:

- :class:`Router` — replica registration / heartbeat / drain / death
  lifecycle, **load-aware dispatch** (least-loaded by the same
  queue-depth + occupancy signal the ``serving_*`` gauges sample, TTFT
  EWMA as the tiebreak) with **prefix-affinity sticky routing**
  (rendezvous hashing on the prompt's first block of tokens, so
  requests sharing a system prompt land where the radix prefix cache
  already holds it — taken only when the sticky replica is within
  ``affinity_slack`` of the least-loaded, so a hot prefix cannot
  starve the fleet), **resumable retry-and-requeue** when a replica
  dies mid-request: undelivered requests are re-dispatched to peers,
  and a request that was mid-DECODE carries its KV spill
  (:meth:`ServingEngine.evict_request` →
  :class:`~hetu_tpu.serving.kv_pool.SpillEntry`) so the peer resumes
  it with zero prefill-lane work instead of regenerating from scratch
  (greedy decoding makes the fresh-replay fallback token-identical
  when the spill cannot travel — e.g. a weight-version mismatch), and
  fleet-wide HEALTHZ/METRICS aggregation (:meth:`Router.fleet_status`);
- :class:`WeightPublisher` — the Trainer-side push: per-replica
  **drain → swap → resume**, rolling across the fleet so capacity
  never reaches zero. The swap leg is
  ``ServingEngine.swap_params``: weight generation bumped on the
  engine + KV pool, version-stale prefix-cache entries flushed
  (``prefix_cache.set_version``), so no token is ever decoded against
  KV prefilled under superseded weights. Parameters move onto each
  replica's topology through the HotSPa reshard core
  (:func:`~hetu_tpu.parallel.switch.reshard_tree` — the same
  ParamSlice-intersection machinery that does training-side hot
  switches), force-copied so a trainer's later donated step can never
  delete a replica's buffers.

Everything here is host-side control plane: no jax in the dispatch
path, the replicas' compiled steps never see the router. The line
protocol grows matching verbs (``FLEET`` / ``DRAIN`` / ``RESUME`` in
``rpc/py_server.py``; SUBMIT/RESULT/GENERATE accept a Router wherever
they accepted an engine), and ``workloads/rollout_loop.py`` drives the
closed loop: router-fanned rollouts → SFT trainer → publish → serve
on, uninterrupted. ``docs/SERVING.md`` ("Fleet") has the state
machines.
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
import time
import uuid
from collections import deque
from typing import Optional, Sequence, Union

from hetu_tpu import telemetry
from hetu_tpu.serving.engine import ServingEngine
from hetu_tpu.serving.scheduler import Request, SamplingParams
from hetu_tpu.telemetry.flight import flight_record
from hetu_tpu.telemetry.spans import REQ_TRACK_BASE


@dataclasses.dataclass
class RouterRequest:
    """One request's fleet-level lifecycle: dispatched to a replica,
    possibly re-dispatched after a replica death, finished exactly
    once. Mirrors the engine's :class:`Request` surface (``id`` /
    ``status`` / ``done`` / ``result()``) so the line-protocol front
    end serves a Router and an engine through the same verbs."""

    id: int
    prompt: list
    sampling: SamplingParams
    submit_s: float
    status: str = "queued"       # queued|dispatched|done|rejected|failed
    replica: Optional[str] = None        # current / last assignment
    attempts: int = 0                    # dispatches (1 = never requeued)
    tokens: list = dataclasses.field(default_factory=list)
    error: Optional[str] = None
    weight_version: Optional[int] = None
    finish_s: Optional[float] = None
    spill: Optional[object] = dataclasses.field(
        default=None, repr=False, compare=False)  # SpillEntry salvaged
    #                                    from a dead/draining replica —
    #                                    rides the next dispatch so the
    #                                    peer resumes instead of
    #                                    re-prefilling
    resumed_dispatches: int = 0          # dispatches that carried KV
    trace_id: str = dataclasses.field(
        default_factory=lambda: uuid.uuid4().hex[:12])
    traceparent: Optional[str] = None    # inbound wire context — when
    #                                      set, trace_id matches it and
    #                                      every dispatch propagates it
    #                                      downstream (ISSUE 16)
    inner: Optional[Request] = dataclasses.field(
        default=None, repr=False, compare=False)
    done: threading.Event = dataclasses.field(
        default_factory=threading.Event, repr=False, compare=False)

    def result(self) -> dict:
        timing: dict = {"trace_id": self.trace_id,
                        "attempts": self.attempts}
        if self.inner is not None:
            timing.update(self.inner.timing())
            timing["trace_id"] = self.trace_id   # router id wins
        if self.finish_s is not None:
            timing["router_total_ms"] = round(
                (self.finish_s - self.submit_s) * 1e3, 3)
        return {"id": self.id, "status": self.status,
                "tokens": list(self.tokens), "error": self.error,
                "replica": self.replica,
                "weight_version": self.weight_version,
                "timing": timing}


class _StreamBridge:
    """Joins one outward token subscription on a :class:`RouterRequest`
    to whatever INNER request currently serves it (ISSUE 19).

    The bridge owns a global token cursor (``sub.sent``): every attach
    — first dispatch, or re-dispatch after a replica death — replays
    the inner stream from that cursor and clips any overlap, so a
    failover subscriber sees each token exactly once, in order. Inner
    ``done``/``end`` markers are NOT forwarded: the terminal frame is
    the router's to emit (``finalize``), carrying the router-level
    result with ``router_total_ms`` and the attempt count."""

    def __init__(self, rreq: "RouterRequest", sub):
        self.rreq = rreq
        self.sub = sub
        self._detach_cb = None
        self._emlock = threading.Lock()

    @property
    def dead(self) -> bool:
        return self.sub.closed or self.sub.dropped

    def attach(self, h: "ReplicaHandle") -> None:
        """Feed the bridge from ``rreq.inner`` on ``h`` — remote
        replicas tap the proxy's event fan-out, local engines get a
        real subscription drained by a forwarder thread. Both degrade
        silently (finalize still delivers everything)."""
        self.detach()
        inner = self.rreq.inner
        if inner is None or self.dead:
            return
        try:
            eng = h.engine
            if getattr(h, "remote", False):
                if hasattr(eng, "stream_tap"):
                    # register the tap FIRST, then replay the backlog:
                    # a racing live event is clipped, never lost
                    self._detach_cb = eng.stream_tap(inner,
                                                     self._on_inner)
                    self._on_inner({"off": 0,
                                    "toks": list(inner.tokens),
                                    "done": False})
            elif hasattr(eng, "stream_subscribe"):
                isub = eng.stream_subscribe(inner, offset=self.sub.sent,
                                            max_queue=1024)
                stop = threading.Event()
                threading.Thread(
                    target=self._forward, args=(isub, stop),
                    daemon=True,
                    name=f"stream-bridge-{self.rreq.id}").start()

                def _cb(isub=isub, stop=stop):
                    stop.set()
                    isub.close()
                self._detach_cb = _cb
        except Exception:                             # noqa: BLE001
            self._detach_cb = None      # finalize-only degradation

    def detach(self) -> None:
        cb, self._detach_cb = self._detach_cb, None
        if cb is not None:
            try:
                cb()
            except Exception:                         # noqa: BLE001
                pass

    def _forward(self, isub, stop: threading.Event) -> None:
        while not stop.is_set() and not self.dead:
            ev = isub.get(timeout=0.2)
            if ev is None:
                if isub.closed or isub.dropped:
                    return
                continue
            self._on_inner(ev)
            if ev.get("done") or ev.get("end"):
                return

    def _on_inner(self, ev: dict) -> None:
        """Forward one inner token delta outward, clipped at the global
        cursor. Inner offsets ARE global offsets: a KV-resumed inner
        request preloads the tokens generated before the failover."""
        if ev.get("k") not in (None, "ev"):
            return                       # drop/lost frames: reattach or
        #                                  finalize will recover
        toks = ev.get("toks") or []
        off = int(ev.get("off", 0))
        with self._emlock:
            skip = self.sub.sent - off
            if skip < 0 or skip >= len(toks):
                return     # gap (wait for finalize) or full overlap
            out_toks = [int(t) for t in toks[skip:]]
            out = {"req": self.rreq.id, "trace": self.rreq.trace_id,
                   "off": self.sub.sent, "toks": out_toks,
                   "first": self.sub.sent == 0,
                   "done": False,
                   "ts": ev.get("ts", round(time.monotonic(), 6))}
            if self.sub.emit(out):
                self.sub.sent += len(out_toks)

    def finalize(self) -> None:
        """Terminal frame: the remaining delta + the ROUTER-level
        result (trailing timing payload)."""
        self.detach()
        rreq = self.rreq
        with self._emlock:
            toks = [int(t) for t in rreq.tokens[self.sub.sent:]]
            ev = {"req": rreq.id, "trace": rreq.trace_id,
                  "off": self.sub.sent, "toks": toks,
                  "first": self.sub.sent == 0 and bool(toks),
                  "done": True, "result": rreq.result(),
                  "ts": round(time.monotonic(), 6)}
            self.sub.sent = len(rreq.tokens)
            self.sub.emit(ev)
            self.sub.close()


class FleetPrefixDirectory:
    """Router-owned map from whole-block prompt-prefix hashes to the
    replica whose radix prefix cache holds that prefix (ISSUE 18).

    Entries are ``(replica, weight_version)``-tagged: the version rides
    every publish, and :meth:`flush_stale` atomically invalidates a
    replica's entries on a weight push or its death — the directory can
    then never route a pull at KV prefilled under superseded weights
    (the engine's ``SpillEntry.compatible_with`` gate is the second,
    engine-side line of defense). Purely host-side bookkeeping: the
    directory holds hashes, never KV bytes, so a wrong entry costs one
    failed pull and a plain prefill — never a wrong token.

    One publish records every whole-block boundary of the prompt (k
    blocks for k = 1..nb), so a later prompt sharing only PART of the
    prefix still finds its longest cached span. Capacity is a FIFO cap
    on total entries; re-publishing refreshes an entry's position."""

    def __init__(self, max_entries: int = 4096):
        self.max_entries = int(max_entries)
        #: hash(block_size, token prefix) → (replica, weight_version,
        #: n_blocks, block_size); insertion-ordered = FIFO eviction
        self._entries: dict[str, tuple[str, int, int, int]] = {}
        self._block_sizes: set[int] = set()
        self.published_total = 0         # host ledgers (tests/bench)
        self.flushed_total = 0

    @staticmethod
    def _key(tokens: Sequence[int], n_tokens: int,
             block_size: int) -> str:
        h = hashlib.blake2b(digest_size=16)
        h.update(f"{block_size}|".encode())
        h.update(",".join(str(int(t))
                          for t in tokens[:n_tokens]).encode())
        return h.hexdigest()

    def __len__(self) -> int:
        return len(self._entries)

    def publish(self, replica: str, tokens: Sequence[int], *,
                block_size: int, weight_version: int) -> int:
        """Record that ``replica`` holds every whole-block prefix of
        ``tokens`` under ``weight_version``; returns blocks recorded."""
        bs = int(block_size)
        nb = 0 if bs <= 0 else len(tokens) // bs
        if nb <= 0:
            return 0
        self._block_sizes.add(bs)
        for k in range(1, nb + 1):
            key = self._key(tokens, k * bs, bs)
            self._entries.pop(key, None)     # refresh FIFO position
            self._entries[key] = (replica, int(weight_version), k, bs)
        self.published_total += nb
        while len(self._entries) > self.max_entries:
            self._entries.pop(next(iter(self._entries)))
        return nb

    def lookup(self, tokens: Sequence[int]
               ) -> Optional[tuple[str, int, int]]:
        """Longest directory-known whole-block prefix of ``tokens`` →
        ``(replica, n_blocks, block_size)``, or None. Longest-first so
        a pull moves the maximal cached span in one hop."""
        best: Optional[tuple[str, int, int]] = None
        for bs in self._block_sizes:
            for k in range(len(tokens) // bs, 0, -1):
                if best is not None and k * bs <= best[1] * best[2]:
                    break                # cannot beat the current best
                ent = self._entries.get(self._key(tokens, k * bs, bs))
                if ent is None:
                    continue
                best = (ent[0], k, bs)
                break
        return best

    def flush_stale(self, *, replica: Optional[str] = None,
                    below_version: Optional[int] = None) -> int:
        """Atomically drop entries for ``replica`` and/or entries whose
        tagged version is below ``below_version`` — the invalidation
        hook weight pushes and replica deaths call. Returns dropped."""
        doomed = [k for k, (rep, ver, _nb, _bs) in self._entries.items()
                  if (replica is not None and rep == replica)
                  or (below_version is not None
                      and ver < int(below_version))]
        for k in doomed:
            self._entries.pop(k, None)
        self.flushed_total += len(doomed)
        return len(doomed)

    def drop_replica(self, replica: str) -> int:
        return self.flush_stale(replica=replica)


class ReplicaHandle:
    """Router-side view of one registered replica."""

    def __init__(self, name: str, engine: ServingEngine):
        self.name = name
        self.engine = engine
        self.state = "live"          # live | draining | dead
        self.role = "both"           # both | prefill | decode (P/D
        #                              disaggregation — set by register)
        self.registered_s = time.monotonic()
        self.last_beat: Optional[float] = None   # external heartbeats
        self.inflight: dict[int, RouterRequest] = {}   # inner id → rreq
        self.dispatched = 0
        self.ttft_ewma_s: Optional[float] = None

    def loop_alive(self) -> bool:
        t = self.engine._thread
        return t is not None and t.is_alive()

    def loop_died(self) -> bool:
        """True only for a loop that RAN and exited — a replica
        registered with ``start=False`` (caller drives the engine, e.g.
        tests stepping by hand) is not dead, just externally driven."""
        t = self.engine._thread
        return t is not None and not t.is_alive()

    @property
    def load(self) -> int:
        return self.engine.load

    @property
    def weight_version(self) -> int:
        return self.engine.weight_version

    def status(self) -> dict:
        return {"state": self.state, "role": self.role,
                "load": self.load,
                "queue_depth": self.engine.scheduler.depth,
                "occupancy": round(self.engine.scheduler.occupancy, 4),
                "loop_running": self.loop_alive(),
                "weight_version": self.weight_version,
                "dispatched": self.dispatched,
                "inflight": len(self.inflight),
                "ttft_ewma_ms": None if self.ttft_ewma_s is None
                else round(self.ttft_ewma_s * 1e3, 3)}


class Router:
    """Load-aware, prefix-sticky dispatch over registered replicas.

    Replicas come in two shapes: live :class:`ServingEngine` objects
    whose background loops this process runs (threads — the suite's
    and the rollout workload's single-host shape), and REMOTE engine
    processes registered through a
    :class:`~hetu_tpu.serving.fleet.RemoteEngineProxy` (ISSUE 15 —
    one engine per accelerator host, the serving verbs travel the
    coordinator line protocol). Death is detected from the replica's
    loop thread, or — for remote/externally-driven replicas — from
    heartbeat staleness; a monitor thread finalizes completions,
    streams prefill-tier handoffs to the decode tier, requeues a dead
    replica's undelivered requests onto peers, and keeps the fleet
    gauges fresh.
    """

    def __init__(self, *, affinity_tokens: int = 16,
                 affinity_slack: int = 2,
                 beat_timeout_s: float = 2.0,
                 max_attempts: int = 5,
                 poll_s: float = 0.002,
                 scrape_every_s: float = 1.0,
                 kv_pull: bool = True,
                 replicate_kv: bool = False,
                 replicate_cadence_s: float = 0.02,
                 directory_max_entries: int = 4096):
        self.affinity_tokens = int(affinity_tokens)
        #: a sticky (prefix-affinity) pick is honored only while its
        #: load is within this many requests of the least-loaded
        #: replica — past that, cache locality loses to balance
        self.affinity_slack = int(affinity_slack)
        self.beat_timeout_s = float(beat_timeout_s)
        self.max_attempts = int(max_attempts)
        self.poll_s = float(poll_s)
        self._replicas: dict[str, ReplicaHandle] = {}
        self._pending: deque[RouterRequest] = deque()
        self._lock = threading.RLock()
        self._next_id = 0
        self._requests_by_id: dict[int, RouterRequest] = {}  # RPC poll
        self.requeues_total = 0              # host ledger (tests read)
        self._monitor: Optional[threading.Thread] = None
        self._stop_ev: Optional[threading.Event] = None
        self.slo = None          # HEALTHZ duck-type parity with engines
        # -- metrics/health federation (ISSUE 16): the monitor scrapes
        # each replica's METRICS/HEALTHZ on this cadence; FLEETMETRICS
        # and the fleet HEALTHZ rollup serve from the cache
        self.scrape_every_s = float(scrape_every_s)
        self._fed_lock = threading.Lock()
        self._fed: dict[str, dict] = {}      # name → {metrics, health}
        self._fed_ts = 0.0                   # monotonic of last scrape
        # -- fleet-global KV plane (ISSUE 18) --------------------------
        #: consult the prefix directory at dispatch and pull a fleet-hot
        #: prefix onto a miss replica instead of re-prefilling it
        self.kv_pull = bool(kv_pull)
        #: stream every decoding request's newly committed KV blocks to
        #: a rendezvous-chosen buddy replica, so a SIGKILLed replica's
        #: mid-decode requests resume from the buddy's replica set
        self.replicate_kv = bool(replicate_kv)
        self.replicate_cadence_s = float(replicate_cadence_s)
        self._directory = FleetPrefixDirectory(directory_max_entries)
        self._buddy_of: dict[str, str] = {}  # origin → buddy name
        # streaming control plane (ISSUE 19): rreq id → bridges feeding
        # outward token subscriptions across dispatches/failovers
        self._stream_bridges: dict[int, list[_StreamBridge]] = {}

    # -- replica lifecycle --------------------------------------------------
    def register(self, name: str, engine: ServingEngine, *,
                 start: bool = True,
                 role: str = "both") -> ReplicaHandle:
        """Add a replica (its engine loop is started unless it already
        runs or ``start=False``) and ensure the monitor is running.

        ``engine`` may be an in-process :class:`ServingEngine` or a
        :class:`~hetu_tpu.serving.fleet.RemoteEngineProxy` (a replica
        in another process — the handle then detects death by
        heartbeat staleness instead of watching a loop thread).

        ``role`` is the P/D-disaggregation tier: ``"both"`` (default —
        the colocated shape), ``"prefill"`` (admission + prefill only:
        finished KV blocks stream to the decode tier), or ``"decode"``
        (resumes streamed KV and decodes). Dispatch only splits when a
        live prefill replica AND a live decode-capable replica both
        exist; otherwise requests run colocated wherever they land."""
        if role not in ("both", "prefill", "decode"):
            raise ValueError(f"role must be both|prefill|decode, "
                             f"got {role!r}")
        with self._lock:
            if name in self._replicas \
                    and self._replicas[name].state != "dead":
                raise ValueError(f"replica {name!r} already registered")
            if start:
                # start BEFORE the handle is visible: the monitor marks
                # replicas whose loop thread died as dead, and a handle
                # published with the thread not yet up would race it
                engine.start()
            if getattr(engine, "remote", False):
                from hetu_tpu.serving.fleet import RemoteReplicaHandle
                h = RemoteReplicaHandle(name, engine)
            else:
                h = ReplicaHandle(name, engine)
            h.role = role
            self._replicas[name] = h
        flight_record("router_replica", replica=name, state="live",
                      event="register", role=role)
        self.start()
        return h

    def heartbeat(self, name: str) -> None:
        with self._lock:
            self._replicas[name].last_beat = time.monotonic()

    def drain(self, name: str, *, timeout_s: float = 30.0,
              preempt: bool = False) -> int:
        """Stop dispatching to ``name``, re-dispatch its queued (not
        yet admitted) requests onto peers, and wait for its admitted
        work to run out. Returns how many requests were re-dispatched.
        The engine's loop keeps running throughout — drain is a routing
        state, not a process state.

        ``preempt=True`` is the RESUMABLE drain (the weight publisher's
        default): instead of waiting for admitted requests to decode to
        completion, evict them — mid-decode requests spill their KV and
        resume on a peer with zero prefill-lane work. Taken only for
        requests a live SAME-weight-version peer can resume; when the
        fleet has no such peer (e.g. the last replica of a rolling
        push, its peers already swapped), the request runs out here
        under the weights it started with — preempting it onto new
        weights would splice two models into one output."""
        with self._lock:
            h = self._replicas[name]
            if h.state == "dead":
                raise ValueError(f"replica {name!r} is dead")
            h.state = "draining"
            # pull only the queued requests the ROUTER owns: one
            # submitted directly to the engine stays queued and drains
            # through normal admission (orphaning it would leave its
            # done event unset forever)
            moved = h.engine.cancel_queued(set(h.inflight.keys()))
            n = 0
            for inner in moved:
                rreq = h.inflight.pop(inner.id, None)
                if rreq is not None:
                    rreq.spill = inner.spill     # a preempted-then-
                    #                              pulled request keeps
                    #                              its KV
                    self._requeue_locked(rreq, from_replica=name,
                                         reason="drain")
                    n += 1
            if preempt:
                version = h.engine.weight_version
                peer_ok = any(
                    p.state == "live" and p is not h
                    and p.role in ("both", "decode")
                    and p.engine.weight_version == version
                    for p in self._replicas.values())
                if peer_ok:
                    for inner_id, rreq in list(h.inflight.items()):
                        if rreq.inner is None \
                                or rreq.inner.done.is_set():
                            continue
                        try:
                            entry = h.engine.evict_request(
                                rreq.inner, lock_timeout_s=5.0)
                        except Exception:
                            continue             # best-effort: let it run
                        if rreq.inner.status != "evicted":
                            continue             # finished under us
                        h.inflight.pop(inner_id, None)
                        rreq.spill = entry
                        self._requeue_locked(rreq, from_replica=name,
                                             reason="drain_preempt")
                        n += 1
        flight_record("router_replica", replica=name, state="draining",
                      event="drain", requeued=n)
        deadline = time.monotonic() + timeout_s
        while h.engine.has_work():
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"replica {name!r} still busy after {timeout_s}s "
                    f"drain (load={h.load})")
            time.sleep(self.poll_s)
        return n

    def resume(self, name: str) -> None:
        """Return a drained replica to the dispatch pool."""
        with self._lock:
            h = self._replicas[name]
            if h.state == "dead":
                raise ValueError(f"replica {name!r} is dead")
            h.state = "live"
        flight_record("router_replica", replica=name, state="live",
                      event="resume")

    def kill_replica(self, name: str) -> int:
        """Chaos hook: treat ``name`` as crashed RIGHT NOW — halt its
        loop without waiting, mark it dead, and requeue every
        undelivered in-flight request onto peers. Returns the number
        requeued."""
        with self._lock:
            h = self._replicas[name]
            if h.engine._stop is not None:
                h.engine._stop.set()     # crash semantics: no join
            return self._mark_dead_locked(h, reason="killed")

    def _mark_dead_locked(self, h: ReplicaHandle, *, reason: str) -> int:
        if h.state == "dead":
            return 0
        h.state = "dead"
        n = 0
        for inner_id, rreq in list(h.inflight.items()):
            h.inflight.pop(inner_id)
            if rreq.inner is not None and rreq.inner.done.is_set():
                self._finalize_locked(h, rreq)   # it DID finish — keep
            else:
                # salvage the KV: a killed replica is a stopped loop in
                # THIS process, so its arena is still readable — a
                # mid-decode request spills and resumes on a peer
                # instead of regenerating from scratch. Salvage is
                # best-effort and BOUNDED: a replica that is dead
                # because its step is WEDGED still holds its iteration
                # lock, and this path runs under the router lock — a
                # timed-out acquire degrades to the pre-spill fresh
                # requeue instead of freezing the whole fleet. A dead
                # REMOTE replica is unreachable by definition (beats
                # stopped: SIGKILL, host loss, partition) — attempting
                # K wire EVICTs here would stall the router lock up to
                # K connect timeouts for salvage that cannot succeed;
                # cross-process KV moves only on cooperative paths
                # (drains, P/D handoffs)
                if rreq.inner is not None \
                        and not getattr(h, "remote", False):
                    try:
                        rreq.spill = h.engine.evict_request(
                            rreq.inner, lock_timeout_s=2.0)
                    except Exception:            # salvage is best-effort
                        rreq.spill = None
                # second line of defense (ISSUE 18): when the arena is
                # unreachable (SIGKILLed remote process, wedged step),
                # the rendezvous buddy's replica set still holds the
                # request's streamed decode KV — fetch it by trace_id
                # so the requeue RESUMES mid-decode instead of
                # replaying the prompt
                if rreq.spill is None and self.replicate_kv \
                        and rreq.inner is not None:
                    rreq.spill = self._fetch_buddy_kv_locked(h, rreq)
                self._requeue_locked(rreq, from_replica=h.name,
                                     reason=reason)
                n += 1
        # the dead replica's prefix-directory entries and buddy wiring
        # are void; origins that replicated TO it rewire next tick
        self._directory.drop_replica(h.name)
        self._buddy_of.pop(h.name, None)
        for origin, b in list(self._buddy_of.items()):
            if b == h.name:
                self._buddy_of.pop(origin, None)
        flight_record("router_replica", replica=h.name, state="dead",
                      event=reason, requeued=n)
        return n

    # -- dispatch -----------------------------------------------------------
    def _affinity_pick(self, prompt: Sequence[int],
                       live: list[ReplicaHandle]) -> ReplicaHandle:
        """Rendezvous (highest-random-weight) hash of the prompt's
        first ``affinity_tokens`` ids over the LIVE replica names:
        requests sharing a prefix agree on a replica, and replica
        arrival/death reshuffles only the keys that hashed to the
        changed member — the prefix cache keeps hitting through fleet
        churn."""
        key = ",".join(str(int(t))
                       for t in prompt[:self.affinity_tokens])
        return max(live, key=lambda h: hashlib.blake2b(
            f"{h.name}|{key}".encode(), digest_size=8).digest())

    def _pick_locked(self, prompt, *, tier: str = "decode",
                     sampling=None
                     ) -> Optional[tuple[ReplicaHandle, str]]:
        roles = ("prefill",) if tier == "prefill" \
            else ("both", "decode")
        live = [h for h in self._replicas.values()
                if h.state == "live" and h.role in roles]
        if not live:
            return None
        loads = {h.name: h.load for h in live}
        least = min(live, key=lambda h: (
            loads[h.name],
            h.ttft_ewma_s if h.ttft_ewma_s is not None else 0.0,
            h.name))
        # adapter-affine dispatch (ISSUE 20): a request carrying a LoRA
        # adapter prefers a replica whose arena already holds it — the
        # landing there skips an ensure_resident load (and a possible
        # LRU eviction churning some other tenant's page). Same load-
        # slack discipline as the prefix planes: a hot adapter cannot
        # starve the fleet, past the slack the pick falls through and
        # the publisher/engine loads the adapter wherever the request
        # lands.
        adapter = getattr(sampling, "adapter", None) \
            if sampling is not None else None
        if adapter is not None:
            tenant = getattr(sampling, "tenant", None)
            holders = []
            for h in live:
                plane = getattr(h.engine, "tenancy", None)
                if plane is None:
                    continue
                try:
                    if plane.registry.resident(tenant, adapter):
                        holders.append(h)
                except Exception:   # remote proxy without the surface
                    continue
            if holders:
                best = min(holders, key=lambda h: (loads[h.name],
                                                   h.name))
                if loads[best.name] <= loads[least.name] \
                        + self.affinity_slack:
                    return best, "adapter"
        # the fleet prefix directory outranks rendezvous affinity: it
        # records where the prefix ACTUALLY sits (affinity only guesses
        # where it should), under the same load-slack rule so a fleet-
        # hot prefix cannot starve the fleet. Past the slack the
        # dispatch falls through — and _pull_prefix_locked moves the
        # prefix to wherever the request lands instead.
        if self.kv_pull and tier != "prefill":
            hit = self._directory.lookup(prompt)
            if hit is not None:
                owner = self._replicas.get(hit[0])
                if owner is not None and owner.name in loads \
                        and loads[owner.name] <= loads[least.name] \
                        + self.affinity_slack:
                    return owner, "directory"
        sticky = self._affinity_pick(prompt, live)
        if loads[sticky.name] <= loads[least.name] + self.affinity_slack:
            return sticky, "affinity"
        return least, "least_loaded"

    def _dispatch_locked(self, rreq: RouterRequest) -> bool:
        """Place ``rreq`` on a live replica; False parks it pending."""
        if rreq.attempts >= self.max_attempts:
            rreq.status = "failed"
            rreq.error = (f"gave up after {rreq.attempts} dispatch "
                          f"attempts (replicas kept dying)")
            rreq.finish_s = time.monotonic()
            rreq.done.set()
            self._stream_finish_locked(rreq)
            return True                      # terminal — not pending
        # P/D disaggregation: a FRESH request (no KV spill riding along)
        # goes to the prefill tier when one exists alongside a live
        # decode-capable peer — it prefills there, parks after its
        # first token, and the monitor streams its KV blocks to the
        # decode tier (reason "pd_handoff" requeue). A spill-carrying
        # request always goes straight to the decode tier: its KV
        # already exists.
        handoff = False
        if rreq.spill is None and any(
                h.state == "live" and h.role == "prefill"
                for h in self._replicas.values()) and any(
                h.state == "live" and h.role in ("both", "decode")
                for h in self._replicas.values()):
            picked = self._pick_locked(rreq.prompt, tier="prefill",
                                       sampling=rreq.sampling)
            handoff = picked is not None
        else:
            picked = None
        if picked is None:
            picked = self._pick_locked(rreq.prompt,
                                       sampling=rreq.sampling)
        if picked is None:
            return False
        h, reason = picked
        # fleet-global prefix plane (ISSUE 18): a fresh request landing
        # off the directory's owner first PULLS the cached prefix onto
        # its replica (export → wire → import), so a fleet-hot prefix
        # prefills ONCE per weight version no matter where load-
        # balancing scatters its requests. Hit/miss token ledgers feed
        # the bench + acceptance asserts.
        if not handoff and rreq.spill is None and self.kv_pull:
            warm = self._pull_prefix_locked(h, rreq)
            reg0 = telemetry.get_registry()
            reg0.counter(
                "fleet_prefix_hit_tokens_total",
                "prompt tokens covered by the fleet prefix directory "
                "at dispatch (served from cached KV — locally or via "
                "a cross-replica pull — not the prefill lane)").inc(
                warm)
            reg0.counter(
                "fleet_prefix_miss_tokens_total",
                "prompt tokens the fleet prefix directory could not "
                "cover at dispatch (prefilled from scratch)").inc(
                max(0, len(rreq.prompt) - warm))
        # every dispatch hop mints a fresh span id under the request's
        # one trace id — the replica's local spans and flight events
        # then join the fleet trace (ISSUE 16)
        tp = telemetry.make_traceparent(rreq.trace_id)
        t0 = time.perf_counter()
        if handoff:
            reason = "pd_prefill"
            inner = h.engine.submit(rreq.prompt, rreq.sampling,
                                    handoff=True, traceparent=tp)
        else:
            inner = h.engine.submit(rreq.prompt, rreq.sampling,
                                    resume=rreq.spill, traceparent=tp)
        if rreq.spill is not None:
            if inner.spill is rreq.spill:     # the peer took the KV
                rreq.resumed_dispatches += 1
                telemetry.get_registry().counter(
                    "router_resumed_requeues_total",
                    "requeued requests whose KV spill a peer accepted "
                    "(resumed mid-decode, no re-prefill)").inc()
            rreq.spill = None      # stale either way once dispatched —
            #                        a later death re-spills fresh state
        if not handoff:
            # a planned prefill-tier placement is half of the normal
            # P/D flow, not a failure retry: only the decode placement
            # (and real requeues) spend the max_attempts budget, so a
            # split request tolerates as many replica deaths as a
            # colocated one. The evict-failure loop stays bounded —
            # _handoff_locked charges an attempt when the KV pull
            # comes back empty.
            rreq.attempts += 1
        rreq.replica = h.name
        rreq.inner = inner
        if inner.status == "rejected":       # admission gate: terminal
            rreq.status = "rejected"
            rreq.error = inner.error
            rreq.finish_s = time.monotonic()
            rreq.done.set()
            self._stream_finish_locked(rreq)
            return True
        rreq.status = "dispatched"
        h.inflight[inner.id] = rreq
        for br in self._stream_bridges.get(rreq.id, ()):
            br.attach(h)                 # resume the push at the cursor
        h.dispatched += 1
        reg = telemetry.get_registry()
        reg.counter("router_requests_total",
                    "requests dispatched by the fleet router, by "
                    "replica").inc(replica=h.name)
        reg.counter("router_dispatch_reason_total",
                    "why the router picked the replica it picked").inc(
            reason=reason)
        flight_record("router_dispatch", req=rreq.id,
                      trace=rreq.trace_id, replica=h.name,
                      reason=reason, attempt=rreq.attempts,
                      load=h.load)
        self._trace_req_span(rreq, "dispatch", t0,
                             replica=h.name, reason=reason)
        return True

    def _trace_req_span(self, rreq: RouterRequest, name: str,
                        t0: float, **attrs) -> None:
        """Emit a span on the request's Perfetto track in THIS process:
        the router-side fragments (dispatch, KV handoff) that
        ``tools/fleet_trace.py`` merges with the replicas' queued /
        prefill / decode fragments into one cross-process request
        timeline keyed by ``trace_id`` (ISSUE 16)."""
        tracer = telemetry.get_tracer()
        if not tracer.enabled:
            return
        tid = REQ_TRACK_BASE + rreq.id
        tracer.name_track(tid, f"req {rreq.trace_id}")
        tracer.complete(name, time.perf_counter() - t0, cat="request",
                        tid=tid, trace_id=rreq.trace_id, req=rreq.id,
                        **attrs)

    # -- fleet-global KV plane (ISSUE 18) ------------------------------------
    @staticmethod
    def _replica_block_size(h: ReplicaHandle) -> int:
        """The replica's KV block size — straight off the pool for an
        in-process engine, off the last ESTATUS poll for a remote one
        (0 until the first poll lands: publication just waits)."""
        try:
            if getattr(h, "remote", False):
                return int(getattr(h.engine, "block_size", 0) or 0)
            return int(h.engine.pool.block_size)
        except Exception:                             # noqa: BLE001
            return 0

    def _pull_prefix_locked(self, h: ReplicaHandle,
                            rreq: RouterRequest) -> int:
        """Consult the directory for ``rreq.prompt`` and, when the
        owner is a DIFFERENT live replica, pull the cached span onto
        ``h`` (owner export → wire → ``h`` import) before the submit.
        Returns the prompt tokens now warm on ``h`` (0 = cold: the
        request prefills normally). Every failure mode — dead owner,
        export miss, stale weight version, full arena — degrades to
        that plain prefill; a pull can cost time, never correctness."""
        hit = self._directory.lookup(rreq.prompt)
        if hit is None:
            return 0
        owner_name, nb, bs = hit
        span = nb * bs
        if owner_name == h.name:
            return span              # dispatch landed ON the owner
        owner = self._replicas.get(owner_name)
        if owner is None or owner.state == "dead":
            self._directory.drop_replica(owner_name)
            return 0
        t0 = time.perf_counter()
        try:
            entry = owner.engine.export_prefix(rreq.prompt[:span])
        except Exception:                             # noqa: BLE001
            entry = None
        if entry is None:
            # the owner no longer holds it (LRU churn, weight swap
            # flush, wedged step): the directory lied — retract it
            self._directory.flush_stale(replica=owner_name)
            return 0
        try:
            ok = h.engine.import_prefix(entry)
        except Exception:                             # noqa: BLE001
            ok = False
        if not ok:
            return 0     # version-stale or no free blocks: prefill
        reg = telemetry.get_registry()
        reg.counter(
            "fleet_kv_pull_blocks_total",
            "KV blocks pulled between replicas by the fleet prefix "
            "directory (a fleet-hot prefix prefills once, then "
            "travels)").inc(entry.n_blocks)
        reg.counter(
            "fleet_kv_pull_bytes_total",
            "KV bytes moved by fleet prefix-directory pulls").inc(
            entry.nbytes())
        # the pulled span is now cached HERE too — future lookups may
        # land on either copy
        self._directory.publish(
            h.name, list(entry.tokens), block_size=entry.block_size,
            weight_version=entry.weight_version)
        flight_record("fleet_kv_pull", req=rreq.id,
                      trace=rreq.trace_id, owner=owner_name,
                      to=h.name, blocks=entry.n_blocks,
                      bytes=entry.nbytes())
        self._trace_req_span(rreq, "kv_pull", t0, owner=owner_name,
                             to=h.name, blocks=entry.n_blocks)
        return entry.n_blocks * entry.block_size

    def _buddy_pick(self, origin: ReplicaHandle,
                    candidates: list) -> ReplicaHandle:
        """Rendezvous hash over (origin, candidate) pairs: stable under
        churn — a replica joining/dying reshuffles only the origins
        that hashed to it."""
        return max(candidates, key=lambda p: hashlib.blake2b(
            f"{origin.name}|{p.name}".encode(),
            digest_size=8).digest())

    def _assign_buddies_locked(self) -> None:
        """Keep every decode-capable replica's replication stream
        pointed at its rendezvous buddy; rewires only on membership
        change. A REMOTE origin replicates only to a remote buddy (its
        engine process needs a coordinator address to ship to — the
        router process is not one); in-process origins take any peer."""
        live = [h for h in self._replicas.values()
                if h.state in ("live", "draining")]
        for h in live:
            if h.role == "prefill":
                continue         # the prefill tier holds no decode KV
            peers = [p for p in live if p is not h
                     and p.role in ("both", "decode")]
            if getattr(h, "remote", False):
                peers = [p for p in peers
                         if getattr(p, "remote", False)]
            cur = self._buddy_of.get(h.name)
            if not peers:
                if cur is not None:
                    self._wire_buddy(h, None)
                continue
            buddy = self._buddy_pick(h, peers)
            if buddy.name != cur:
                self._wire_buddy(h, buddy)

    def _wire_buddy(self, h: ReplicaHandle,
                    buddy: Optional[ReplicaHandle]) -> None:
        try:
            if buddy is None:
                if getattr(h, "remote", False):
                    h.engine.set_kv_buddy(None)
                else:
                    h.engine.configure_replication(None)
                self._buddy_of.pop(h.name, None)
                return
            if getattr(h, "remote", False):
                # KVBUDDY: the origin's engine process opens its own
                # socket to the buddy's front door and streams KVREPL
                if not h.engine.set_kv_buddy(
                        buddy.engine.host, buddy.engine.port,
                        token=buddy.engine._token, origin=h.name,
                        cadence_s=self.replicate_cadence_s):
                    return               # retried next monitor tick
            elif getattr(buddy, "remote", False):
                h.engine.configure_replication(
                    buddy.engine.kv_put, origin=h.name,
                    cadence_s=self.replicate_cadence_s)
            else:
                h.engine.configure_replication(
                    buddy.engine.kv_replica_store.put, origin=h.name,
                    cadence_s=self.replicate_cadence_s)
            self._buddy_of[h.name] = buddy.name
            flight_record("fleet_kv_buddy", origin=h.name,
                          buddy=buddy.name)
        except Exception:                             # noqa: BLE001
            pass          # wire failure: reassignment retries next tick

    def _fetch_buddy_kv_locked(self, h: ReplicaHandle,
                               rreq: RouterRequest):
        """Recover a dead replica's mid-decode request from its buddy's
        replica set, keyed by the fleet-stable ``trace_id``. None when
        the buddy is gone or never got a complete shipment — the
        requeue then replays from the prompt (greedy decoding keeps
        that token-identical, just slower)."""
        buddy = self._replicas.get(self._buddy_of.get(h.name, ""))
        if buddy is None or buddy.state == "dead":
            return None
        try:
            if getattr(buddy, "remote", False):
                entry = buddy.engine.kv_fetch(rreq.trace_id)
            else:
                entry = buddy.engine.kv_replica_store.fetch(
                    rreq.trace_id)
        except Exception:                             # noqa: BLE001
            return None
        if entry is not None:
            telemetry.get_registry().counter(
                "fleet_kv_recoveries_total",
                "mid-decode requests resumed from a buddy's "
                "replicated KV after their replica died (no prefill "
                "replay)").inc()
            flight_record("fleet_kv_recover", req=rreq.id,
                          trace=rreq.trace_id, victim=h.name,
                          buddy=buddy.name, blocks=entry.n_blocks,
                          pos=entry.pos)
        return entry

    def _requeue_locked(self, rreq: RouterRequest, *,
                        from_replica: str, reason: str) -> None:
        for br in self._stream_bridges.get(rreq.id, ()):
            br.detach()                  # stop feeding from the corpse
        rreq.inner = None                    # old replica's work is void
        rreq.status = "queued"
        reg = telemetry.get_registry()
        if reason == "pd_handoff":
            # the planned prefill→decode hop is not a failure requeue —
            # it gets its own ledger so drain/death stats stay honest
            reg.counter(
                "fleet_pd_handoffs_total",
                "requests handed from the prefill tier to the decode "
                "tier (P/D disaggregation — KV streamed, zero "
                "re-prefill)").inc()
        else:
            self.requeues_total += 1
            reg.counter(
                "router_requeues_total",
                "in-flight requests re-dispatched after a replica "
                "drain/death").inc()
            src = self._replicas.get(from_replica)
            if src is not None and getattr(src, "remote", False):
                reg.counter(
                    "fleet_remote_requeues_total",
                    "requeues whose source replica was a REMOTE "
                    "process (death detected by heartbeat staleness, "
                    "or a cross-process drain)").inc()
        flight_record("router_requeue", req=rreq.id,
                      trace=rreq.trace_id, from_replica=from_replica,
                      reason=reason)
        if not self._dispatch_locked(rreq):
            self._pending.append(rreq)

    # -- request surface (same shape as ServingEngine's) --------------------
    def submit(self, prompt: Sequence[int],
               sampling: Optional[SamplingParams] = None, *,
               traceparent: Optional[str] = None) -> RouterRequest:
        """Dispatch one request to the fleet; parks it pending when no
        replica is live (the monitor places it as soon as one is).
        ``traceparent`` adopts an upstream trace context (a front-door
        SUBMIT that already carries one) instead of minting a fresh
        trace id."""
        sampling = sampling or SamplingParams()
        with self._lock:
            rreq = RouterRequest(
                id=self._next_id, prompt=[int(t) for t in prompt],
                sampling=sampling, submit_s=time.monotonic())
            self._next_id += 1
            if traceparent:
                tid, _span = telemetry.parse_traceparent(traceparent)
                if tid:
                    rreq.trace_id = tid
                    rreq.traceparent = traceparent
            if not self._dispatch_locked(rreq):
                self._pending.append(rreq)
        return rreq

    def result(self, req: RouterRequest,
               timeout: Optional[float] = None) -> Optional[dict]:
        if not req.done.wait(timeout):
            return None
        return req.result()

    def stream_subscribe(self, rreq: RouterRequest, *,
                         offset: int = 0, max_queue: int = 256):
        """Duck-parity with :meth:`ServingEngine.stream_subscribe`
        (the front door serves a Router and an engine through one
        STREAM/SUBSCRIBE code path): a bounded token subscription fed
        by whatever replica currently serves ``rreq``, surviving
        requeues and failovers — every re-dispatch resumes the push at
        the subscription's token cursor, so nothing is lost and
        nothing replays (ISSUE 19)."""
        from hetu_tpu.serving.streaming import TokenSubscription
        sub = TokenSubscription(rreq.id, offset=offset,
                                max_queue=max_queue)
        br = _StreamBridge(rreq, sub)
        with self._lock:
            if rreq.done.is_set():
                br.finalize()            # backlog + terminal, replayed
                return sub
            self._stream_bridges.setdefault(rreq.id, []).append(br)
            if rreq.status == "dispatched" and rreq.inner is not None:
                h = self._replicas.get(rreq.replica)
                if h is not None:
                    br.attach(h)
        return sub

    def _stream_finish_locked(self, rreq: RouterRequest) -> None:
        for br in self._stream_bridges.pop(rreq.id, ()):
            br.finalize()

    def generate_many(
            self, prompts: Sequence[Sequence[int]],
            sampling: Union[SamplingParams, Sequence[SamplingParams],
                            None] = None) -> list[list[int]]:
        """Fleet analogue of ``ServingEngine.generate_many``: submit
        every prompt, wait, return per-request tokens in submission
        order — which replica served each request never changes its
        tokens (greedy; asserted in tests). Raises on any admission
        rejection, like the engine."""
        if sampling is None or isinstance(sampling, SamplingParams):
            sampling = [sampling or SamplingParams()] * len(prompts)
        reqs = [self.submit(p, sp) for p, sp in zip(prompts, sampling)]
        bad = [r for r in reqs if r.status == "rejected"]
        if bad:
            raise ValueError(
                f"{len(bad)} request(s) rejected at admission: "
                + "; ".join(f"#{r.id}: {r.error}" for r in bad[:3]))
        for r in reqs:
            r.done.wait()
            if r.status != "done":
                raise RuntimeError(
                    f"request #{r.id} {r.status}: {r.error}")
        return [list(r.tokens) for r in reqs]

    # -- the monitor --------------------------------------------------------
    def _finalize_locked(self, h: ReplicaHandle,
                         rreq: RouterRequest) -> None:
        inner = rreq.inner
        rreq.tokens = list(inner.tokens)
        rreq.status = inner.status
        rreq.error = inner.error
        rreq.weight_version = inner.weight_version
        rreq.finish_s = time.monotonic()
        if inner.first_token_s is not None:
            ttft = inner.first_token_s - inner.submit_s
            h.ttft_ewma_s = ttft if h.ttft_ewma_s is None \
                else 0.8 * h.ttft_ewma_s + 0.2 * ttft
        # a finished request leaves its prompt's whole-block prefix in
        # the replica's radix cache — publish that to the fleet
        # directory (version-tagged) so peers can pull it (ISSUE 18)
        if self.kv_pull and rreq.status == "done" \
                and h.state != "dead":
            bs = self._replica_block_size(h)
            if bs > 0:
                self._directory.publish(
                    h.name, rreq.prompt, block_size=bs,
                    weight_version=int(rreq.weight_version or 0))
        rreq.done.set()
        self._stream_finish_locked(rreq)

    def _handoff_locked(self, h: ReplicaHandle, inner_id: int,
                        rreq: RouterRequest, reg) -> None:
        """Move one prefilled request from its prefill-tier replica to
        the decode tier: evict the parked KV (a SpillEntry — the same
        payload preemption and drains move) and requeue it with the
        spill riding along, so the decode replica resumes it with ZERO
        prefill-lane work."""
        inner = rreq.inner
        t0 = time.perf_counter()
        try:
            entry = h.engine.evict_request(inner, lock_timeout_s=5.0)
        except Exception:                             # noqa: BLE001
            entry = None
        if inner.done.is_set():          # raced to completion under us
            h.inflight.pop(inner_id, None)
            self._finalize_locked(h, rreq)
            return
        h.inflight.pop(inner_id, None)
        rreq.spill = entry
        if entry is None:
            # the KV pull failed (wedged engine / lost wire payload):
            # this re-enters the prefill tier for a fresh prefill —
            # charge an attempt so a persistently failing replica
            # cannot loop the request forever
            rreq.attempts += 1
        if entry is not None:
            reg.counter(
                "fleet_kv_stream_blocks_total",
                "KV blocks streamed between fleet replicas "
                "(prefill→decode handoffs, cross-process drains and "
                "salvage)").inc(entry.n_blocks)
            flight_record("fleet_kv_stream", req=rreq.id,
                          trace=rreq.trace_id, from_replica=h.name,
                          blocks=entry.n_blocks,
                          tokens=len(entry.tokens))
            self._trace_req_span(rreq, "kv_handoff", t0,
                                 from_replica=h.name,
                                 blocks=entry.n_blocks)
        self._requeue_locked(rreq, from_replica=h.name,
                             reason="pd_handoff")

    def _tick(self) -> None:
        now = time.monotonic()
        reg = telemetry.get_registry()
        with self._lock:
            for h in list(self._replicas.values()):
                if h.state == "dead":
                    continue
                # heartbeat staleness is the liveness signal only for
                # EXTERNALLY-driven replicas: when this process runs
                # the loop thread, a verifiably-alive thread outranks a
                # stale beat (an ops probe that beats once must not
                # doom a healthy replica 2s later)
                beat_stale = h.last_beat is not None \
                    and now - h.last_beat > self.beat_timeout_s \
                    and not h.loop_alive()
                if h.loop_died() or beat_stale:
                    self._mark_dead_locked(
                        h, reason="beat_timeout" if beat_stale
                        else "loop_dead")
                    continue
                for inner_id, rreq in list(h.inflight.items()):
                    if rreq.inner is not None \
                            and rreq.inner.done.is_set():
                        h.inflight.pop(inner_id)
                        self._finalize_locked(h, rreq)
                    elif rreq.inner is not None \
                            and getattr(rreq.inner, "handoff", False) \
                            and rreq.inner.status == "prefilled":
                        # P/D: prefill finished and PARKED — pull its
                        # KV blocks (one gather, or already carried by
                        # the remote PREFILL round trip) and stream
                        # them to the decode tier
                        self._handoff_locked(h, inner_id, rreq, reg)
                    elif getattr(rreq.inner, "status", "") \
                            == "transport_failed":
                        # the remote submit never landed (transient
                        # transport failure, retries exhausted) — the
                        # replica may be perfectly alive, so staleness
                        # will never fire: requeue it ourselves
                        h.inflight.pop(inner_id, None)
                        if getattr(rreq.inner, "handoff", False):
                            # prefill placements are budget-free —
                            # charge the failure here so a flaky
                            # prefill tier cannot loop forever
                            rreq.attempts += 1
                        self._requeue_locked(
                            rreq, from_replica=h.name,
                            reason="transport_failed")
            # keep decode-KV replication streams pointed at the
            # current rendezvous buddies (ISSUE 18)
            if self.replicate_kv:
                self._assign_buddies_locked()
            # place parked requests as capacity (re)appears
            still: deque[RouterRequest] = deque()
            while self._pending:
                rreq = self._pending.popleft()
                if not self._dispatch_locked(rreq):
                    still.append(rreq)
                    break                    # no live replica: stop
            still.extend(self._pending)
            self._pending = still
            live = sum(1 for h in self._replicas.values()
                       if h.state == "live")
            reg.gauge("router_replicas_live",
                      "replicas currently accepting dispatch").set(live)
            for h in self._replicas.values():
                reg.gauge("router_replica_load",
                          "per-replica queued+prefilling+decoding "
                          "requests, as dispatch sees it").set(
                    0 if h.state == "dead" else h.load,
                    replica=h.name)
                if getattr(h, "remote", False) and h.state != "dead" \
                        and h.last_beat is not None:
                    reg.gauge(
                        "fleet_replica_beat_age_seconds",
                        "seconds since a remote replica's last "
                        "successful status poll — the staleness "
                        "signal that declares it dead past "
                        "beat_timeout_s").set(
                        round(now - h.last_beat, 3), replica=h.name)

    def start(self) -> None:
        with self._lock:
            if self._monitor is not None and self._monitor.is_alive():
                return
            self._stop_ev = threading.Event()

            def loop():
                while not self._stop_ev.is_set():
                    self._tick()
                    # federation scrape on its own (slower) cadence —
                    # outside _tick's lock: remote scrapes do network
                    # I/O and must not stall dispatch
                    if time.monotonic() - self._fed_ts \
                            >= self.scrape_every_s:
                        self._scrape_replicas()
                    self._stop_ev.wait(self.poll_s)

            self._monitor = threading.Thread(target=loop, daemon=True,
                                             name="router-monitor")
            self._monitor.start()

    def stop(self) -> None:
        """Stop the monitor and every live replica's engine loop."""
        if self._stop_ev is not None:
            self._stop_ev.set()
        if self._monitor is not None:
            self._monitor.join(timeout=10.0)
            self._monitor = None
        with self._lock:
            handles = list(self._replicas.values())
        for h in handles:
            if h.state != "dead":
                h.engine.stop()

    # -- fleet observability ------------------------------------------------
    def fleet_status(self) -> dict:
        """One aggregation of the whole fleet — what the ``FLEET`` verb
        returns and what ``HEALTHZ`` embeds when a Router (not a bare
        engine) is attached to the coordinator."""
        with self._lock:
            reps = {name: h.status()
                    for name, h in self._replicas.items()}
            return {
                "replicas": reps,
                "live": sum(1 for r in reps.values()
                            if r["state"] == "live"),
                "pending": len(self._pending),
                "requests_total": self._next_id,
                "requeues_total": self.requeues_total,
                "weight_versions": sorted(
                    {r["weight_version"] for r in reps.values()
                     if r["state"] != "dead"}),
                "prefix_directory": {
                    "entries": len(self._directory),
                    "published_total":
                        self._directory.published_total,
                    "flushed_total": self._directory.flushed_total},
                "kv_buddies": dict(self._buddy_of),
            }

    # -- metrics/health federation (ISSUE 16) -------------------------------
    def _scrape_replicas(self) -> None:
        """One federation round: pull METRICS/HEALTHZ from every remote
        replica and snapshot local replica health. Runs WITHOUT the
        router lock (network I/O must not stall dispatch); the handle
        list is snapshotted under it."""
        with self._lock:
            targets = list(self._replicas.items())
        reg = telemetry.get_registry()
        results: dict[str, dict] = {}
        for name, h in targets:
            if h.state == "dead":
                results[name] = {"metrics": None,
                                 "health": {"status": "dead",
                                            "state": "dead"}}
                continue
            if getattr(h, "remote", False):
                try:
                    text = h.engine.metrics_text()
                    health = dict(h.engine.healthz())
                    health.setdefault("status", "ok")
                    outcome = "ok"
                except Exception as e:                # noqa: BLE001
                    text = None
                    health = {"status": "unreachable",
                              "error": f"{type(e).__name__}: {e}"}
                    outcome = "error"
                reg.counter(
                    "fleet_scrapes_total",
                    "federation scrape rounds per remote replica, by "
                    "outcome").inc(replica=name, outcome=outcome)
            else:
                # in-process replicas share THIS process's registry —
                # their series are included once, under "_local", by
                # fleet_metrics_text(); here only health is per-replica
                text = None
                health = dict(h.status())
                health["status"] = "ok" if h.state == "live" \
                    else "degraded"
            if h.state == "draining":
                health["status"] = "degraded"
            results[name] = {"metrics": text, "health": health}
        with self._fed_lock:
            self._fed = results
            self._fed_ts = time.monotonic()

    def _fed_fresh(self, max_age_s: Optional[float]) -> dict:
        """The federation cache, scraping first when stale (or never
        scraped) — keeps FLEETMETRICS correct before the monitor's
        first cadence tick and in externally-driven routers."""
        max_age = self.scrape_every_s if max_age_s is None \
            else float(max_age_s)
        if time.monotonic() - self._fed_ts > max_age or not self._fed:
            self._scrape_replicas()
        with self._fed_lock:
            return dict(self._fed)

    def fleet_metrics_text(self, *,
                           max_age_s: Optional[float] = None) -> str:
        """Fleet-scoped Prometheus page (the FLEETMETRICS verb): every
        remote replica's series labeled ``replica="<name>"``, the local
        process registry once under ``replica="_local"`` (in-process
        replicas share it), plus pre-aggregated ``replica="_fleet"``
        totals."""
        fed = self._fed_fresh(max_age_s)
        texts = {name: doc["metrics"] for name, doc in fed.items()
                 if doc.get("metrics")}
        texts["_local"] = telemetry.get_registry().to_prometheus()
        return telemetry.merge_prometheus(texts)

    def fleet_healthz(self, *,
                      max_age_s: Optional[float] = None) -> dict:
        """Fleet HEALTHZ rollup naming the degraded replicas — embedded
        into the front door's HEALTHZ document when a Router is
        attached."""
        fed = self._fed_fresh(max_age_s)
        return telemetry.health_rollup(
            {name: doc["health"] for name, doc in fed.items()})


def jax_tree_leaves(tree):
    """Array leaves of a pytree (lazy jax import — the router stays
    importable host-side)."""
    import jax
    return [x for x in jax.tree.leaves(tree) if hasattr(x, "size")]


def materialize_params(params, engine: ServingEngine):
    """Copy ``params`` onto ``engine``'s topology for a swap.

    Planned (sharded) replica: the HotSPa reshard core moves every leaf
    onto the replica plan's param shardings — ``force_copy`` because
    the publisher's source is typically a live TrainState whose buffers
    the next train step will DONATE; an aliased fast-path leaf would be
    deleted out from under the replica. Unplanned replica: a plain
    forced device copy, same reasoning."""
    import jax
    import jax.numpy as jnp

    from hetu_tpu.parallel.switch import reshard_tree

    plan = engine._plan
    if plan is not None:
        return reshard_tree(params, plan.state_shardings.params,
                            force_copy=True)
    return jax.tree.map(
        lambda x: jnp.array(x, copy=True)
        if isinstance(x, jax.Array) else x, params)


class WeightPublisher:
    """Trainer-side live weight push: rolling drain → swap → resume.

    One :meth:`publish` call walks the fleet one replica at a time;
    while a replica drains, the router's dispatch (plus the requeue of
    its not-yet-admitted requests) moves its traffic to peers, so with
    ≥ 2 replicas fleet capacity never reaches zero and serving sees no
    downtime — the acceptance bar. Requests admitted before the swap
    finish under the old weights (their tokens are tagged with that
    generation); everything admitted after decodes under the new one.
    A replica that cannot drain within ``drain_timeout_s`` is declared
    dead (its work requeues) rather than blocking the push.

    Drains route through the RESUMABLE path by default
    (``preempt=True`` → :meth:`Router.drain` with KV spill): a
    replica's mid-decode requests move to a same-version peer with
    their KV instead of pinning the drain to the longest running
    decode — push latency stops scaling with ``max_tokens``. The last
    replica of a rolling push (no old-version peer left) falls back to
    run-to-completion, preserving the one-request-one-version
    invariant.

    **Transports** (ISSUE 15): ``transport="reshard"`` (default) moves
    parameters in memory through the HotSPa reshard core — in-process
    replicas only. ``transport="dist_ckpt"`` publishes the new version
    ONCE as a sharded checkpoint (``utils/dist_checkpoint.
    save_params_distributed`` under ``ckpt_dir`` — successive pushes
    delta against the previous version, so a fine-tune push writes
    only what changed) and each replica loads it onto its own
    topology: in-process engines via ``load_params_distributed``,
    remote engine processes via the SWAPWEIGHTS verb (the path must be
    reachable from every replica host — shared filesystem or blob
    store). Version directories referenced by a delta must outlive it;
    the publisher never deletes them."""

    def __init__(self, router: Router, *,
                 drain_timeout_s: float = 60.0, preempt: bool = True,
                 transport: str = "reshard",
                 ckpt_dir: Optional[str] = None):
        if transport not in ("reshard", "dist_ckpt"):
            raise ValueError(f"transport must be reshard|dist_ckpt, "
                             f"got {transport!r}")
        if transport == "dist_ckpt" and not ckpt_dir:
            raise ValueError("transport='dist_ckpt' needs ckpt_dir= "
                             "(where version directories are written)")
        self.router = router
        self.drain_timeout_s = float(drain_timeout_s)
        self.preempt = bool(preempt)
        self.transport = transport
        self.ckpt_dir = ckpt_dir
        self._last_dir: Optional[str] = None   # delta base for the
        #                                        next version's save
        self._last_version = 0       # monotonic floor: remote handles
        #                              report POLLED versions, which can
        #                              lag a just-finished push — the
        #                              publisher's own ledger keeps
        #                              auto-versioning monotonic anyway

    def _publish_checkpoint(self, params, version: int, reg) -> str:
        """Write version ``version`` once (delta against the previous
        push when one exists) and return its directory."""
        import os

        from hetu_tpu.utils.dist_checkpoint import (
            save_params_distributed,
        )
        path = os.path.join(self.ckpt_dir, f"v{int(version):08d}")
        writer = save_params_distributed(
            path, params, version=version,
            delta_base=self._last_dir, hash_pieces=True)
        writer.wait()
        reg.counter(
            "weight_push_bytes_total",
            "parameter bytes moved by fleet weight pushes, by "
            "transport (dist_ckpt counts bytes WRITTEN once per push "
            "— delta savings show here; reshard counts device-copy "
            "bytes per replica)").inc(
            writer.stats["written_bytes"], transport="dist_ckpt")
        return path

    def _swap_replica(self, h: ReplicaHandle, params, path, version,
                      reg) -> dict:
        """The per-replica swap leg, by transport + replica locality."""
        if self.transport == "dist_ckpt":
            if getattr(h, "remote", False):
                return h.engine.swap_from_checkpoint(path, version)
            from hetu_tpu.utils.dist_checkpoint import (
                load_params_distributed,
            )
            local = load_params_distributed(path, h.engine.model,
                                            plan=h.engine._plan)
            return h.engine.swap_params(local, version=version)
        if getattr(h, "remote", False):
            raise RuntimeError(
                f"replica {h.name!r} is remote: the in-memory reshard "
                f"transport cannot reach another process — use "
                f"WeightPublisher(transport='dist_ckpt', ckpt_dir=...)")
        local = materialize_params(params, h.engine)
        reg.counter(
            "weight_push_bytes_total",
            "parameter bytes moved by fleet weight pushes, by "
            "transport (dist_ckpt counts bytes WRITTEN once per push "
            "— delta savings show here; reshard counts device-copy "
            "bytes per replica)").inc(
            sum(int(x.size) * x.dtype.itemsize
                for x in jax_tree_leaves(local)),
            transport="reshard")
        return h.engine.swap_params(local, version=version)

    def publish(self, state_or_params, *,
                version: Optional[int] = None) -> dict:
        """Push ``state_or_params`` (a TrainState or a bare param
        pytree) to every non-dead replica. Returns the push report
        (per-replica durations + flush counts)."""
        params = getattr(state_or_params, "params", state_or_params)
        t0 = time.perf_counter()
        # the push gets its own trace context, active for the whole
        # rolling walk: drain/swap flight events (and a concurrent
        # chaos kill) stamp it, so fleet_trace.py can pin a TTFT spike
        # on the push that caused it (ISSUE 16)
        push_tp = telemetry.make_traceparent(uuid.uuid4().hex[:12])
        with telemetry.use_trace(push_tp):
            return self._publish_traced(params, t0, push_tp,
                                        version=version)

    def _publish_traced(self, params, t0: float, push_tp: str, *,
                        version: Optional[int]) -> dict:
        reg = telemetry.get_registry()
        with self.router._lock:
            names = sorted(n for n, h in self.router._replicas.items()
                           if h.state != "dead")
            if version is None:
                version = max(
                    1 + max((self.router._replicas[n].weight_version
                             for n in names), default=0),
                    self._last_version + 1)
        self._last_version = max(self._last_version, int(version))
        path = None
        if self.transport == "dist_ckpt":
            path = self._publish_checkpoint(params, version, reg)
        per = []
        for name in names:
            h = self.router._replicas.get(name)
            if h is None or h.state == "dead":
                continue
            t1 = time.perf_counter()
            try:
                requeued = self.router.drain(
                    name, timeout_s=self.drain_timeout_s,
                    preempt=self.preempt)
            except TimeoutError:
                with self.router._lock:
                    self.router._mark_dead_locked(
                        h, reason="drain_timeout")
                per.append({"replica": name, "skipped": "drain_timeout"})
                continue
            info = self._swap_replica(h, params, path, version, reg)
            # the swap flushed the replica's version-stale prefix
            # cache; flush the ROUTER's directory view of it in the
            # same breath, so no peer pulls at superseded KV (the
            # engine-side compatible_with gate would refuse the entry
            # anyway — this keeps the directory honest, not just safe)
            self.router._directory.flush_stale(replica=name)
            self.router.resume(name)
            per.append({"replica": name, "requeued": requeued,
                        "flushed_blocks": info.get("flushed_blocks", 0),
                        "ms": round((time.perf_counter() - t1) * 1e3,
                                    3)})
        if path is not None:
            self._last_dir = path
        dur_ms = (time.perf_counter() - t0) * 1e3
        reg = telemetry.get_registry()
        reg.histogram("weight_push_duration_ms",
                      "one rolling fleet weight push, end to end "
                      "(drain + reshard + swap, all replicas)").observe(
            dur_ms)
        reg.counter("weight_pushes_total",
                    "rolling fleet weight pushes completed").inc()
        flight_record("weight_push", version=version,
                      replicas=len(per), ms=round(dur_ms, 3),
                      trace=push_tp)
        return {"version": version, "replicas": per,
                "duration_ms": round(dur_ms, 3), "trace": push_tp}

    # -- per-tenant adapter push (ISSUE 20) -----------------------------
    def publish_adapter(self, tenant: str, name: str, weights=None, *,
                        path: Optional[str] = None,
                        version: Optional[int] = None,
                        scaling: float = 1.0) -> dict:
        """Push one tenant's LoRA adapter to every non-dead replica
        WITHOUT draining anything: adapters hot-swap under live
        traffic (the engine registers the new version, flushes the
        superseded version's prefix spans, and in-flight requests
        pinning the old page finish on it untouched). The base weights
        — and every other tenant — are never disturbed. Pass
        ``weights`` (the in-memory pages dict) or ``path`` (a
        ``save_adapter_distributed`` directory each replica host can
        reach)."""
        t0 = time.perf_counter()
        with self.router._lock:
            names = sorted(n for n, h in self.router._replicas.items()
                           if h.state != "dead")
        per = []
        for rname in names:
            h = self.router._replicas.get(rname)
            if h is None or h.state == "dead":
                continue
            if getattr(h.engine, "tenancy", None) is None:
                per.append({"replica": rname, "skipped": "no_tenancy"})
                continue
            t1 = time.perf_counter()
            try:
                info = h.engine.load_adapter(
                    tenant, name, weights, path=path,
                    version=version, scaling=scaling)
            except Exception as err:  # replica-local failure: keep
                per.append({"replica": rname,     # walking the fleet
                            "skipped": f"{type(err).__name__}: {err}"})
                continue
            per.append({"replica": rname, "version": info["version"],
                        "uid": info["uid"],
                        "flushed_blocks": info["flushed_blocks"],
                        "ms": round((time.perf_counter() - t1) * 1e3,
                                    3)})
        dur_ms = (time.perf_counter() - t0) * 1e3
        telemetry.get_registry().counter(
            "adapter_pushes_total",
            "fleet-wide per-tenant adapter pushes completed (no "
            "drain — adapters hot-swap under live traffic)").inc()
        flight_record("adapter_push", tenant=tenant, adapter=name,
                      replicas=len(per), ms=round(dur_ms, 3))
        return {"tenant": tenant, "adapter": name, "replicas": per,
                "duration_ms": round(dur_ms, 3)}

    def evict_adapter(self, tenant: str, name: str) -> dict:
        """Deregister ``(tenant, name)`` fleet-wide: each replica drops
        the registry entry, frees its arena page once the last pinned
        request finishes, and flushes the adapter's prefix spans."""
        with self.router._lock:
            names = sorted(n for n, h in self.router._replicas.items()
                           if h.state != "dead")
        per = []
        for rname in names:
            h = self.router._replicas.get(rname)
            if h is None or h.state == "dead" \
                    or getattr(h.engine, "tenancy", None) is None:
                continue
            try:
                per.append({"replica": rname,
                            **h.engine.evict_adapter(tenant, name)})
            except KeyError:
                per.append({"replica": rname, "skipped": "unknown"})
        flight_record("adapter_evict_fleet", tenant=tenant,
                      adapter=name, replicas=len(per))
        return {"tenant": tenant, "adapter": name, "replicas": per}
