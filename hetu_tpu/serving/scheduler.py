"""Request lifecycle + FCFS slot scheduler for the serving engine.

Orca-style continuous batching (iteration-level scheduling, OSDI'22)
reduces, on the scheduling side, to a small amount of bookkeeping: a
FCFS queue, a free-slot list over the KV pool, and an admission gate
that answers one question — does this request's worst case
(``len(prompt) + max_tokens``) fit a slot? Everything dynamic
(admission, completion, eviction) is a host-side list operation; the
device only ever sees fixed-shape control vectors.

The scheduler is deliberately free of jax and telemetry: pure logic the
engine drives (and tests exercise without a device). Preemption is a
non-goal — admission guarantees a request admitted to a slot runs to
completion (no swapping, no recompute-on-resume), which is the right
trade for fixed-shape slots where eviction can't free partial bytes.
"""

from __future__ import annotations

import dataclasses
import threading
import time
import uuid
from collections import deque
from typing import Optional

import numpy as np

from hetu_tpu.models.generation import PromptTooLongError


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request decode knobs — traced per-slot operands in the engine
    step (so changing them across requests never recompiles)."""

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 0.0
    eos_id: Optional[int] = None
    max_tokens: int = 16


@dataclasses.dataclass
class Request:
    """One request's full lifecycle: queued → prefill → decode → done
    (or rejected at admission).

    ``trace_id`` + ``events`` make the lifecycle reconstructable after
    the fact: every phase transition appends ``(phase, ts_s, dur_s)``
    (``mark``), the engine renders them as a per-request Perfetto track,
    and :meth:`timing` folds them into the breakdown the ``RESULT``
    protocol verb returns."""

    id: int
    prompt: np.ndarray                 # (P,) int32
    sampling: SamplingParams
    submit_s: float
    status: str = "queued"
    slot: Optional[int] = None
    tokens: list = dataclasses.field(default_factory=list)
    first_token_s: Optional[float] = None
    finish_s: Optional[float] = None
    error: Optional[str] = None
    cached_tokens: int = 0             # prompt tokens served by the
    #                                    prefix cache (skipped prefill)
    cp_lane: bool = False              # admitted into the CP-prefill
    #                                    lane: worst case exceeds one
    #                                    slot's budget but fits the
    #                                    long_max_len lane — prefill
    #                                    runs cp-sharded in one pass
    #                                    instead of the packed chunk
    #                                    loop (docs/SERVING.md)
    weight_version: int = 0            # weight generation the request
    #                                    was admitted (and decoded) under
    #                                    — swaps only land on drained
    #                                    engines, so one request is one
    #                                    version, end to end
    admit: Optional[dict] = dataclasses.field(
        default=None, repr=False, compare=False)  # paged admission plan
    trace_id: str = dataclasses.field(
        default_factory=lambda: uuid.uuid4().hex[:12])
    events: list = dataclasses.field(default_factory=list,
                                     repr=False, compare=False)
    done: threading.Event = dataclasses.field(
        default_factory=threading.Event, repr=False, compare=False)

    def mark(self, phase: str, dur_s: float = 0.0,
             ts_s: Optional[float] = None) -> None:
        """Append one lifecycle event (``ts_s`` defaults to now; the
        clock is ``time.monotonic`` — the same one ``submit_s`` uses)."""
        self.events.append(
            (phase, time.monotonic() if ts_s is None else ts_s,
             float(dur_s)))

    def timing(self) -> dict:
        """Phase breakdown in milliseconds for the RESULT verb: queued
        (submit → admit), prefill (admit → first token), decode (first
        token → finish), total, plus per-prefill-chunk count."""
        out = {"trace_id": self.trace_id}
        admit_s = next((t for p, t, _ in self.events if p == "admit"),
                       None)
        if admit_s is not None:
            out["queued_ms"] = round((admit_s - self.submit_s) * 1e3, 3)
        if self.first_token_s is not None and admit_s is not None:
            out["prefill_ms"] = round(
                (self.first_token_s - admit_s) * 1e3, 3)
            out["ttft_ms"] = round(
                (self.first_token_s - self.submit_s) * 1e3, 3)
        if self.finish_s is not None and self.first_token_s is not None:
            out["decode_ms"] = round(
                (self.finish_s - self.first_token_s) * 1e3, 3)
        if self.finish_s is not None:
            out["total_ms"] = round(
                (self.finish_s - self.submit_s) * 1e3, 3)
        out["prefill_chunks"] = sum(
            1 for p, _, _ in self.events if p == "prefill_chunk")
        out["cached_tokens"] = self.cached_tokens
        return out

    def result(self) -> dict:
        return {"id": self.id, "status": self.status,
                "tokens": list(self.tokens), "error": self.error,
                "weight_version": self.weight_version,
                "timing": self.timing()}


class Scheduler:
    """FCFS admission over a fixed slot pool.

    ``max_len`` gating is the HBM-budget gate in disguise: the pool was
    sized so ``slots * max_len`` rows fit the budget
    (``engine.memory.size_kv_pool``), so "fits a slot" == "fits HBM".

    With a paged pool (``blocks=`` a BlockManager, ``block_size=``),
    admission moves from slot-count to FREE-BLOCK accounting: a request
    is admitted when a control slot is free AND its worst case fits in
    NEW blocks — where "new" is net of the prefix cache
    (``prefix_cache=``), so a full-prefix hit costs ~0 blocks and
    admits even into a nearly-full pool. When blocks run short the
    scheduler first LRU-evicts unpinned cache leaves; if still short,
    the head of the queue WAITS (head-of-line, preserving FCFS — a
    later cheaper request never jumps it, which is what keeps
    ``generate_many`` outputs in submission order under churn).
    """

    def __init__(self, slots: int, max_len: int, *, blocks=None,
                 prefix_cache=None, block_size: Optional[int] = None,
                 long_max_len: Optional[int] = None):
        self.slots = int(slots)
        self.max_len = int(max_len)
        #: CP-prefill lane budget: requests whose worst case exceeds
        #: one slot's max_len but fits here are admitted with
        #: ``cp_lane=True`` instead of rejected (engine runs their
        #: prefill as one cp-sharded pass). None = lane off (historical
        #: rejection behavior, now with a structured error).
        self.long_max_len = int(long_max_len) if long_max_len else None
        if self.long_max_len is not None \
                and self.long_max_len <= self.max_len:
            raise ValueError(
                f"long_max_len {self.long_max_len} must exceed the "
                f"per-slot max_len {self.max_len}")
        self.queue: deque[Request] = deque()
        self.free: list[int] = list(range(self.slots))
        self.blocks = blocks              # BlockManager | None (legacy)
        self.cache = prefix_cache         # PrefixCache | None
        self.block_size = int(block_size) if block_size else None
        self.evictions_total = 0          # host ledger (engine syncs
        #                                   the telemetry counter)

    # -- admission ----------------------------------------------------------
    def submit(self, req: Request) -> bool:
        """Queue ``req`` FCFS; False = rejected (can never fit a slot).

        Rejection carries a STRUCTURED :class:`PromptTooLongError`
        message naming the per-slot budget and — when the CP-prefill
        lane exists — its larger budget, so a caller knows which knob
        (max_len / long_max_len / max_tokens) would admit the request.
        """
        worst = len(req.prompt) + req.sampling.max_tokens
        limit = self.long_max_len or self.max_len
        if len(req.prompt) == 0:
            req.status, req.error = "rejected", "empty prompt"
        elif worst > limit:
            err = PromptTooLongError(
                prompt_len=len(req.prompt),
                max_tokens=req.sampling.max_tokens,
                limit=self.max_len, cp_limit=self.long_max_len,
                source="serving slot",
                hint="raise long_max_len (CP-prefill lane) or trim "
                     "the prompt" if self.long_max_len is not None
                else "pass long_max_len= to enable the CP-prefill "
                     "lane for prompts beyond one slot")
            req.status, req.error = "rejected", str(err)
        elif worst > self.max_len:
            # beyond one slot's budget but inside the lane: the engine
            # prefills it cp-sharded in one pass, decode is normal
            req.cp_lane = True
        if req.status == "rejected":
            req.done.set()
            return False
        req.mark("queued")
        self.queue.append(req)
        return True

    def next_admission(self) -> Optional[tuple[Request, int]]:
        """Pop the oldest queued request into a free slot, or None
        (no queue, no slot, or — paged — not enough free blocks even
        after cache eviction: the head waits).

        Paged pools attach the admission plan as ``req.admit``:
        ``{"table": [block ids], "first_uncached": int,
        "cow": (src, dst) | None}`` — blocks already allocated/shared,
        so the engine only maps them into control vectors."""
        if not self.queue or not self.free:
            return None
        req = self.queue[0]
        plan = None
        if self.blocks is not None:
            plan = self._page_plan(req)
            if plan is None:
                return None
        self.queue.popleft()
        slot = self.free.pop(0)
        req.slot = slot
        req.status = "prefill"
        req.admit = plan
        req.mark("admit")
        return req, slot

    def _page_plan(self, req: Request) -> Optional[dict]:
        """Price ``req`` in blocks net of the prefix cache, evicting
        LRU cache leaves if the free list is short; None = cannot fit
        yet. On success every table block is live (shared or freshly
        allocated) and charged to this request."""
        bs = self.block_size
        P = len(req.prompt)
        total = -(-(P + req.sampling.max_tokens) // bs)   # worst case
        shared: list[int] = []
        partial = None
        # CP-lane requests skip the prefix cache: their prefill is one
        # cp-sharded pass over the WHOLE prompt (a partial-skip offset
        # would re-shape the lane's bucketed executable), and they do
        # not insert on completion either — long-prompt prefix sharing
        # is future work (docs/SERVING.md)
        if self.cache is not None and not req.cp_lane:
            shared, partial = self.cache.match(req.prompt.tolist())
            shared = shared[:total]
        matched = len(shared) * bs + (partial[1] if partial else 0)
        # a FULL-prompt hit still recomputes the last token (its logits
        # seed decoding); the rewrite of position P-1 into a possibly
        # shared block is benign — same tokens, same values
        first_uncached = min(matched, P - 1)
        if partial is not None and first_uncached <= len(shared) * bs:
            partial = None                 # tail match buys nothing
            first_uncached = min(len(shared) * bs, P - 1)
        n_new = total - len(shared)        # incl. the CoW destination
        # pin the matched path BEFORE evicting: evict() reclaims any
        # refcount-1 trie leaf, and peeling a cached chain tail-first
        # can reach the very blocks we just matched — unpinned, they
        # would be freed (and possibly re-allocated) out from under
        # this request's table
        pins = list(shared)
        if partial is not None:
            pins.append(partial[0])
        for b in pins:
            self.blocks.share(b)
        if n_new > self.blocks.free_blocks and self.cache is not None:
            self.evictions_total += self.cache.evict(
                n_new - self.blocks.free_blocks)
        if n_new > self.blocks.free_blocks:
            for b in pins:                 # unwind; the trie ref remains
                self.blocks.release(b)
            return None
        fresh = [self.blocks.alloc() for _ in range(n_new)]
        if partial is not None:
            # the src pin only guarded eviction: the table never maps
            # the src (the engine copies it into fresh[0] this step)
            self.blocks.release(partial[0])
        table = shared + fresh
        cow = (partial[0], fresh[0]) if partial is not None else None
        req.cached_tokens = first_uncached
        return {"table": table, "first_uncached": first_uncached,
                "cow": cow}

    def release(self, slot: int, table=None) -> None:
        """Return a slot (and, paged, every block its table maps —
        shared blocks just drop a holder; blocks the prefix cache
        adopted at insert stay cached)."""
        self.free.append(slot)
        if self.blocks is not None and table is not None:
            for b in table:
                if b:
                    self.blocks.release(int(b))

    # -- introspection ------------------------------------------------------
    @property
    def depth(self) -> int:
        return len(self.queue)

    @property
    def occupancy(self) -> float:
        return 1.0 - len(self.free) / self.slots
