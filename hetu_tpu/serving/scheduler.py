"""Request lifecycle + FCFS slot scheduler for the serving engine.

Orca-style continuous batching (iteration-level scheduling, OSDI'22)
reduces, on the scheduling side, to a small amount of bookkeeping: a
FCFS queue, a free-slot list over the KV pool, and an admission gate
that answers one question — does this request's worst case
(``len(prompt) + max_tokens``) fit a slot? Everything dynamic
(admission, completion, eviction) is a host-side list operation; the
device only ever sees fixed-shape control vectors.

The scheduler is deliberately free of jax and telemetry: pure logic the
engine drives (and tests exercise without a device). Preemption is a
non-goal — admission guarantees a request admitted to a slot runs to
completion (no swapping, no recompute-on-resume), which is the right
trade for fixed-shape slots where eviction can't free partial bytes.
"""

from __future__ import annotations

import dataclasses
import threading
import time
import uuid
from collections import deque
from typing import Optional

import numpy as np

from hetu_tpu.models.generation import PromptTooLongError


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request decode knobs — traced per-slot operands in the engine
    step (so changing them across requests never recompiles).

    ``priority`` is the request's QoS class: LOWER is more urgent
    (0 = interactive, 1 = standard/default, 2+ = batch). Admission is
    deficit-weighted across classes (class ``c`` gets a ``2^-c`` share
    of admissions when everything is backlogged — urgent traffic goes
    first but batch traffic never starves), and a queued request may
    PREEMPT a running strictly-lower-priority one when slots or blocks
    run dry — the victim's KV spills to the host arena and resumes
    later without re-running prefill (docs/SERVING.md)."""

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 0.0
    eos_id: Optional[int] = None
    max_tokens: int = 16
    priority: int = 1
    #: multi-tenant serving (serving/tenancy.py): ``tenant`` names the
    #: request's QoS identity (token-bucket rate limits + slot caps at
    #: admission), ``adapter`` the tenant's LoRA adapter to decode
    #: under (None = the shared base model). Both are host-side
    #: routing/admission data — the traced step only ever sees the
    #: adapter's arena page id, so tenant churn never recompiles.
    tenant: Optional[str] = None
    adapter: Optional[str] = None
    #: per-request PRNG seed for sampled decoding (temperature > 0):
    #: the engine derives the slot's traced key stream from it, so a
    #: sampled run replays bit-for-bit — and matches one-shot
    #: ``generate(rng=jax.random.key(seed))``. None derives a stream
    #: from the engine seed + request id (reproducible per engine).
    seed: Optional[int] = None


@dataclasses.dataclass
class Request:
    """One request's full lifecycle: queued → prefill → decode → done
    (or rejected at admission).

    ``trace_id`` + ``events`` make the lifecycle reconstructable after
    the fact: every phase transition appends ``(phase, ts_s, dur_s)``
    (``mark``), the engine renders them as a per-request Perfetto track,
    and :meth:`timing` folds them into the breakdown the ``RESULT``
    protocol verb returns."""

    id: int
    prompt: np.ndarray                 # (P,) int32
    sampling: SamplingParams
    submit_s: float
    status: str = "queued"
    slot: Optional[int] = None
    tokens: list = dataclasses.field(default_factory=list)
    first_token_s: Optional[float] = None
    finish_s: Optional[float] = None
    error: Optional[str] = None
    cached_tokens: int = 0             # prompt tokens served by the
    #                                    prefix cache (skipped prefill)
    cp_lane: bool = False              # admitted into the CP-prefill
    #                                    lane: worst case exceeds one
    #                                    slot's budget but fits the
    #                                    long_max_len lane — prefill
    #                                    runs cp-sharded in one pass
    #                                    instead of the packed chunk
    #                                    loop (docs/SERVING.md)
    weight_version: int = 0            # weight generation the request
    #                                    was admitted (and decoded) under
    #                                    — swaps only land on drained
    #                                    engines, so one request is one
    #                                    version, end to end
    handoff: bool = False              # prefill-tier mode (ISSUE 15):
    #                                    the engine parks the request
    #                                    after its FIRST token (status
    #                                    "prefilled", slot inactive but
    #                                    owned) instead of decoding on —
    #                                    the fleet layer evicts its KV
    #                                    and streams it to a decode-tier
    #                                    replica (docs/SERVING.md)
    admit: Optional[dict] = dataclasses.field(
        default=None, repr=False, compare=False)  # paged admission plan
    # -- speculation + QoS ledgers (ISSUE 11) --
    drafted: int = 0                   # draft tokens this request saw
    accepted: int = 0                  # drafts the verify lane accepted
    preemptions: int = 0               # times evicted mid-decode
    spilled_blocks: int = 0            # KV blocks copied to the host
    #                                    spill arena across preemptions
    resumed_blocks: int = 0            # KV blocks mapped back on resume
    spill: Optional[object] = dataclasses.field(
        default=None, repr=False, compare=False)  # live SpillEntry while
    #                                    preempted/queued-for-resume —
    #                                    its presence is what routes
    #                                    admission through the resume
    #                                    path instead of prefill
    # -- multi-tenant adapter plane (serving/tenancy.py) --
    adapter_ref: Optional[object] = dataclasses.field(
        default=None, repr=False, compare=False)  # AdapterSpec pinned at
    #                                    admission (refcount held until
    #                                    finish — the arena page cannot
    #                                    be evicted under this request)
    kv_adapter: int = 0                # adapter KV-compat uid this
    #                                    request's KV is written under
    #                                    (0 = base-compatible): tags its
    #                                    prefix-cache inserts + spills
    #                                    and filters its prefix matches
    trace_id: str = dataclasses.field(
        default_factory=lambda: uuid.uuid4().hex[:12])
    traceparent: Optional[str] = dataclasses.field(
        default=None, repr=False, compare=False)  # inbound wire context
    #                                    ("<trace_id>-<span_id>", ISSUE
    #                                    16) — when set, trace_id above
    #                                    is overridden to match it so
    #                                    every process stamps the
    #                                    originating id
    events: list = dataclasses.field(default_factory=list,
                                     repr=False, compare=False)
    done: threading.Event = dataclasses.field(
        default_factory=threading.Event, repr=False, compare=False)

    def mark(self, phase: str, dur_s: float = 0.0,
             ts_s: Optional[float] = None) -> None:
        """Append one lifecycle event (``ts_s`` defaults to now; the
        clock is ``time.monotonic`` — the same one ``submit_s`` uses)."""
        self.events.append(
            (phase, time.monotonic() if ts_s is None else ts_s,
             float(dur_s)))

    def timing(self) -> dict:
        """Phase breakdown in milliseconds for the RESULT verb: queued
        (submit → admit), prefill (admit → first token), decode (first
        token → finish), total, plus per-prefill-chunk count."""
        out = {"trace_id": self.trace_id}
        admit_s = next((t for p, t, _ in self.events if p == "admit"),
                       None)
        if admit_s is not None:
            out["queued_ms"] = round((admit_s - self.submit_s) * 1e3, 3)
        if self.first_token_s is not None and admit_s is not None:
            out["prefill_ms"] = round(
                (self.first_token_s - admit_s) * 1e3, 3)
            out["ttft_ms"] = round(
                (self.first_token_s - self.submit_s) * 1e3, 3)
        if self.finish_s is not None and self.first_token_s is not None:
            out["decode_ms"] = round(
                (self.finish_s - self.first_token_s) * 1e3, 3)
        if self.finish_s is not None:
            out["total_ms"] = round(
                (self.finish_s - self.submit_s) * 1e3, 3)
        out["prefill_chunks"] = sum(
            1 for p, _, _ in self.events if p == "prefill_chunk")
        out["cached_tokens"] = self.cached_tokens
        # speculation + QoS breakdown (ISSUE 11): how many tokens the
        # draft plane proposed/landed for this request, and what the
        # scheduler did to it under pressure
        out["priority"] = self.sampling.priority
        out["drafted"] = self.drafted
        out["accepted"] = self.accepted
        out["preemptions"] = self.preemptions
        out["spilled_blocks"] = self.spilled_blocks
        out["resumed_blocks"] = self.resumed_blocks
        # the chaos-soak contract (ISSUE 18): a request recovered from
        # a buddy's replicated KV reports that it RESUMED mid-decode
        # rather than replaying the prompt — RESULT carries the proof
        out["resumed"] = any(p == "resumed" for p, _, _ in self.events)
        return out

    def result(self) -> dict:
        return {"id": self.id, "status": self.status,
                "tokens": list(self.tokens), "error": self.error,
                "weight_version": self.weight_version,
                "timing": self.timing()}


class Scheduler:
    """FCFS admission over a fixed slot pool.

    ``max_len`` gating is the HBM-budget gate in disguise: the pool was
    sized so ``slots * max_len`` rows fit the budget
    (``engine.memory.size_kv_pool``), so "fits a slot" == "fits HBM".

    With a paged pool (``blocks=`` a BlockManager, ``block_size=``),
    admission moves from slot-count to FREE-BLOCK accounting: a request
    is admitted when a control slot is free AND its worst case fits in
    NEW blocks — where "new" is net of the prefix cache
    (``prefix_cache=``), so a full-prefix hit costs ~0 blocks and
    admits even into a nearly-full pool. When blocks run short the
    scheduler first LRU-evicts unpinned cache leaves; if still short,
    the chosen head WAITS (head-of-line within its class — a later
    cheaper request never jumps it, which is what keeps
    ``generate_many`` outputs in submission order under churn).

    **QoS (ISSUE 11)**: admission is no longer pure FCFS.
    ``SamplingParams.priority`` names the request's class (lower = more
    urgent), and the scheduler runs deficit-weighted selection across
    the classes present in the queue: every selection round each
    backlogged class earns credits proportional to its weight
    (``2^-priority`` by default, override via ``class_weights=``), the
    richest class admits its OLDEST request and pays one credit. With a
    single class this degenerates to exact FCFS (the historical
    contract, relied on by ``generate_many``'s submission-order
    guarantee); with mixed classes, urgent traffic takes a ``2^Δ``
    share of admissions over batch traffic while the credit accrual
    makes starvation impossible. A request carrying a KV spill
    (``req.spill``) is priced and admitted through the RESUME path —
    fresh blocks, no prefill, no prefix-cache interaction.
    """

    def __init__(self, slots: int, max_len: int, *, blocks=None,
                 prefix_cache=None, block_size: Optional[int] = None,
                 long_max_len: Optional[int] = None,
                 class_weights: Optional[dict] = None):
        self.slots = int(slots)
        self.max_len = int(max_len)
        #: CP-prefill lane budget: requests whose worst case exceeds
        #: one slot's max_len but fits here are admitted with
        #: ``cp_lane=True`` instead of rejected (engine runs their
        #: prefill as one cp-sharded pass). None = lane off (historical
        #: rejection behavior, now with a structured error).
        self.long_max_len = int(long_max_len) if long_max_len else None
        if self.long_max_len is not None \
                and self.long_max_len <= self.max_len:
            raise ValueError(
                f"long_max_len {self.long_max_len} must exceed the "
                f"per-slot max_len {self.max_len}")
        self.queue: deque[Request] = deque()
        self.free: list[int] = list(range(self.slots))
        self.blocks = blocks              # BlockManager | None (legacy)
        self.cache = prefix_cache         # PrefixCache | None
        self.block_size = int(block_size) if block_size else None
        self.evictions_total = 0          # host ledger (engine syncs
        #                                   the telemetry counter)
        self.class_weights = dict(class_weights) if class_weights else {}
        self._credit: dict[int, float] = {}   # deficit counters by class
        self.preemptions_total = 0        # host ledger by-product
        #: optional per-request admission gate (the engine's tenant
        #: QoS hook, serving/tenancy.py): ``callable(req) -> bool``.
        #: False = the request is NOT eligible this round (rate-limited
        #: tenant, slot-capped tenant, adapter arena full) — the
        #: deficit selection simply skips it, so a throttled tenant's
        #: backlog never blocks other tenants' admissions (noisy-
        #: neighbor isolation), and never burns its class's credits.
        self.admission_gate = None

    # -- admission ----------------------------------------------------------
    def submit(self, req: Request) -> bool:
        """Queue ``req`` FCFS; False = rejected (can never fit a slot).

        Rejection carries a STRUCTURED :class:`PromptTooLongError`
        message naming the per-slot budget and — when the CP-prefill
        lane exists — its larger budget, so a caller knows which knob
        (max_len / long_max_len / max_tokens) would admit the request.
        """
        worst = len(req.prompt) + req.sampling.max_tokens
        limit = self.long_max_len or self.max_len
        if len(req.prompt) == 0:
            req.status, req.error = "rejected", "empty prompt"
        elif worst > limit:
            err = PromptTooLongError(
                prompt_len=len(req.prompt),
                max_tokens=req.sampling.max_tokens,
                limit=self.max_len, cp_limit=self.long_max_len,
                source="serving slot",
                hint="raise long_max_len (CP-prefill lane) or trim "
                     "the prompt" if self.long_max_len is not None
                else "pass long_max_len= to enable the CP-prefill "
                     "lane for prompts beyond one slot")
            req.status, req.error = "rejected", str(err)
        elif worst > self.max_len and req.spill is None:
            # beyond one slot's budget but inside the lane: the engine
            # prefills it cp-sharded in one pass, decode is normal.
            # (A resume request never re-routes through the lane — its
            # KV already exists; admission maps it back in.)
            req.cp_lane = True
        if req.status == "rejected":
            req.done.set()
            return False
        req.mark("queued")
        self.queue.append(req)
        return True

    def requeue_preempted(self, req: Request) -> None:
        """Put an evicted request back at the HEAD of the queue (it was
        already admitted once — it resumes before its class peers; the
        deficit selection still decides WHEN its class runs again)."""
        req.status = "preempted"
        self.queue.appendleft(req)

    # -- QoS class selection ------------------------------------------------
    def _weight(self, c: int) -> float:
        w = self.class_weights.get(c)
        if w is None:
            return 2.0 ** (-max(int(c), 0))
        # a zero/negative override would deadlock the credit accrual —
        # clamp to a tiny share instead (≈ "only when alone")
        return max(float(w), 1e-6)

    def _eligible(self) -> list:
        """The queue minus requests the admission gate defers (tenant
        rate limits / slot caps / adapter waits) — the population the
        deficit selection runs over this round."""
        if self.admission_gate is None:
            return list(self.queue)
        return [r for r in self.queue if self.admission_gate(r)]

    def _select_class(self, queue=None) -> tuple[
            Optional[int], Optional[dict]]:
        """Deficit-weighted pick among classes present in the queue
        (pure — commits nothing). Every backlogged class earns its
        weight per round until one can afford an admission (credit
        >= 1); richest wins, urgency breaks ties. Returns
        ``(class, credits-after-accrual)``."""
        if queue is None:
            queue = self.queue
        present = {r.sampling.priority for r in queue}
        if not present:
            return None, None
        eff = {c: self._credit.get(c, 0.0) for c in present}
        while max(eff.values()) < 1.0:
            for c in eff:
                eff[c] += self._weight(c)
        win = min(present, key=lambda c: (-eff[c], c))
        return win, eff

    def peek_candidate(self) -> Optional[Request]:
        """The request :meth:`next_admission` would try next (oldest of
        the deficit-selected class) — the engine's preemption planner
        asks this to decide whether a blocked urgent request justifies
        evicting a running batch one."""
        eligible = self._eligible()
        win, _ = self._select_class(eligible)
        if win is None:
            return None
        return next(r for r in eligible
                    if r.sampling.priority == win)

    def blocks_needed(self, req: Request) -> int:
        """Worst-case NEW blocks ``req`` needs (gross of prefix
        sharing — the preemption planner's conservative bound).
        Handoff requests decode elsewhere: a prefill-tier replica only
        ever writes the prompt + the first token before releasing the
        reservation, so price P+1 instead of P+max_tokens."""
        bs = self.block_size or self.max_len
        tail = 1 if req.handoff else req.sampling.max_tokens
        return -(-(len(req.prompt) + tail) // bs)

    def preemption_victim(self, candidate: Request,
                          running) -> Optional[int]:
        """Pick the slot to evict for ``candidate``: among running
        requests with STRICTLY lower priority (higher class number),
        the lowest-priority one, least-progressed first (fewest decoded
        tokens = fewest spilled bytes = least wasted work if it never
        resumes). ``running`` is ``[(slot, Request), ...]``; None = no
        eligible victim (equal-or-higher-priority work never preempts,
        so uniform-priority traffic keeps the historical run-to-
        completion guarantee)."""
        pc = candidate.sampling.priority
        victims = [(s, r) for s, r in running
                   if r.sampling.priority > pc]
        if not victims:
            return None
        slot, _ = max(victims, key=lambda sr: (
            sr[1].sampling.priority, -len(sr[1].tokens), sr[0]))
        return slot

    def next_admission(self) -> Optional[tuple[Request, int]]:
        """Pop the deficit-selected class's oldest request into a free
        slot, or None (no queue, no slot, or — paged — not enough free
        blocks even after cache eviction: the chosen head waits).

        Paged pools attach the admission plan as ``req.admit``:
        ``{"table": [block ids], "first_uncached": int,
        "cow": (src, dst) | None}`` — blocks already allocated/shared,
        so the engine only maps them into control vectors. A request
        carrying a KV spill instead gets
        ``{"table": ..., "resume": True, ...}``: all-fresh blocks the
        engine refills from the host arena (no prefill lane work)."""
        if not self.queue or not self.free:
            return None
        eligible = self._eligible()
        win, eff = self._select_class(eligible)
        if win is None:
            return None
        req = next(r for r in eligible
                   if r.sampling.priority == win)
        plan = None
        if self.blocks is not None:
            plan = self._resume_plan(req) if req.spill is not None \
                else self._page_plan(req)
            if plan is None:
                return None
        # commit the deficit round only on a real admission (a blocked
        # head must not burn its class's credits while it waits)
        self._credit = eff
        self._credit[win] -= 1.0
        self.queue.remove(req)
        slot = self.free.pop(0)
        req.slot = slot
        req.status = "resuming" if req.spill is not None else "prefill"
        req.admit = plan
        req.mark("admit")
        return req, slot

    def _resume_plan(self, req: Request) -> Optional[dict]:
        """Price a spill-resume: the full worst case in FRESH blocks
        (no prefix sharing — the spilled bytes are this request's own
        history and flow back from the host arena), evicting cache
        leaves if the free list is short. None = cannot fit yet."""
        total = self.blocks_needed(req)
        if total > self.blocks.free_blocks and self.cache is not None:
            self.evictions_total += self.cache.evict(
                total - self.blocks.free_blocks)
        if total > self.blocks.free_blocks:
            return None
        fresh = [self.blocks.alloc() for _ in range(total)]
        req.cached_tokens = 0
        return {"table": fresh, "first_uncached": 0, "cow": None,
                "resume": True}

    def _page_plan(self, req: Request) -> Optional[dict]:
        """Price ``req`` in blocks net of the prefix cache, evicting
        LRU cache leaves if the free list is short; None = cannot fit
        yet. On success every table block is live (shared or freshly
        allocated) and charged to this request."""
        bs = self.block_size
        P = len(req.prompt)
        # handoff requests never decode here: the prefill tier writes
        # the prompt + first token, ships the KV, and releases the
        # blocks — reserving max_tokens of decode room would only
        # throttle this tier's admission for space it never uses
        tail = 1 if req.handoff else req.sampling.max_tokens
        total = -(-(P + tail) // bs)                      # worst case
        shared: list[int] = []
        partial = None
        # CP-lane requests skip the prefix cache: their prefill is one
        # cp-sharded pass over the WHOLE prompt (a partial-skip offset
        # would re-shape the lane's bucketed executable), and they do
        # not insert on completion either — long-prompt prefix sharing
        # is future work (docs/SERVING.md)
        if self.cache is not None and not req.cp_lane:
            shared, partial = self.cache.match(req.prompt.tolist(),
                                               adapter=req.kv_adapter)
            shared = shared[:total]
        matched = len(shared) * bs + (partial[1] if partial else 0)
        # a FULL-prompt hit still recomputes the last token (its logits
        # seed decoding); the rewrite of position P-1 into a possibly
        # shared block is benign — same tokens, same values
        first_uncached = min(matched, P - 1)
        if partial is not None and first_uncached <= len(shared) * bs:
            partial = None                 # tail match buys nothing
            first_uncached = min(len(shared) * bs, P - 1)
        n_new = total - len(shared)        # incl. the CoW destination
        # pin the matched path BEFORE evicting: evict() reclaims any
        # refcount-1 trie leaf, and peeling a cached chain tail-first
        # can reach the very blocks we just matched — unpinned, they
        # would be freed (and possibly re-allocated) out from under
        # this request's table
        pins = list(shared)
        if partial is not None:
            pins.append(partial[0])
        for b in pins:
            self.blocks.share(b)
        if n_new > self.blocks.free_blocks and self.cache is not None:
            self.evictions_total += self.cache.evict(
                n_new - self.blocks.free_blocks)
        if n_new > self.blocks.free_blocks:
            for b in pins:                 # unwind; the trie ref remains
                self.blocks.release(b)
            return None
        fresh = [self.blocks.alloc() for _ in range(n_new)]
        if partial is not None:
            # the src pin only guarded eviction: the table never maps
            # the src (the engine copies it into fresh[0] this step)
            self.blocks.release(partial[0])
        table = shared + fresh
        cow = (partial[0], fresh[0]) if partial is not None else None
        req.cached_tokens = first_uncached
        return {"table": table, "first_uncached": first_uncached,
                "cow": cow}

    def release(self, slot: int, table=None) -> None:
        """Return a slot (and, paged, every block its table maps —
        shared blocks just drop a holder; blocks the prefix cache
        adopted at insert stay cached)."""
        self.free.append(slot)
        if self.blocks is not None and table is not None:
            for b in table:
                if b:
                    self.blocks.release(int(b))

    # -- introspection ------------------------------------------------------
    @property
    def depth(self) -> int:
        return len(self.queue)

    @property
    def occupancy(self) -> float:
        return 1.0 - len(self.free) / self.slots
