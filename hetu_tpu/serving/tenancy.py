"""Multi-tenant adapter serving plane: host-side state.

Many tenants share one base model; each tenant's LoRA adapters live in
a device-resident *arena* of fixed-shape pages so the fused serving
step never retraces when adapters load, evict, or mix within a batch
(Punica / S-LoRA shaped batched-gather LoRA, "BGMV").  This module is
the host half of that plane:

* :class:`AdapterRegistry` — tenant → (adapter name, version) → arena
  page.  Pages are refcounted by admitted requests and evicted LRU
  among idle pages; every load gets a fresh monotonic *uid* so a stale
  version can never alias a reused page (the same trick
  ``PrefixCache`` / ``SpillEntry`` play with weight versions).
* :class:`TenantQoS` — per-tenant token-bucket rate limits and
  concurrent-slot caps, enforced at admission on the deficit
  scheduler so a noisy neighbour cannot starve other tenants' TTFT.
* :class:`TenantPlane` — the facade the engine mounts (registry + QoS
  + per-tenant ledgers).
* :func:`extract_adapter` / :func:`save_adapter_distributed` /
  :func:`load_adapter_distributed` — pull stacked per-layer A/B pages
  out of a ``peft.lora``-wrapped param tree and move them over the
  existing dist-ckpt transport.

Everything here is plain numpy + bookkeeping; the engine owns the
device arena and rewrites pages via ``registry.on_page_write``.

Page 0 is the base model: an all-zero page whose delta is exactly
``0.0``, so adapter id 0 decodes bitwise identical to a build without
tenancy.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from hetu_tpu import telemetry
from hetu_tpu.telemetry.flight import flight_record

# Projections whose adapters perturb the written KV (q/k/v write the
# cache directly; out_proj changes this block's output and therefore
# every later block's K/V).  fc_*/gate/up adapters change hidden
# states too, but only *after* the first block — the registry treats
# MLP-only adapters as base-KV-compatible by default (the S-LoRA
# sharing rule from the issue) and exposes ``mlp_shares_base_prefix``
# to turn that off for exact multi-layer prefix semantics.
ATTN_TARGETS = frozenset({"q_proj", "k_proj", "v_proj", "out_proj"})
MLP_TARGETS = frozenset({"fc_in", "fc_out", "gate_proj", "up_proj"})
DEFAULT_TARGETS = ("q_proj", "k_proj", "v_proj", "out_proj",
                   "fc_in", "fc_out", "gate_proj", "up_proj")

_ADAPTER_MANIFEST = "adapter.json"


class AdapterArenaFull(RuntimeError):
    """Every arena page is pinned by in-flight requests; the request
    must wait at admission (loud flight event) instead of failing."""


@dataclasses.dataclass
class AdapterSpec:
    """One loaded (tenant, name, version) adapter.

    ``weights`` maps projection name → ``{"A": (L, in, r), "B":
    (L, r, out)}`` float32 host arrays, already padded to the arena
    rank and with the LoRA scaling folded into B, so the device lane
    is a pure pair of einsums with no per-adapter scalars.
    """
    tenant: str
    name: str
    version: int
    uid: int
    r: int
    targets: Tuple[str, ...]
    weights: Dict[str, Dict[str, np.ndarray]]
    page: Optional[int] = None
    refs: int = 0
    last_use: float = 0.0
    stale: bool = False

    @property
    def attention_targeting(self) -> bool:
        return bool(set(self.targets) & ATTN_TARGETS)

    @property
    def key(self) -> Tuple[str, str]:
        return (self.tenant, self.name)


class AdapterRegistry:
    """Tenant → adapter → arena page, with refcounted LRU eviction.

    The registry only does bookkeeping over host mirrors; whenever a
    page's contents change it calls ``on_page_write(page, spec_or_None)``
    so the owner (the engine) can rewrite the device arena slice with
    ``.at[:, page].set(...)`` — shapes never change, so the fused step
    never retraces.
    """

    def __init__(self, *, max_adapters: int = 8, r: int = 8,
                 mlp_shares_base_prefix: bool = True,
                 clock: Callable[[], float] = time.monotonic):
        if max_adapters < 2:
            raise ValueError("max_adapters must be >= 2 "
                             "(page 0 is reserved for the base model)")
        if r < 1:
            raise ValueError("adapter rank must be >= 1")
        self.max_adapters = int(max_adapters)
        self.r = int(r)
        self.mlp_shares_base_prefix = bool(mlp_shares_base_prefix)
        self._clock = clock
        self._lock = threading.RLock()
        # Latest version per (tenant, name); stale versions leave this
        # map but stay in _resident until their refs drain.
        self._store: Dict[Tuple[str, str], AdapterSpec] = {}
        self._resident: Dict[int, AdapterSpec] = {}
        self._free = set(range(1, self.max_adapters))
        self._next_uid = 1
        self.on_page_write = None  # callable(page, spec | None)

    # -- registration ------------------------------------------------

    def register(self, tenant: str, name: str,
                 weights: Dict[str, Dict[str, np.ndarray]], *,
                 version: Optional[int] = None,
                 scaling: float = 1.0) -> AdapterSpec:
        """Install (or replace) a tenant's adapter.

        ``weights``: projection → ``{"A": (L, in, ra), "B":
        (L, ra, out)}``; ``ra`` may be smaller than the arena rank
        (zero-padded — mathematically exact) but never larger.
        Replacing an existing (tenant, name) marks the old version
        stale: a resident idle page is flushed immediately, a pinned
        page drains when its last in-flight request releases.  The new
        version gets a fresh uid, so version-tagged caches can never
        serve the old weights.
        """
        folded = self._fold(weights, scaling)
        with self._lock:
            prev = self._store.get((tenant, name))
            if version is None:
                version = prev.version + 1 if prev is not None else 1
            spec = AdapterSpec(
                tenant=tenant, name=name, version=int(version),
                uid=self._next_uid, r=self.r,
                targets=tuple(sorted(folded)), weights=folded)
            self._next_uid += 1
            if prev is not None:
                self._retire_locked(prev)
            self._store[(tenant, name)] = spec
            flight_record("adapter_register", tenant=tenant, name=name,
                          version=spec.version, uid=spec.uid,
                          targets=list(spec.targets))
            return spec

    def deregister(self, tenant: str, name: str) -> None:
        with self._lock:
            prev = self._store.pop((tenant, name), None)
            if prev is not None:
                self._retire_locked(prev)

    def _retire_locked(self, spec: AdapterSpec) -> None:
        spec.stale = True
        if spec.page is not None and spec.refs == 0:
            self._evict_locked(spec)

    def _fold(self, weights, scaling):
        folded: Dict[str, Dict[str, np.ndarray]] = {}
        if not weights:
            raise ValueError("adapter has no LoRA-bearing projections")
        for proj, ab in sorted(weights.items()):
            a = np.asarray(ab["A"], dtype=np.float32)
            b = np.asarray(ab["B"], dtype=np.float32)
            if a.ndim != 3 or b.ndim != 3:
                raise ValueError(
                    f"{proj}: expected stacked (layers, in, r)/(layers,"
                    f" r, out) arrays, got {a.shape} / {b.shape}")
            ra = a.shape[-1]
            if ra != b.shape[1] or a.shape[0] != b.shape[0]:
                raise ValueError(f"{proj}: A {a.shape} and B {b.shape} "
                                 "disagree on rank or layer count")
            if ra > self.r:
                raise ValueError(
                    f"{proj}: adapter rank {ra} exceeds arena rank "
                    f"{self.r}")
            if ra < self.r:  # zero-pad to arena rank — exact
                a = np.concatenate(
                    [a, np.zeros(a.shape[:2] + (self.r - ra,),
                                 np.float32)], axis=-1)
                b = np.concatenate(
                    [b, np.zeros((b.shape[0], self.r - ra, b.shape[2]),
                                 np.float32)], axis=1)
            folded[proj] = {"A": a, "B": b * np.float32(scaling)}
        return folded

    # -- residency ---------------------------------------------------

    def get(self, tenant: str, name: str) -> AdapterSpec:
        with self._lock:
            spec = self._store.get((tenant, name))
            if spec is None:
                raise KeyError(f"unknown adapter {tenant}/{name}")
            return spec

    def has(self, tenant: str, name: str) -> bool:
        with self._lock:
            return (tenant, name) in self._store

    def resident(self, tenant: str, name: str) -> bool:
        """True when the latest version is already on an arena page —
        the router's adapter-affinity signal."""
        with self._lock:
            spec = self._store.get((tenant, name))
            return spec is not None and spec.page is not None

    def ensure_resident(self, tenant: str, name: str) -> AdapterSpec:
        """Give the latest (tenant, name) an arena page, evicting an
        idle LRU page if needed.  Raises :class:`AdapterArenaFull`
        when every page is pinned by in-flight requests."""
        with self._lock:
            spec = self.get(tenant, name)
            if spec.page is None:
                self._load_locked(spec)
            return spec

    def can_load(self) -> bool:
        """True when :meth:`ensure_resident` of a non-resident adapter
        would succeed right now: a free page exists, or an idle
        (refs == 0) resident can be evicted.  The engine's admission
        gate defers adapter requests while this is False instead of
        letting admission hit :class:`AdapterArenaFull`."""
        with self._lock:
            return bool(self._free) \
                or self._lru_idle_locked() is not None

    def acquire(self, tenant: str, name: str) -> AdapterSpec:
        """Admission-side pin: make resident and take a reference."""
        with self._lock:
            spec = self.ensure_resident(tenant, name)
            spec.refs += 1
            spec.last_use = self._clock()
            return spec

    def release(self, spec: AdapterSpec) -> None:
        with self._lock:
            spec.refs = max(0, spec.refs - 1)
            spec.last_use = self._clock()
            if spec.stale and spec.refs == 0 and spec.page is not None:
                self._evict_locked(spec)

    def _load_locked(self, spec: AdapterSpec) -> None:
        if self._free:
            page = min(self._free)
            self._free.discard(page)
        else:
            victim = self._lru_idle_locked()
            if victim is None:
                raise AdapterArenaFull(
                    f"all {self.max_adapters - 1} adapter pages are "
                    "pinned by in-flight requests")
            page = victim.page
            self._evict_locked(victim)
            self._free.discard(page)
        spec.page = page
        spec.last_use = self._clock()
        self._resident[page] = spec
        telemetry.get_registry().counter(
            "adapter_loads_total", "adapter arena page loads").inc()
        flight_record("adapter_load", tenant=spec.tenant,
                      name=spec.name, version=spec.version,
                      uid=spec.uid, page=page)
        if self.on_page_write is not None:
            self.on_page_write(page, spec)

    def _lru_idle_locked(self) -> Optional[AdapterSpec]:
        idle = [s for s in self._resident.values() if s.refs == 0]
        if not idle:
            return None
        return min(idle, key=lambda s: s.last_use)

    def _evict_locked(self, spec: AdapterSpec) -> None:
        page = spec.page
        if page is None:
            return
        self._resident.pop(page, None)
        self._free.add(page)
        spec.page = None
        telemetry.get_registry().counter(
            "adapter_evictions_total", "adapter arena page evictions").inc()
        flight_record("adapter_evict", tenant=spec.tenant,
                      name=spec.name, version=spec.version,
                      uid=spec.uid, page=page)
        if self.on_page_write is not None:
            self.on_page_write(page, None)

    # -- cache-compat tags -------------------------------------------

    def kv_tag(self, spec: Optional[AdapterSpec]) -> int:
        """The adapter id that written-KV spans carry for prefix/spill
        compatibility.  0 = base-compatible."""
        if spec is None:
            return 0
        if not spec.attention_targeting and self.mlp_shares_base_prefix:
            return 0
        return spec.uid

    # -- introspection ----------------------------------------------

    @property
    def pages_in_use(self) -> int:
        with self._lock:
            return len(self._resident)

    def stats(self) -> dict:
        with self._lock:
            return {
                "adapters": len(self._store),
                "pages_in_use": len(self._resident),
                "pages_free": len(self._free),
                "pages_total": self.max_adapters - 1,
                "pinned": sum(1 for s in self._resident.values()
                              if s.refs > 0),
            }


# -- per-tenant QoS ---------------------------------------------------

@dataclasses.dataclass
class TenantPolicy:
    """Admission policy for one tenant.  ``rate`` is a token-bucket
    refill in requests/second (None = unlimited) with depth ``burst``
    (defaults to max(1, ceil(rate))); ``max_slots`` caps concurrently
    admitted decode slots."""
    rate: Optional[float] = None
    burst: Optional[int] = None
    max_slots: Optional[int] = None

    def bucket_depth(self) -> float:
        if self.burst is not None:
            return float(max(1, self.burst))
        if self.rate is not None:
            return float(max(1.0, float(np.ceil(self.rate))))
        return float("inf")


class TenantQoS:
    """Token-bucket rate limits + concurrent-slot caps per tenant,
    checked at admission on the deficit scheduler.  Tenants without a
    policy (and the anonymous base tenant) are unlimited."""

    def __init__(self, policies: Optional[Dict[str, TenantPolicy]] = None,
                 *, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._policies: Dict[str, TenantPolicy] = dict(policies or {})
        # tenant -> [tokens, last_refill_ts, active_slots]
        self._state: Dict[str, list] = {}

    def configure(self, tenant: str, *, rate: Optional[float] = None,
                  burst: Optional[int] = None,
                  max_slots: Optional[int] = None) -> None:
        with self._lock:
            self._policies[tenant] = TenantPolicy(
                rate=rate, burst=burst, max_slots=max_slots)
            self._state.pop(tenant, None)

    def policy(self, tenant: Optional[str]) -> Optional[TenantPolicy]:
        if tenant is None:
            return None
        with self._lock:
            return self._policies.get(tenant)

    def _bucket_locked(self, tenant: str, pol: TenantPolicy) -> list:
        st = self._state.get(tenant)
        now = self._clock()
        if st is None:
            st = self._state[tenant] = [pol.bucket_depth(), now, 0]
        elif pol.rate is not None:
            depth = pol.bucket_depth()
            st[0] = min(depth, st[0] + (now - st[1]) * pol.rate)
            st[1] = now
        return st

    def check(self, tenant: Optional[str]) -> Optional[str]:
        """None when the tenant may admit one more request now, else
        the throttle reason ("rate" | "slots").  Does not consume."""
        pol = self.policy(tenant)
        if pol is None:
            return None
        with self._lock:
            st = self._bucket_locked(tenant, pol)
            if pol.max_slots is not None and st[2] >= pol.max_slots:
                return "slots"
            if pol.rate is not None and st[0] < 1.0:
                return "rate"
            return None

    def on_admit(self, tenant: Optional[str]) -> None:
        pol = self.policy(tenant)
        if pol is None:
            return
        with self._lock:
            st = self._bucket_locked(tenant, pol)
            if pol.rate is not None:
                st[0] = max(0.0, st[0] - 1.0)
            st[2] += 1

    def on_finish(self, tenant: Optional[str]) -> None:
        pol = self.policy(tenant)
        if pol is None:
            return
        with self._lock:
            st = self._bucket_locked(tenant, pol)
            st[2] = max(0, st[2] - 1)

    def active_slots(self, tenant: str) -> int:
        with self._lock:
            st = self._state.get(tenant)
            return 0 if st is None else int(st[2])


class TenantPlane:
    """The facade a :class:`~hetu_tpu.serving.engine.ServingEngine`
    mounts when tenancy is on: adapter registry + QoS + ledgers."""

    def __init__(self, registry: Optional[AdapterRegistry] = None,
                 qos: Optional[TenantQoS] = None, *,
                 max_adapters: int = 8, r: int = 8,
                 mlp_shares_base_prefix: bool = True):
        self.registry = registry if registry is not None else \
            AdapterRegistry(max_adapters=max_adapters, r=r,
                            mlp_shares_base_prefix=mlp_shares_base_prefix)
        self.qos = qos if qos is not None else TenantQoS()

    @property
    def max_adapters(self) -> int:
        return self.registry.max_adapters

    @property
    def r(self) -> int:
        return self.registry.r


# -- adapter extraction / dist-ckpt transport -------------------------

def lora_scaling(model) -> float:
    """alpha/r of the first LoRA layer in ``model`` (the scaling
    :func:`~hetu_tpu.peft.lora.merge_lora` applies)."""
    from ..peft.lora import _first_lora_scaling
    return _first_lora_scaling(model)


def extract_adapter(params, *, task_id: int = 0):
    """Pull one task's stacked A/B pages out of a
    ``wrap_params_for_lora``-shaped param tree.

    Returns projection → ``{"A": (L, in, r), "B": (L, r, out)}`` host
    arrays, ready for :meth:`AdapterRegistry.register` (pass the
    model's ``lora_scaling`` so merge parity holds).
    """
    out: Dict[str, Dict[str, np.ndarray]] = {}
    blocks = params.get("blocks", {})
    for group in ("attn", "mlp"):
        sub = blocks.get(group)
        if not isinstance(sub, dict):
            continue
        for proj, node in sub.items():
            if not (isinstance(node, dict) and "lora_A" in node):
                continue
            a = np.asarray(node["lora_A"], dtype=np.float32)
            b = np.asarray(node["lora_B"], dtype=np.float32)
            if a.ndim == 4:  # (layers, tasks, in, r)
                a, b = a[:, task_id], b[:, task_id]
            elif a.ndim == 3:  # unstacked (tasks, in, r): single layer
                a, b = a[None, task_id], b[None, task_id]
            out[proj] = {"A": a, "B": b}
    if not out:
        raise ValueError("params carry no lora_A/lora_B leaves — "
                         "inject_lora + wrap_params_for_lora first")
    return out


class _AdapterTreeModel:
    """Duck model for :func:`load_params_distributed`: exposes the
    saved adapter's abstract structure from the sidecar manifest."""

    def __init__(self, manifest: dict):
        self._m = manifest

    def abstract_params(self):
        import jax
        return {
            proj: {k: jax.ShapeDtypeStruct(tuple(v["shape"]),
                                           np.dtype(v["dtype"]))
                   for k, v in sorted(ab.items())}
            for proj, ab in sorted(self._m["projections"].items())
        }


def save_adapter_distributed(path: str, weights, *, version: int = 1,
                             scaling: float = 1.0) -> str:
    """Persist an adapter over the dist-ckpt transport (same sharded
    piece layout the base weight push uses) plus a tiny manifest so
    the loader needs no model."""
    from ..utils.dist_checkpoint import save_params_distributed
    tree = {proj: {"A": np.asarray(ab["A"], np.float32),
                   "B": np.asarray(ab["B"], np.float32)}
            for proj, ab in sorted(weights.items())}
    save_params_distributed(path, tree, version=version).wait()
    manifest = {
        "version": int(version),
        "scaling": float(scaling),
        "projections": {
            proj: {k: {"shape": list(v.shape), "dtype": str(v.dtype)}
                   for k, v in ab.items()}
            for proj, ab in tree.items()},
    }
    tmp = os.path.join(path, _ADAPTER_MANIFEST + ".tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f)
    os.replace(tmp, os.path.join(path, _ADAPTER_MANIFEST))
    return path


def load_adapter_distributed(path: str):
    """Load an adapter saved by :func:`save_adapter_distributed`.
    Returns ``(weights, version, scaling)``."""
    from ..utils.dist_checkpoint import load_params_distributed
    with open(os.path.join(path, _ADAPTER_MANIFEST)) as f:
        manifest = json.load(f)
    tree = load_params_distributed(path, _AdapterTreeModel(manifest))
    weights = {proj: {"A": np.asarray(ab["A"]),
                      "B": np.asarray(ab["B"])}
               for proj, ab in tree.items()}
    return weights, int(manifest["version"]), float(manifest["scaling"])
