"""Serving plane: continuous-batching inference over a block-paged KV
cache with radix-tree prefix sharing.

- :mod:`~hetu_tpu.serving.kv_pool` — the paged KV arena
  (``(layers, n_blocks, block_size, hkv, d)``), the refcounting
  :class:`BlockManager`, and sizing from the memory-plane ledger;
- :mod:`~hetu_tpu.serving.prefix_cache` — the radix-tree prompt-prefix
  cache (whole-block sharing, CoW partial tails, LRU leaf eviction);
- :mod:`~hetu_tpu.serving.engine` — the jit-once fused step (packed
  multi-request prefill + all-slot decode through block tables,
  per-slot SamplingParams as traced operands) and the
  :class:`ServingEngine` host loop;
- :mod:`~hetu_tpu.serving.scheduler` — FCFS admission, cache-aware
  free-block gating, completion/eviction;
- :mod:`~hetu_tpu.serving.server` — the line-protocol front end over
  ``rpc/py_server.py`` plus payload codecs.

``docs/SERVING.md`` documents the architecture and block lifecycle.
"""

from hetu_tpu.serving.engine import ServingEngine, sample_slots
from hetu_tpu.serving.kv_pool import (
    NULL_BLOCK, BlockManager, KVPool, cache_dtype_name,
)
from hetu_tpu.serving.prefix_cache import PrefixCache
from hetu_tpu.serving.scheduler import Request, SamplingParams, Scheduler

__all__ = [
    "ServingEngine", "sample_slots",
    "KVPool", "BlockManager", "NULL_BLOCK", "cache_dtype_name",
    "PrefixCache",
    "Request", "SamplingParams", "Scheduler",
]
