"""Serving plane: continuous-batching inference over a block-paged KV
cache with radix-tree prefix sharing.

- :mod:`~hetu_tpu.serving.kv_pool` — the paged KV arena
  (``(layers, n_blocks, block_size, hkv, d)``), the refcounting
  :class:`BlockManager`, and sizing from the memory-plane ledger;
- :mod:`~hetu_tpu.serving.prefix_cache` — the radix-tree prompt-prefix
  cache (whole-block sharing, CoW partial tails, LRU leaf eviction);
- :mod:`~hetu_tpu.serving.engine` — the jit-once fused step (packed
  multi-request prefill + all-slot decode through block tables,
  per-slot SamplingParams as traced operands) and the
  :class:`ServingEngine` host loop;
- :mod:`~hetu_tpu.serving.scheduler` — priority-class admission
  (deficit-weighted fairness; exact FCFS for single-class traffic),
  cache-aware free-block gating, completion/eviction, and resumable
  preemption planning;
- :mod:`~hetu_tpu.serving.speculative` — the draft plane for
  speculative decoding (self-drafting n-gram/prompt-lookup, optional
  small-model draftsman) behind ``ServingEngine(spec_depth=k)``;
- :mod:`~hetu_tpu.serving.server` — the line-protocol front end over
  ``rpc/py_server.py`` plus payload codecs;
- :mod:`~hetu_tpu.serving.router` — the FLEET plane: load-aware +
  prefix-sticky dispatch over N replicas, drain/death requeue, and the
  :class:`WeightPublisher` live train→serve weight push (rolling
  drain → swap → resume through the HotSPa reshard core, or — for
  multi-process fleets — the ``dist_ckpt`` sharded-checkpoint
  transport);
- :mod:`~hetu_tpu.serving.fleet` — the MULTI-PROCESS rung: remote
  replicas driven through coordinator verbs (heartbeat-staleness death
  detection, idempotency-keyed submission), prefill/decode
  disaggregation roles, the KV-block wire format, and the engine
  process entry point (``python -m hetu_tpu.serving.fleet``).

``docs/SERVING.md`` documents the architecture, block lifecycle, and
the fleet state machines.
"""

from hetu_tpu.serving.engine import ServingEngine, sample_slots
from hetu_tpu.serving.fleet import (
    RemoteEngineProxy, RemoteReplicaHandle, RemoteRequest,
    spill_from_wire, spill_to_wire,
)
from hetu_tpu.serving.kv_pool import (
    NULL_BLOCK, BlockManager, HostSpillArena, KVPool, SpillEntry,
    cache_dtype_name,
)
from hetu_tpu.serving.prefix_cache import PrefixCache
from hetu_tpu.serving.router import (
    ReplicaHandle, Router, RouterRequest, WeightPublisher,
    materialize_params,
)
from hetu_tpu.serving.scheduler import (
    PromptTooLongError, Request, SamplingParams, Scheduler,
)
from hetu_tpu.serving.speculative import (
    ModelDraftsman, NgramDraftsman, SpeculativeConfigError,
)

__all__ = [
    "ServingEngine", "sample_slots",
    "KVPool", "BlockManager", "NULL_BLOCK", "cache_dtype_name",
    "HostSpillArena", "SpillEntry",
    "PrefixCache",
    "Request", "SamplingParams", "Scheduler", "PromptTooLongError",
    "NgramDraftsman", "ModelDraftsman", "SpeculativeConfigError",
    "Router", "RouterRequest", "ReplicaHandle", "WeightPublisher",
    "materialize_params",
    "RemoteEngineProxy", "RemoteReplicaHandle", "RemoteRequest",
    "spill_to_wire", "spill_from_wire",
]
