"""Serving plane: continuous-batching inference over a slot-pooled KV
cache.

- :mod:`~hetu_tpu.serving.kv_pool` — the fixed-shape KV arena + sizing
  from the memory-plane ledger;
- :mod:`~hetu_tpu.serving.engine` — the jit-once fused step (chunked
  prefill + all-slot decode, per-slot SamplingParams as traced
  operands) and the :class:`ServingEngine` host loop;
- :mod:`~hetu_tpu.serving.scheduler` — FCFS admission, slot gating,
  completion/eviction;
- :mod:`~hetu_tpu.serving.server` — the line-protocol front end over
  ``rpc/py_server.py`` plus payload codecs.

``docs/SERVING.md`` documents the architecture and slot lifecycle.
"""

from hetu_tpu.serving.engine import ServingEngine, sample_slots
from hetu_tpu.serving.kv_pool import KVPool, cache_dtype_name
from hetu_tpu.serving.scheduler import Request, SamplingParams, Scheduler

__all__ = [
    "ServingEngine", "sample_slots",
    "KVPool", "cache_dtype_name",
    "Request", "SamplingParams", "Scheduler",
]
