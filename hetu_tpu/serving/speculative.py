"""Speculative-decoding draft plane: who proposes the k tokens the
fused serving step verifies.

Decode emits one token per active slot per fused-step iteration, so at
production TPOT targets most of each step's FLOPs sit idle — the
memory-bound decode wall speculative decoding (Leviathan et al., "Fast
Inference from Transformers via Speculative Decoding") climbs by
verifying k DRAFTED tokens in one forward pass. The serving engine's
verify lane (``ServingEngine(spec_depth=k)``) does the checking; this
module is where drafts come from:

- :class:`NgramDraftsman` — self-drafting prompt-lookup (Saxena,
  "Prompt Lookup Decoding" / LLMA): a host-side per-slot suffix index
  over the request's OWN tokens (prompt + emitted). The last n-gram is
  looked up in the history; if it occurred before, the tokens that
  followed it are the draft. No second model, no device work, and on
  the repetitive traffic real serving sees (code edits, RAG quoting
  its context, multi-turn echoes) acceptance is high exactly when the
  tokens were cheapest to predict;
- :class:`ModelDraftsman` — the small-model path through the existing
  model zoo (a tiny GPT drafting for a Llama, etc.): the draft model
  keeps its own per-slot KV arena and ONE jitted step per iteration
  first *catches up* on the tokens the target committed last iteration
  (a ``(S, k+1)``-wide masked window — no separate prefill lane: a
  fresh slot warms up over its first ``ceil(P/(k+1))`` iterations,
  drafting meanwhile disabled for it), then greedily drafts k tokens.
  Draft KV for rejected tokens is overwritten by the next catch-up
  before anything attends it, the same rewind discipline the target
  arena uses.

Both draftsmen are PROPOSERS only: the engine's verify lane accepts a
draft token iff the rejection-sampling test passes — at temperature 0
that reduces to "equals what sequential greedy decode would have
emitted"; at temperature > 0 the Leviathan et al. correction accepts a
draft with probability ``min(1, p_target/q_draft)`` and resamples the
first rejection from the normalized residual ``max(0, p - q)``, which
provably preserves the target sampler's output distribution. Either
way a bad draftsman can only cost speed, never correctness
(``docs/SERVING.md`` — "Speculation + QoS" / "Sampled speculation").

To support the sampled lane, draftsmen surface per-token proposal
probabilities ``q`` (``surfaces_q = True``): :class:`NgramDraftsman`
proposals are deterministic so their q is a degenerate one-hot (the
engine synthesizes it on-device); :class:`ModelDraftsman` SAMPLES its
draft chain from its own adjusted softmax at the request's knobs and
returns those rows — drafts must be distributed ~q for the accept
test to be exact.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


def adjust_logits(logits, temperature, top_k, top_p):
    """Apply the serving sampler's temperature/top-k/top-p masking to
    ``logits`` (..., V) and return the masked, scaled logits.

    This is the single source of truth for BOTH the fused verify lane's
    target distribution p and the draft models' proposal distribution q
    — bitwise identical arithmetic to the engine's ``sample_slots`` (and
    value-identical to ``generation._sample``), so a sampled serving
    token drawn from these logits matches the one-shot reference.

    ``temperature``/``top_k``/``top_p`` are traced scalars or arrays
    broadcastable against the leading dims of ``logits`` — knob churn
    is DATA, never a recompile."""
    import jax
    import jax.numpy as jnp

    V = logits.shape[-1]
    temperature = jnp.asarray(temperature, jnp.float32)
    top_k = jnp.asarray(top_k, jnp.int32)
    top_p = jnp.asarray(top_p, jnp.float32)
    t = jnp.where(temperature > 0, temperature, 1.0)
    scaled = logits / t[..., None].astype(logits.dtype)
    sorted_desc = jnp.sort(scaled, axis=-1)[..., ::-1]
    kth = jnp.take_along_axis(
        sorted_desc,
        jnp.broadcast_to(jnp.clip(top_k - 1, 0, V - 1)[..., None],
                         scaled.shape[:-1] + (1,)),
        axis=-1)
    keep_k = (top_k <= 0)[..., None] | (scaled >= kth)
    masked = jnp.where(keep_k, scaled, -jnp.inf)
    sd = jnp.where((top_k <= 0)[..., None] | (sorted_desc >= kth),
                   sorted_desc, -jnp.inf)
    probs = jax.nn.softmax(sd, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = cum - probs < top_p[..., None]
    cutoff = jnp.min(jnp.where(keep, sd, jnp.inf), axis=-1,
                     keepdims=True)
    use_p = ((top_p > 0) & (top_p < 1))[..., None]
    return jnp.where(use_p & (masked < cutoff), -jnp.inf, masked)


def speculative_verify(logits, drafts, depth, q, temperature, top_k,
                       top_p, key_data):
    """Rejection-sampling verify for ONE slot — traced, vmapped over
    the slot axis by the engine's fused step.

    Inputs: ``logits`` (K+1, V) target rows over the draft window,
    ``drafts`` (K,) proposed tokens, ``depth`` scalar per-slot draft
    length, ``q`` (K, V) proposal probabilities the drafts were sampled
    from, scalar sampling knobs, and ``key_data`` (KW,) the slot's raw
    PRNG key state (``jax.random.key_data`` layout).

    Per Leviathan et al.: draft i is accepted with probability
    ``min(1, p_i[d_i] / q_i[d_i])`` (evaluated as ``u * q < p`` with an
    independent uniform); at the first rejection the token is resampled
    from the normalized residual ``max(0, p - q)``; if every draft is
    accepted the bonus token is a fresh sample from the last row. At
    temperature 0 the accept test collapses to ``draft == argmax`` and
    the emitted values are bitwise the greedy verify lane's.

    PRNG discipline mirrors ``generation.generate``: exactly ONE
    ``jax.random.split`` is consumed per COMMITTED token (so a slot
    that speculates is stream-compatible with one that does not, and a
    no-draft sampled slot is bitwise identical to the one-shot
    reference at the same seed); accept uniforms and residual draws
    ride fold_in side-channels off the per-token subkeys.

    Returns ``(committed (K+1,) int32, ncommit scalar int32,
    last_tok scalar int32, new_key_data (KW,))``."""
    import jax
    import jax.numpy as jnp

    K = drafts.shape[0]
    V = logits.shape[-1]
    temperature = jnp.asarray(temperature, jnp.float32)

    # one split per potentially-committed token: ks[i] is the carry
    # after i+1 splits (the new key state if i+1 tokens commit),
    # subs[i] the subkey that samples token i
    carry = jax.random.wrap_key_data(key_data)
    ks, subs, accept_u = [], [], []
    for i in range(K + 1):
        carry, sub = jax.random.split(carry)
        ks.append(jax.random.key_data(carry))
        subs.append(jax.random.key_data(sub))
        if i < K:
            accept_u.append(jax.random.uniform(
                jax.random.fold_in(sub, 0xACC)))
    ks = jnp.stack(ks)                       # (K+1, KW)
    subs = jnp.stack(subs)                   # (K+1, KW)
    u = jnp.stack(accept_u) if K else jnp.zeros((0,), jnp.float32)

    masked = adjust_logits(logits, temperature, top_k, top_p)
    p = jax.nn.softmax(masked.astype(jnp.float32), axis=-1)  # (K+1, V)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)   # (K+1,)

    lane = jnp.arange(K)
    p_d = jnp.take_along_axis(p[:K], drafts[:, None], axis=-1)[:, 0]
    q_d = jnp.take_along_axis(q, drafts[:, None], axis=-1)[:, 0]
    samp_ok = u * q_d < p_d
    greedy_ok = drafts == greedy[:K]
    ok = jnp.where(temperature > 0, samp_ok, greedy_ok) \
        & (lane < depth)
    a = jnp.sum(jnp.cumprod(ok.astype(jnp.int32)))  # accepted count

    # token at column a: greedy / residual-resample / fresh sample
    q_pad = jnp.concatenate([q, jnp.zeros((1, V), q.dtype)], axis=0)
    p_a = jnp.take(p, a, axis=0)
    residual = jnp.maximum(p_a - jnp.take(q_pad, a, axis=0), 0.0)
    r_sum = jnp.sum(residual)
    use_resid = (temperature > 0) & (a < depth) & (r_sum > 0)
    sub_a = jax.random.wrap_key_data(jnp.take(subs, a, axis=0))
    masked_a = jnp.take(masked, a, axis=0)
    drawn_full = jax.random.categorical(sub_a, masked_a)
    resid_logits = jnp.where(residual > 0, jnp.log(residual), -jnp.inf)
    drawn_resid = jax.random.categorical(sub_a, resid_logits)
    tok_a = jnp.where(
        temperature == 0.0, jnp.take(greedy, a),
        jnp.where(use_resid, drawn_resid, drawn_full)).astype(jnp.int32)

    cols = jnp.arange(K + 1)
    drafts_pad = jnp.concatenate(
        [drafts.astype(jnp.int32), jnp.zeros((1,), jnp.int32)])
    committed = jnp.where(cols < a, drafts_pad, 0)
    committed = jnp.where(cols == a, tok_a, committed)
    new_key_data = jnp.take(ks, a, axis=0)
    return (committed.astype(jnp.int32), (a + 1).astype(jnp.int32),
            tok_a, new_key_data)


def check_sampled_draft(draftsman) -> None:
    """Refuse speculation at temperature > 0 with a draftsman that
    cannot satisfy the sampled-verify contract.

    The rejection-sampling accept test needs per-token proposal
    probabilities ``q`` (``surfaces_q = True`` on the draftsman) and a
    per-request PRNG key (seeded via ``SamplingParams.seed``) so
    sampled runs are reproducible; a draftsman without q would force
    the engine to guess the proposal distribution and silently skew
    the output distribution — fail loudly at submit instead."""
    if draftsman is None:
        return
    if not getattr(draftsman, "surfaces_q", False):
        raise SpeculativeConfigError(
            f"draftsman {type(draftsman).__name__} does not surface "
            f"per-token proposal probabilities (q): speculation at "
            f"temperature > 0 runs the rejection-sampling accept test "
            f"min(1, p/q), which needs the draftsman's q rows "
            f"(surfaces_q = True) and a per-request seed "
            f"(SamplingParams.seed) for a reproducible PRNG stream — "
            f"add q support to the draftsman or submit the request "
            f"with temperature == 0")


class SpeculativeConfigError(ValueError):
    """A speculation configuration that could never run soundly.

    Raised at :class:`~hetu_tpu.serving.engine.ServingEngine`
    construction (never mid-decode, where the failure mode would be a
    silently corrupted ``pos``): a draft depth whose verify window
    cannot fit a slot, or a draft model whose gate couples co-batched
    rows (its routing depends on which OTHER requests share the batch,
    so its drafts — and its own KV — are not a function of the request
    alone)."""

    def __init__(self, msg: str):
        super().__init__(msg)


def check_draft_depth(spec_depth: int, max_len: int) -> int:
    """Validate the engine-level draft depth against the slot budget.

    The verify lane feeds ``spec_depth + 1`` rows per slot, so a depth
    that cannot fit even an empty slot (``spec_depth + 1 > max_len``)
    would force every write past the blocks the table owns — raise the
    named error instead of letting the clamp arithmetic corrupt
    ``pos``."""
    k = int(spec_depth)
    if k < 0:
        raise SpeculativeConfigError(
            f"spec_depth must be >= 0, got {k}")
    if k and k + 1 > int(max_len):
        raise SpeculativeConfigError(
            f"spec_depth {k} would overflow a slot: the verify lane "
            f"writes {k + 1} rows per iteration but max_len is "
            f"{max_len} — lower spec_depth or raise max_len")
    return k


def check_draft_model(draft_model) -> None:
    """Refuse draft models whose routing is batch-coupled.

    A gate with ``batch_coupled = True`` (the PR 9 marker on
    Sinkhorn-style balance gates) routes each row as a function of the
    WHOLE batch, so the draft model's proposals for one request change
    with its co-batched neighbors — its KV cache is not replayable and
    its drafts are not a pure function of the request. The verify lane
    would still be correct (bad drafts just get rejected), but the
    draft cache's catch-up replay would diverge from what was drafted;
    fail loudly at construction instead."""
    seen: set[int] = set()
    stack = [draft_model]
    while stack:
        obj = stack.pop()
        if id(obj) in seen or not hasattr(obj, "__dict__"):
            continue
        seen.add(id(obj))
        if getattr(obj, "batch_coupled", False):
            raise SpeculativeConfigError(
                f"draft model uses a batch-coupled gate "
                f"({type(obj).__name__}): its routing depends on which "
                f"other requests share the batch, so its drafts are "
                f"not a function of one request — use a per-token "
                f"gate (topk/ktop1/sam) for the draft model")
        for v in vars(obj).values():
            if hasattr(v, "__dict__"):
                stack.append(v)


class NgramDraftsman:
    """Per-slot prompt-lookup drafting over the request's own tokens.

    For each slot, an incremental suffix index maps every n-gram
    (``n = ngram`` down to 1) to the position of its most recent
    occurrence. :meth:`propose` looks up the current tail n-gram
    (longest first) and drafts the tokens that followed its previous
    occurrence. Pure host bookkeeping — O(appended tokens) per
    iteration, nothing on the device."""

    #: draftsmen are proposal-only: the engine treats this flag as "no
    #: device work per iteration" (cheap enough to run under the lock)
    host_only = True

    #: proposals are deterministic (a history lookup), so the proposal
    #: distribution is a one-hot on the drafted token — the engine
    #: synthesizes that q on-device, no host work here
    surfaces_q = True

    def __init__(self, slots: int, *, ngram: int = 3):
        self.ngram = max(1, int(ngram))
        self._index: list[dict] = [dict() for _ in range(slots)]
        self._prev: list[dict] = [dict() for _ in range(slots)]
        self._seq: list[list[int]] = [[] for _ in range(slots)]

    def reset(self, slot: int, tokens: Sequence[int]) -> None:
        """(Re)bind ``slot`` to a fresh request whose history is
        ``tokens`` (the prompt at admission; prompt + emitted on a
        spill-resume)."""
        self._index[slot] = {}
        self._prev[slot] = {}
        self._seq[slot] = []
        self.extend(slot, tokens)

    def extend(self, slot: int, tokens: Sequence[int]) -> None:
        """Append committed tokens and index the new suffixes. Index
        values are the position AFTER the n-gram (where its
        continuation starts); the previous occurrence is kept too —
        the current TAIL's latest occurrence is always itself, and the
        draft is whatever followed it last time around."""
        seq = self._seq[slot]
        idx = self._index[slot]
        prev = self._prev[slot]
        for t in tokens:
            seq.append(int(t))
            end = len(seq)
            for n in range(1, self.ngram + 1):
                if end >= n:
                    key = tuple(seq[end - n:end])
                    old = idx.get(key)
                    if old is not None:
                        prev[key] = old
                    idx[key] = end

    def propose(self, slot: int, k: int) -> list[int]:
        """Up to ``k`` draft tokens continuing the slot's current tail
        (longest matching n-gram wins; an n-gram whose only occurrence
        is the tail itself proposes nothing)."""
        if k <= 0:
            return []
        seq = self._seq[slot]
        idx = self._index[slot]
        prev = self._prev[slot]
        end = len(seq)
        for n in range(min(self.ngram, end), 0, -1):
            key = tuple(seq[end - n:end])
            j = idx.get(key)
            if j == end:                 # the tail is its own latest hit
                j = prev.get(key)
            if j is not None and j < end:
                return seq[j:j + k]
        return []


class ModelDraftsman:
    """Small-model drafting with a per-slot KV cache and one jitted
    step (catch-up + k-token greedy scan), compiled once.

    The draft arena is the paged layout with ONE wide block per slot
    (identity block tables), so the masked per-cell writes ride the
    same ``row_mask`` scatter path the verify lane uses. Per slot the
    draftsman tracks ``draft_pos`` — how many committed positions its
    cache has consumed; a slot drafts only when fully caught up
    (``draft_pos == pos + 1``), so admissions and spill-resumes warm up
    over a few iterations instead of needing a draft prefill lane."""

    host_only = False

    #: sampled drafting: the chain is SAMPLED from the draft model's
    #: adjusted softmax at the request's knobs and those rows are
    #: returned as q — the rejection test's proposal distribution
    surfaces_q = True

    def __init__(self, model, params, *, slots: int, max_len: int,
                 spec_depth: int, cache_dtype=None,
                 target_vocab: Optional[int] = None):
        import jax
        import jax.numpy as jnp

        from hetu_tpu.models.generation import init_kv_caches

        check_draft_model(model)
        self.model = model
        self.params = params
        # the verify lane's p lives over the TARGET vocab; q rows must
        # match it, so draft logits past target_vocab are masked to
        # -inf before sampling (a draft model may pad its vocab)
        self.target_vocab = (int(target_vocab)
                            if target_vocab is not None else None)
        self.K = int(spec_depth)
        self.H = self.K + 1                  # catch-up window width
        self.slots = int(slots)
        # one wide block per slot, sized so the deepest speculative
        # write (pos + K - 1 <= max_len + K - 2) never clamps
        self.row_len = int(max_len) + self.K + 1
        max_pos = getattr(getattr(model, "cfg", None), "max_positions",
                          None)
        if max_pos is not None and self.row_len > max_pos:
            raise SpeculativeConfigError(
                f"draft model max_positions {max_pos} cannot address "
                f"the target's max_len {max_len} + spec_depth "
                f"{self.K} rows — use a draft model with a longer "
                f"context or lower spec_depth")
        self.caches = init_kv_caches(
            model, self.slots + 1, self.row_len,
            cache_dtype if cache_dtype is not None else jnp.float32)
        # identity tables: slot r owns arena block r+1 (0 = null)
        self._tables = jnp.asarray(
            np.arange(1, self.slots + 1, dtype=np.int32)[:, None])
        self.draft_pos = np.zeros(self.slots, np.int64)
        self._fn = self._build(jax, jnp)

    def _build(self, jax, jnp):
        model, K, H = self.model, self.K, self.H
        Vt = self.target_vocab

        def draft_step(params, caches, hist_tok, hist_pos, hist_len,
                       active, tables, temps, topks, topps, keys):
            from hetu_tpu.engine.train_step import record_trace
            from hetu_tpu.models import generation
            record_trace("serving_draft_step")   # 1 compile, ever
            # per-slot draft PRNG: a fold_in side-channel off the
            # slot's commit key (which advances every committed token,
            # so draft draws differ across iterations without touching
            # the commit stream the verify lane replays)
            kbase = jax.vmap(lambda kd: jax.random.fold_in(
                jax.random.wrap_key_data(kd), 0xD4AF7))(keys)

            def pick(lg_rows, j):
                """Sample draft token j from the adjusted softmax (or
                argmax at temperature 0) and return (tok, q_row)."""
                Vd = lg_rows.shape[-1]
                Vq = Vt if Vt is not None else Vd
                if Vt is not None and Vd > Vt:
                    lg_rows = jnp.where(
                        jnp.arange(Vd) < Vt, lg_rows, -jnp.inf)
                masked = adjust_logits(lg_rows, temps, topks, topps)
                g = jnp.argmax(lg_rows, axis=-1).astype(jnp.int32)
                kj = jax.vmap(lambda k: jax.random.fold_in(k, j))(kbase)
                drawn = jax.vmap(jax.random.categorical)(kj, masked)
                tok = jnp.where(temps == 0.0, g, drawn).astype(jnp.int32)
                pq = jax.nn.softmax(masked.astype(jnp.float32), axis=-1)
                if Vd > Vq:
                    pq = pq[..., :Vq]       # masked rows carry 0 there
                elif Vd < Vq:
                    pq = jnp.pad(pq, ((0, 0), (0, Vq - Vd)))
                qrow = jnp.where(
                    (temps == 0.0)[:, None],
                    jax.nn.one_hot(tok, Vq, dtype=jnp.float32), pq)
                return tok, qrow

            lane = jnp.arange(H)[None, :]
            positions = hist_pos[:, None] + lane
            valid = (lane < hist_len[:, None]) & active[:, None] \
                & (positions < self.row_len)
            logits, caches = generation.decode(
                model, params, hist_tok, positions, caches,
                slot_mask=active, block_tables=tables, row_mask=valid)
            seed_row = jnp.clip(hist_len - 1, 0, H - 1)
            lg = jnp.take_along_axis(
                logits, seed_row[:, None, None], axis=1)[:, 0]
            first, q1 = pick(lg, 0)
            base = hist_pos + hist_len            # first draft's write

            def body(carry, j):
                caches, tok, qrow = carry
                pos = (base + j)[:, None]
                # rows that consumed nothing this call have no seed —
                # their scan output is garbage and must not write
                ok = active[:, None] & (hist_len > 0)[:, None] \
                    & (pos < self.row_len)
                lg, caches = generation.decode(
                    model, params, tok[:, None], pos, caches,
                    slot_mask=active, block_tables=tables, row_mask=ok)
                nxt, qn = pick(lg[:, 0], j + 1)
                return (caches, nxt, qn), (tok, qrow)

            if K > 1:
                (caches, last, q_last), (toks, qs) = jax.lax.scan(
                    body, (caches, first, q1), jnp.arange(K - 1))
                drafts = jnp.concatenate(
                    [jnp.moveaxis(toks, 0, 1), last[:, None]], axis=1)
                q = jnp.concatenate(
                    [jnp.moveaxis(qs, 0, 1), q_last[:, None]], axis=1)
            else:
                drafts = first[:, None]
                q = q1[:, None]
            return caches, drafts, q           # (S, K), (S, K, Vq)

        return jax.jit(draft_step, donate_argnums=(1,))

    def reset(self, slot: int, tokens: Sequence[int]) -> None:
        """A new (or resumed) request owns ``slot``: its draft KV is
        cold — catch-up restarts from position 0."""
        self.draft_pos[slot] = 0

    def extend(self, slot: int, tokens: Sequence[int]) -> None:
        """Committed tokens are consumed via catch-up, not eagerly."""

    def propose_all(self, seqs: list[Optional[Sequence[int]]],
                    pos: np.ndarray, active: np.ndarray,
                    budget: np.ndarray, *, temps=None, topks=None,
                    topps=None, keys=None):
        """One draft pass for the whole slot pool.

        ``seqs[r]`` is slot r's full committed history (prompt +
        emitted tokens, ``None`` for empty slots), ``pos[r]`` the
        target's next KV write index (history[pos] is the not-yet-fed
        last token), ``budget[r]`` the engine's per-slot depth clamp.
        ``temps``/``topks``/``topps`` are the per-slot sampling knobs
        (defaults: greedy) and ``keys`` the per-slot raw commit-key
        state ``(S, KW) uint32`` the sampled chain derives its draws
        from. Returns ``(draft_tok (S, K) int32, draft_len (S,) int32,
        q (S, K, V) device array)`` — zero length for cold (still
        catching up) or inactive slots."""
        import numpy as _np
        S, H = self.slots, self.H
        hist_tok = _np.zeros((S, H), _np.int32)
        hist_pos = _np.zeros(S, _np.int32)
        hist_len = _np.zeros(S, _np.int32)
        warm = _np.zeros(S, bool)
        for r in range(S):
            if not active[r] or seqs[r] is None:
                continue
            avail = int(pos[r]) + 1 - int(self.draft_pos[r])
            if avail <= 0:
                continue       # nothing new to consume — skip this turn
            h = min(H, avail)
            lo = int(self.draft_pos[r])
            hist_tok[r, :h] = seqs[r][lo:lo + h]
            hist_pos[r] = lo
            hist_len[r] = h
            self.draft_pos[r] = lo + h
            warm[r] = (lo + h) == int(pos[r]) + 1
        if temps is None:
            temps = _np.zeros(S, _np.float32)
        if topks is None:
            topks = _np.zeros(S, _np.int32)
        if topps is None:
            topps = _np.zeros(S, _np.float32)
        if keys is None:
            import jax
            kw = jax.random.key_data(jax.random.key(0)).shape[-1]
            keys = _np.zeros((S, kw), _np.uint32)
        self.caches, drafts, q = self._fn(
            self.params, self.caches, hist_tok, hist_pos, hist_len,
            active, self._tables,
            _np.asarray(temps, _np.float32),
            _np.asarray(topks, _np.int32),
            _np.asarray(topps, _np.float32),
            _np.asarray(keys, _np.uint32))
        drafts = _np.asarray(drafts)
        draft_len = _np.where(warm & active, budget, 0).astype(_np.int32)
        return drafts.astype(_np.int32), draft_len, q
