"""Speculative-decoding draft plane: who proposes the k tokens the
fused serving step verifies.

Decode emits one token per active slot per fused-step iteration, so at
production TPOT targets most of each step's FLOPs sit idle — the
memory-bound decode wall speculative decoding (Leviathan et al., "Fast
Inference from Transformers via Speculative Decoding") climbs by
verifying k DRAFTED tokens in one forward pass. The serving engine's
verify lane (``ServingEngine(spec_depth=k)``) does the checking; this
module is where drafts come from:

- :class:`NgramDraftsman` — self-drafting prompt-lookup (Saxena,
  "Prompt Lookup Decoding" / LLMA): a host-side per-slot suffix index
  over the request's OWN tokens (prompt + emitted). The last n-gram is
  looked up in the history; if it occurred before, the tokens that
  followed it are the draft. No second model, no device work, and on
  the repetitive traffic real serving sees (code edits, RAG quoting
  its context, multi-turn echoes) acceptance is high exactly when the
  tokens were cheapest to predict;
- :class:`ModelDraftsman` — the small-model path through the existing
  model zoo (a tiny GPT drafting for a Llama, etc.): the draft model
  keeps its own per-slot KV arena and ONE jitted step per iteration
  first *catches up* on the tokens the target committed last iteration
  (a ``(S, k+1)``-wide masked window — no separate prefill lane: a
  fresh slot warms up over its first ``ceil(P/(k+1))`` iterations,
  drafting meanwhile disabled for it), then greedily drafts k tokens.
  Draft KV for rejected tokens is overwritten by the next catch-up
  before anything attends it, the same rewind discipline the target
  arena uses.

Both draftsmen are PROPOSERS only: the engine's verify lane accepts a
draft token iff it equals what sequential greedy decode would have
emitted, so a bad draftsman can only cost speed, never correctness
(``docs/SERVING.md`` — "Speculation + QoS").
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


class SpeculativeConfigError(ValueError):
    """A speculation configuration that could never run soundly.

    Raised at :class:`~hetu_tpu.serving.engine.ServingEngine`
    construction (never mid-decode, where the failure mode would be a
    silently corrupted ``pos``): a draft depth whose verify window
    cannot fit a slot, or a draft model whose gate couples co-batched
    rows (its routing depends on which OTHER requests share the batch,
    so its drafts — and its own KV — are not a function of the request
    alone)."""

    def __init__(self, msg: str):
        super().__init__(msg)


def check_draft_depth(spec_depth: int, max_len: int) -> int:
    """Validate the engine-level draft depth against the slot budget.

    The verify lane feeds ``spec_depth + 1`` rows per slot, so a depth
    that cannot fit even an empty slot (``spec_depth + 1 > max_len``)
    would force every write past the blocks the table owns — raise the
    named error instead of letting the clamp arithmetic corrupt
    ``pos``."""
    k = int(spec_depth)
    if k < 0:
        raise SpeculativeConfigError(
            f"spec_depth must be >= 0, got {k}")
    if k and k + 1 > int(max_len):
        raise SpeculativeConfigError(
            f"spec_depth {k} would overflow a slot: the verify lane "
            f"writes {k + 1} rows per iteration but max_len is "
            f"{max_len} — lower spec_depth or raise max_len")
    return k


def check_draft_model(draft_model) -> None:
    """Refuse draft models whose routing is batch-coupled.

    A gate with ``batch_coupled = True`` (the PR 9 marker on
    Sinkhorn-style balance gates) routes each row as a function of the
    WHOLE batch, so the draft model's proposals for one request change
    with its co-batched neighbors — its KV cache is not replayable and
    its drafts are not a pure function of the request. The verify lane
    would still be correct (bad drafts just get rejected), but the
    draft cache's catch-up replay would diverge from what was drafted;
    fail loudly at construction instead."""
    seen: set[int] = set()
    stack = [draft_model]
    while stack:
        obj = stack.pop()
        if id(obj) in seen or not hasattr(obj, "__dict__"):
            continue
        seen.add(id(obj))
        if getattr(obj, "batch_coupled", False):
            raise SpeculativeConfigError(
                f"draft model uses a batch-coupled gate "
                f"({type(obj).__name__}): its routing depends on which "
                f"other requests share the batch, so its drafts are "
                f"not a function of one request — use a per-token "
                f"gate (topk/ktop1/sam) for the draft model")
        for v in vars(obj).values():
            if hasattr(v, "__dict__"):
                stack.append(v)


class NgramDraftsman:
    """Per-slot prompt-lookup drafting over the request's own tokens.

    For each slot, an incremental suffix index maps every n-gram
    (``n = ngram`` down to 1) to the position of its most recent
    occurrence. :meth:`propose` looks up the current tail n-gram
    (longest first) and drafts the tokens that followed its previous
    occurrence. Pure host bookkeeping — O(appended tokens) per
    iteration, nothing on the device."""

    #: draftsmen are proposal-only: the engine treats this flag as "no
    #: device work per iteration" (cheap enough to run under the lock)
    host_only = True

    def __init__(self, slots: int, *, ngram: int = 3):
        self.ngram = max(1, int(ngram))
        self._index: list[dict] = [dict() for _ in range(slots)]
        self._prev: list[dict] = [dict() for _ in range(slots)]
        self._seq: list[list[int]] = [[] for _ in range(slots)]

    def reset(self, slot: int, tokens: Sequence[int]) -> None:
        """(Re)bind ``slot`` to a fresh request whose history is
        ``tokens`` (the prompt at admission; prompt + emitted on a
        spill-resume)."""
        self._index[slot] = {}
        self._prev[slot] = {}
        self._seq[slot] = []
        self.extend(slot, tokens)

    def extend(self, slot: int, tokens: Sequence[int]) -> None:
        """Append committed tokens and index the new suffixes. Index
        values are the position AFTER the n-gram (where its
        continuation starts); the previous occurrence is kept too —
        the current TAIL's latest occurrence is always itself, and the
        draft is whatever followed it last time around."""
        seq = self._seq[slot]
        idx = self._index[slot]
        prev = self._prev[slot]
        for t in tokens:
            seq.append(int(t))
            end = len(seq)
            for n in range(1, self.ngram + 1):
                if end >= n:
                    key = tuple(seq[end - n:end])
                    old = idx.get(key)
                    if old is not None:
                        prev[key] = old
                    idx[key] = end

    def propose(self, slot: int, k: int) -> list[int]:
        """Up to ``k`` draft tokens continuing the slot's current tail
        (longest matching n-gram wins; an n-gram whose only occurrence
        is the tail itself proposes nothing)."""
        if k <= 0:
            return []
        seq = self._seq[slot]
        idx = self._index[slot]
        prev = self._prev[slot]
        end = len(seq)
        for n in range(min(self.ngram, end), 0, -1):
            key = tuple(seq[end - n:end])
            j = idx.get(key)
            if j == end:                 # the tail is its own latest hit
                j = prev.get(key)
            if j is not None and j < end:
                return seq[j:j + k]
        return []


class ModelDraftsman:
    """Small-model drafting with a per-slot KV cache and one jitted
    step (catch-up + k-token greedy scan), compiled once.

    The draft arena is the paged layout with ONE wide block per slot
    (identity block tables), so the masked per-cell writes ride the
    same ``row_mask`` scatter path the verify lane uses. Per slot the
    draftsman tracks ``draft_pos`` — how many committed positions its
    cache has consumed; a slot drafts only when fully caught up
    (``draft_pos == pos + 1``), so admissions and spill-resumes warm up
    over a few iterations instead of needing a draft prefill lane."""

    host_only = False

    def __init__(self, model, params, *, slots: int, max_len: int,
                 spec_depth: int, cache_dtype=None):
        import jax
        import jax.numpy as jnp

        from hetu_tpu.models.generation import init_kv_caches

        check_draft_model(model)
        self.model = model
        self.params = params
        self.K = int(spec_depth)
        self.H = self.K + 1                  # catch-up window width
        self.slots = int(slots)
        # one wide block per slot, sized so the deepest speculative
        # write (pos + K - 1 <= max_len + K - 2) never clamps
        self.row_len = int(max_len) + self.K + 1
        max_pos = getattr(getattr(model, "cfg", None), "max_positions",
                          None)
        if max_pos is not None and self.row_len > max_pos:
            raise SpeculativeConfigError(
                f"draft model max_positions {max_pos} cannot address "
                f"the target's max_len {max_len} + spec_depth "
                f"{self.K} rows — use a draft model with a longer "
                f"context or lower spec_depth")
        self.caches = init_kv_caches(
            model, self.slots + 1, self.row_len,
            cache_dtype if cache_dtype is not None else jnp.float32)
        # identity tables: slot r owns arena block r+1 (0 = null)
        self._tables = jnp.asarray(
            np.arange(1, self.slots + 1, dtype=np.int32)[:, None])
        self.draft_pos = np.zeros(self.slots, np.int64)
        self._fn = self._build(jax, jnp)

    def _build(self, jax, jnp):
        model, K, H = self.model, self.K, self.H
        n_rows = (self.slots + 1) * self.row_len

        def draft_step(params, caches, hist_tok, hist_pos, hist_len,
                       active, tables):
            from hetu_tpu.engine.train_step import record_trace
            from hetu_tpu.models import generation
            record_trace("serving_draft_step")   # 1 compile, ever
            lane = jnp.arange(H)[None, :]
            positions = hist_pos[:, None] + lane
            valid = (lane < hist_len[:, None]) & active[:, None] \
                & (positions < self.row_len)
            logits, caches = generation.decode(
                model, params, hist_tok, positions, caches,
                slot_mask=active, block_tables=tables, row_mask=valid)
            seed_row = jnp.clip(hist_len - 1, 0, H - 1)
            lg = jnp.take_along_axis(
                logits, seed_row[:, None, None], axis=1)[:, 0]
            first = jnp.argmax(lg, axis=-1).astype(jnp.int32)
            base = hist_pos + hist_len            # first draft's write

            def body(carry, j):
                caches, tok = carry
                pos = (base + j)[:, None]
                # rows that consumed nothing this call have no seed —
                # their scan output is garbage and must not write
                ok = active[:, None] & (hist_len > 0)[:, None] \
                    & (pos < self.row_len)
                lg, caches = generation.decode(
                    model, params, tok[:, None], pos, caches,
                    slot_mask=active, block_tables=tables, row_mask=ok)
                nxt = jnp.argmax(lg[:, 0], axis=-1).astype(jnp.int32)
                return (caches, nxt), tok

            if K > 1:
                (caches, last), toks = jax.lax.scan(
                    body, (caches, first), jnp.arange(K - 1))
                drafts = jnp.concatenate(
                    [jnp.moveaxis(toks, 0, 1), last[:, None]], axis=1)
            else:
                drafts = first[:, None]
            return caches, drafts                  # (S, K)

        return jax.jit(draft_step, donate_argnums=(1,))

    def reset(self, slot: int, tokens: Sequence[int]) -> None:
        """A new (or resumed) request owns ``slot``: its draft KV is
        cold — catch-up restarts from position 0."""
        self.draft_pos[slot] = 0

    def extend(self, slot: int, tokens: Sequence[int]) -> None:
        """Committed tokens are consumed via catch-up, not eagerly."""

    def propose_all(self, seqs: list[Optional[Sequence[int]]],
                    pos: np.ndarray, active: np.ndarray,
                    budget: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """One draft pass for the whole slot pool.

        ``seqs[r]`` is slot r's full committed history (prompt +
        emitted tokens, ``None`` for empty slots), ``pos[r]`` the
        target's next KV write index (history[pos] is the not-yet-fed
        last token), ``budget[r]`` the engine's per-slot depth clamp.
        Returns ``(draft_tok (S, K) int32, draft_len (S,) int32)`` —
        zero length for cold (still catching up) or inactive slots."""
        import numpy as _np
        S, H = self.slots, self.H
        hist_tok = _np.zeros((S, H), _np.int32)
        hist_pos = _np.zeros(S, _np.int32)
        hist_len = _np.zeros(S, _np.int32)
        warm = _np.zeros(S, bool)
        for r in range(S):
            if not active[r] or seqs[r] is None:
                continue
            avail = int(pos[r]) + 1 - int(self.draft_pos[r])
            if avail <= 0:
                continue       # nothing new to consume — skip this turn
            h = min(H, avail)
            lo = int(self.draft_pos[r])
            hist_tok[r, :h] = seqs[r][lo:lo + h]
            hist_pos[r] = lo
            hist_len[r] = h
            self.draft_pos[r] = lo + h
            warm[r] = (lo + h) == int(pos[r]) + 1
        self.caches, drafts = self._fn(
            self.params, self.caches, hist_tok, hist_pos, hist_len,
            active, self._tables)
        drafts = _np.asarray(drafts)
        draft_len = _np.where(warm & active, budget, 0).astype(_np.int32)
        return drafts.astype(_np.int32), draft_len
