"""Multi-process serving fleet: remote replicas over the coordinator.

PR 8's fleet plane proved the serving verbs and the rolling weight
push, but every replica was an in-process thread sharing one process's
devices. This module is the multi-process rung (ISSUE 15): the Router
keeps its exact dispatch/drain/death machinery, and a replica becomes a
*process* — one :class:`~hetu_tpu.serving.engine.ServingEngine` behind
its own line-protocol coordinator (``serving/server.py``), driven
through:

- :class:`RemoteEngineProxy` — satisfies the engine duck type the
  Router speaks (``submit``/``cancel_queued``/``evict_request``/
  ``has_work``/``load``/``weight_version``/``stop``) by translating
  each call into hardened :class:`~hetu_tpu.rpc.client.CoordinatorClient`
  verbs (SUBMIT with **idempotency keys** so retry-after-timeout is
  safe, ESTATUS polling, CANCELQ/EVICT for drains and salvage,
  SWAPWEIGHTS for the dist-checkpoint weight push, PREFILL for the
  prefill tier). A background poller keeps load/occupancy/version fresh
  and doubles as the liveness signal;
- :class:`RemoteReplicaHandle` — a
  :class:`~hetu_tpu.serving.router.ReplicaHandle` whose death detection
  is **heartbeat staleness** (every successful status poll is a beat;
  ``loop_alive()`` is never true because there is no local loop
  thread), so a SIGKILLed engine process is declared dead by the
  router's existing ``beat_timeout_s`` machinery and its in-flight
  requests requeue onto peers;
- the **KV wire format** (:func:`spill_to_wire` /
  :func:`spill_from_wire`) — a
  :class:`~hetu_tpu.serving.kv_pool.SpillEntry` serialized losslessly
  (raw bytes + dtype + shape per cache leaf, base64 on the one-line
  protocol), so preemptive drains, kill salvage and the prefill tier
  move KV **between processes** instead of assuming shared host RAM.
  Bitwise: ``from_wire(to_wire(e))`` reproduces every page exactly;
- :func:`replica_main` — the engine-process entry point
  (``python -m hetu_tpu.serving.fleet``): builds the engine from an
  env-named ``module:function`` spec, serves it on its port, and waits
  for SIGTERM. ``rpc/launcher.launch_serving_fleet(remote=True)``
  spawns one of these per replica and registers the proxies.

Prefill/decode disaggregation rides the same machinery: a replica
registered with ``role="prefill"`` runs admission + prefill only
(``ServingEngine.prefill_only`` parks the request after its first
token), the router evicts the finished KV blocks and streams them —
wire format and all — to a ``role="decode"`` replica, which maps them
in with a block-table edit and resumes through the existing
``submit(resume=)`` path. TTFT (prefill pool) and TPOT (decode pool)
then scale independently. ``docs/SERVING.md`` ("Disaggregated fleet")
has the state machines and failure semantics.
"""

from __future__ import annotations

import base64
import itertools
import threading
import time
import uuid
from typing import Optional, Sequence

import numpy as np

from hetu_tpu import telemetry
from hetu_tpu.serving.kv_pool import SpillEntry
from hetu_tpu.serving.router import ReplicaHandle
from hetu_tpu.serving.scheduler import SamplingParams
from hetu_tpu.utils.logging import get_logger

# -- KV wire format -----------------------------------------------------------


def array_to_wire(a: np.ndarray) -> dict:
    """One numpy array → a JSON-safe dict (dtype + shape + raw bytes,
    base64). Lossless for every arena dtype incl. int8 pages and their
    fp32 scales."""
    a = np.ascontiguousarray(a)
    return {"dtype": str(a.dtype), "shape": list(a.shape),
            "b64": base64.b64encode(a.tobytes()).decode("ascii")}


def array_from_wire(d: dict) -> np.ndarray:
    return np.frombuffer(
        base64.b64decode(d["b64"]), dtype=np.dtype(d["dtype"])
    ).reshape(d["shape"]).copy()


def spill_to_wire(entry: SpillEntry) -> dict:
    """Serialize a SpillEntry for the line protocol — the payload that
    moves KV blocks replica→replica through the coordinator (preemptive
    drains, kill salvage, prefill→decode streaming)."""
    d = {"req_id": entry.req_id,
         "n_blocks": entry.n_blocks,
         "block_size": entry.block_size,
         "pos": entry.pos, "last_tok": entry.last_tok,
         "tokens": [int(t) for t in entry.tokens],
         "weight_version": entry.weight_version,
         "data": [array_to_wire(a) for a in entry.data]}
    if entry.traceparent:
        d["traceparent"] = entry.traceparent
    if entry.key_state is not None:
        # a sampled request's PRNG commit-key state must travel with
        # its KV — a cross-process resume without it would fork the
        # sample stream and diverge from the undisturbed run
        d["key_state"] = array_to_wire(np.asarray(entry.key_state))
    return d


def spill_from_wire(d: dict) -> SpillEntry:
    ks = d.get("key_state")
    return SpillEntry(
        req_id=int(d["req_id"]),
        data=tuple(array_from_wire(a) for a in d["data"]),
        n_blocks=int(d["n_blocks"]), block_size=int(d["block_size"]),
        pos=int(d["pos"]), last_tok=int(d["last_tok"]),
        tokens=[int(t) for t in d["tokens"]],
        weight_version=int(d["weight_version"]),
        traceparent=d.get("traceparent"),
        key_state=array_from_wire(ks) if ks is not None else None)


# -- decode-KV replication: the buddy-side store ------------------------------


class KVReplicaStore:
    """Buddy-side accumulator of a decoding peer's replicated KV
    (ISSUE 18).

    The origin engine streams newly committed blocks on a
    block-granular cadence (``ServingEngine.configure_replication``);
    each shipment is a JSON-safe doc carrying a contiguous block range
    ``[start, start+n)`` per cache leaf plus a CONSISTENT metadata
    snapshot (pos / tokens / last_tok / PRNG key state, captured under
    the origin's step lock in the same breath as the blocks). Entries
    are keyed by ``trace_id`` — the one identity that survives the
    origin's death and any number of requeues — and :meth:`fetch`
    assembles a full :class:`SpillEntry` the recovery path feeds to
    ``submit(resume=)`` on a live peer: bit-identical to a local
    preemption resume, because it IS one.

    Jax-free and lock-cheap: ``put`` runs on the buddy's verb-handler
    thread (wire) or the origin's replication thread (in-process) and
    only touches numpy."""

    def __init__(self, max_traces: int = 256):
        self.max_traces = int(max_traces)
        self._lock = threading.Lock()
        self._by_trace: dict[str, dict] = {}     # insertion order = LRU
        self.put_total = 0

    @property
    def blocks_held(self) -> int:
        with self._lock:
            return sum(len(e["blocks"])
                       for e in self._by_trace.values())

    def __contains__(self, trace_id: str) -> bool:
        with self._lock:
            return trace_id in self._by_trace

    def put(self, doc: dict) -> None:
        """Absorb one replication shipment (or a ``{"drop": tid}``
        tombstone when the origin finished the request)."""
        tid = doc.get("drop")
        if tid:
            with self._lock:
                self._by_trace.pop(tid, None)
            return
        tid = doc["trace_id"]
        data = [array_from_wire(a) for a in doc["data"]]
        start = int(doc["start"])
        with self._lock:
            ent = self._by_trace.pop(tid, None) or {"blocks": {}}
            self._by_trace[tid] = ent            # refresh LRU position
            for j in range(int(data[0].shape[1])):
                ent["blocks"][start + j] = [a[:, j:j + 1] for a in data]
            ent["meta"] = {k: doc.get(k) for k in (
                "origin", "req_id", "weight_version", "block_size",
                "pos", "last_tok", "tokens", "key_state",
                "traceparent")}
            self.put_total += 1
            while len(self._by_trace) > self.max_traces:
                self._by_trace.pop(next(iter(self._by_trace)))

    def fetch(self, trace_id: str) -> Optional[SpillEntry]:
        """Assemble the replica set into a resumable SpillEntry, or
        ``None`` while coverage is incomplete (a request that died
        before its first shipment simply replays from the prompt)."""
        with self._lock:
            ent = self._by_trace.get(trace_id)
            if ent is None or "meta" not in ent:
                return None
            m = ent["meta"]
            bs, pos = int(m["block_size"]), int(m["pos"])
            nb = max(1, -(-pos // bs))
            blocks = ent["blocks"]
            if any(i not in blocks for i in range(nb)):
                return None
            data = tuple(
                np.concatenate([blocks[i][leaf] for i in range(nb)],
                               axis=1)
                for leaf in range(len(blocks[0])))
        ks = m.get("key_state")
        return SpillEntry(
            req_id=int(m["req_id"]), data=data, n_blocks=nb,
            block_size=bs, pos=pos, last_tok=int(m["last_tok"]),
            tokens=[int(t) for t in m["tokens"]],
            weight_version=int(m["weight_version"]),
            traceparent=m.get("traceparent"),
            key_state=array_from_wire(ks) if ks is not None else None)

    def drop(self, trace_id: str) -> None:
        with self._lock:
            self._by_trace.pop(trace_id, None)


# -- the remote request -------------------------------------------------------


class RemoteRequest:
    """Router-side view of one request living on a REMOTE engine.

    Duck-typed to the slice of :class:`~hetu_tpu.serving.scheduler.
    Request` the router and the line-protocol front end read: ``id``,
    ``status``, ``error``, ``tokens``, ``weight_version``,
    ``first_token_s``, ``submit_s``, ``done``, ``spill``, ``handoff``,
    ``timing()``. The proxy's poller (or the blocking PREFILL call)
    fills it in from RESULT payloads."""

    def __init__(self, prompt, sampling: SamplingParams, *,
                 handoff: bool = False,
                 traceparent: Optional[str] = None):
        self.id: int = _next_provisional_id()
        self.prompt = [int(t) for t in prompt]
        self.sampling = sampling
        self.submit_s = time.monotonic()
        self.status = "queued"
        self.error: Optional[str] = None
        self.tokens: list = []
        self.weight_version: int = 0
        self.first_token_s: Optional[float] = None
        self.finish_s: Optional[float] = None
        self.traceparent = traceparent
        tid, _span = telemetry.parse_traceparent(traceparent)
        self.trace_id = tid or uuid.uuid4().hex[:12]
        self.handoff = bool(handoff)
        self.spill: Optional[SpillEntry] = None
        self.done = threading.Event()
        self._timing: dict = {}

    def timing(self) -> dict:
        return dict(self._timing)

    def result(self) -> dict:
        return {"id": self.id, "status": self.status,
                "tokens": list(self.tokens), "error": self.error,
                "weight_version": self.weight_version,
                "timing": self.timing()}

    def _fill_from(self, doc: dict) -> None:
        """Adopt a RESULT/PREFILL payload as this request's state."""
        self.status = doc.get("status", "done")
        self.error = doc.get("error")
        self.tokens = list(doc.get("tokens", []))
        self.weight_version = int(doc.get("weight_version", 0))
        self._timing = dict(doc.get("timing", {}))
        self.finish_s = time.monotonic()
        if self.first_token_s is None and self.tokens:
            # approximate: the real TTFT happened on the remote engine
            # and rides the timing breakdown; the local stamp only
            # feeds the router's EWMA tiebreak
            ttft_ms = self._timing.get("ttft_ms")
            self.first_token_s = self.submit_s + ttft_ms / 1e3 \
                if ttft_ms is not None else time.monotonic()


#: provisional ids are NEGATIVE so they can never collide with a remote
#: engine's real (>= 0) request ids inside one handle's inflight map
_provisional = itertools.count(1)


def _next_provisional_id() -> int:
    return -next(_provisional)


class _RemoteSched:
    """Duck-typed ``engine.scheduler`` view (depth/occupancy) for
    :meth:`ReplicaHandle.status`, fed by the proxy's status poller."""

    def __init__(self, proxy: "RemoteEngineProxy"):
        self._proxy = proxy

    @property
    def depth(self) -> int:
        return int(self._proxy._status.get("depth", 0))

    @property
    def occupancy(self) -> float:
        return float(self._proxy._status.get("occupancy", 0.0))


# -- the engine proxy ---------------------------------------------------------


class RemoteEngineProxy:
    """ServingEngine duck type over the coordinator line protocol.

    One persistent :class:`CoordinatorClient` (lock-guarded — the
    router thread and the poller share it) carries the short verbs;
    long-blocking calls (PREFILL, SWAPWEIGHTS, STOPENGINE) open their
    own connection so they never starve status polls. Every transport
    failure is survivable: a failed submit leaves the request queued
    and unreachable-marked (the router's heartbeat-staleness death
    detection requeues it onto a peer), a failed evict degrades to a
    fresh requeue, a failed status poll just ages the beat.
    """

    remote = True                    # Router.register picks the handle

    def __init__(self, port: int, host: str = "127.0.0.1", *,
                 token: Optional[str] = None,
                 poll_s: float = 0.05, poll_max_s: float = 0.25,
                 timeout_s: float = 5.0,
                 swap_timeout_s: float = 300.0,
                 use_stream: bool = True,
                 heartbeat_s: float = 0.25):
        self.port, self.host = int(port), host
        self._token = token
        self._poll_s = float(poll_s)
        # streaming control plane (ISSUE 19): subscribe to each
        # submitted request's token stream over one persistent
        # multiplexed channel instead of RESULT-polling it; the poll
        # lane survives only as the loud fallback on stream loss
        # (resubscribe-at-offset reconverges). With a healthy channel,
        # ESTATUS stretches to ``heartbeat_s`` cadence — it stays the
        # router's beat (a SIGKILLed replica is still reaped within
        # ``beat_timeout_s``) but stops being per-tick load noise.
        self.use_stream = bool(use_stream)
        self._heartbeat_s = max(float(heartbeat_s), float(poll_s))
        self._next_beat = 0.0
        self._schan = None
        self._schan_lock = threading.Lock()
        self._schan_next_try = 0.0
        # adaptive RESULT-poll backoff (ISSUE 18 satellite): ESTATUS
        # keeps its fixed cadence (it IS the heartbeat — backing it off
        # would trip the router's staleness reaper), but the per-request
        # RESULT polls back off exponentially toward ``poll_max_s``
        # while they keep answering PEND, and snap back to ``poll_s``
        # on any activity (a result adopted, a new submit)
        self._poll_max_s = max(float(poll_max_s), self._poll_s)
        self._result_delay = self._poll_s
        self._next_result_poll = 0.0
        self._timeout_s = float(timeout_s)
        self._swap_timeout_s = float(swap_timeout_s)
        self._lock = threading.RLock()
        self._cli = None
        self._kv_lock = threading.Lock()
        self._kv_cli = None              # dedicated replication socket
        self._pending: dict[int, RemoteRequest] = {}
        self._status: dict = {}
        #: wall-clock offset of the replica vs this process (replica
        #: clock = ours + offset), from the latest ESTATUS handshake;
        #: fleet_trace.py uses it to align merged spans
        self.clock_offset_s: float = 0.0
        self._handle: Optional[ReplicaHandle] = None   # beat sink
        self._stop = None            # duck parity with ServingEngine
        self._thread: Optional[threading.Thread] = None
        self._stop_ev: Optional[threading.Event] = None
        self.scheduler = _RemoteSched(self)

    # -- transport ----------------------------------------------------------
    def _client(self, *, fresh: bool = False, timeout: Optional[float]
                = None):
        from hetu_tpu.rpc.client import CoordinatorClient
        if fresh:
            return CoordinatorClient(
                self.port, host=self.host, token=self._token,
                timeout=timeout or self._timeout_s, retries=1)
        if self._cli is None:
            # one bounded retry: SUBMIT rides an idempotency key (a
            # duplicate delivery joins the original request), ESTATUS
            # is read-only — a single TCP hiccup must not strand work
            self._cli = CoordinatorClient(
                self.port, host=self.host, token=self._token,
                timeout=self._timeout_s, retries=1, backoff_s=0.02)
        return self._cli

    def _drop_client(self) -> None:
        with self._lock:
            if self._cli is not None:
                try:
                    self._cli.close()
                except OSError:
                    pass
                self._cli = None

    # -- streaming lane (ISSUE 19) -------------------------------------------
    def _stream_channel(self):
        """The proxy's one persistent multiplexed channel (lazily
        connected, throttled reconnect). Raises on connect failure —
        callers degrade to the poll lane."""
        with self._schan_lock:
            ch = self._schan
            if ch is not None and ch.alive:
                return ch
            now = time.monotonic()
            if now < self._schan_next_try:
                raise ConnectionError("stream reconnect backing off")
            self._schan_next_try = now + 0.25
            from hetu_tpu.rpc.stream import StreamChannel
            ch = StreamChannel(self.port, host=self.host,
                               token=self._token or "",
                               connect_timeout=self._timeout_s)
            self._schan = ch
            return ch

    def _subscribe_stream(self, rr: RemoteRequest, *,
                          resume: bool = False) -> bool:
        """Subscribe ``rr`` at its current token offset; False =
        unavailable (the RESULT poll lane keeps it)."""
        if not self.use_stream or rr.id < 0:
            return False
        from hetu_tpu.serving.streaming import (
            count_fallback, count_subscribe,
        )
        try:
            ch = self._stream_channel()
            ch.subscribe(rr.id, offset=len(rr.tokens),
                         sink=lambda ev, _rr=rr:
                         self._on_stream_event(_rr, ev))
        except Exception:                             # noqa: BLE001
            count_fallback("subscribe_failed")
            return False
        rr._stream_ok = True
        count_subscribe("resume" if resume else "new")
        return True

    def _on_stream_event(self, rr: RemoteRequest, ev: dict) -> None:
        """Channel-reader-thread sink: fold one event into ``rr``.
        Token deltas append at their offset (idempotent across replays
        — a resubscribed stream clips the overlap); the ``done`` frame
        adopts the full result exactly like a RESULT poll would; any
        loss marker flips the request back to the poll lane, loudly."""
        from hetu_tpu.serving.streaming import count_fallback
        kind = ev.get("k")
        if kind == "ev":
            toks = [int(t) for t in ev.get("toks", [])]
            off = int(ev.get("off", 0))
            skip = len(rr.tokens) - off
            if skip < 0:
                # a gap means a lost frame — never guess: fall back
                rr._stream_ok = False
                count_fallback("gap")
                self._reset_result_backoff()
                return
            if skip:
                toks = toks[skip:]
            if toks:
                if rr.first_token_s is None:
                    rr.first_token_s = time.monotonic()
                rr.tokens.extend(toks)
            if ev.get("done"):
                rr._fill_from(ev.get("result") or {})
                rr._stream_ok = False
                self._pending.pop(rr.id, None)
                rr.done.set()
            elif ev.get("end"):
                # evicted/cancelled server-side — the router's
                # drain/requeue owns the request now
                rr._stream_ok = False
            for cb in list(getattr(rr, "_taps", ())):
                try:
                    cb(ev)
                except Exception:                     # noqa: BLE001
                    pass
            return
        if kind in ("drop", "lost", "err"):
            rr._stream_ok = False
            if kind == "drop" and ev.get("reason") in (
                    "unsupported", "unknown_request"):
                rr._stream_denied = True    # server can't stream this
            if not rr.done.is_set():
                count_fallback(str(ev.get("reason", kind)))
                self._reset_result_backoff()   # poll lane, eagerly

    def stream_tap(self, rr: RemoteRequest, cb) -> "callable":
        """Register a callback on ``rr``'s live event feed (the
        router's stream bridge). Returns the detach callable."""
        taps = rr.__dict__.setdefault("_taps", [])
        taps.append(cb)

        def _detach(taps=taps, cb=cb):
            try:
                taps.remove(cb)
            except ValueError:
                pass
        return _detach

    #: load reported while the engine is UNREACHABLE (a failed verb or
    #: status poll): effectively infinite, so least-loaded dispatch
    #: steers new work to healthy peers during the staleness window
    #: before the router declares the replica dead. Self-correcting —
    #: the next successful poll restores the real load.
    _SUSPECT_LOAD = 1 << 30

    def _mark_suspect(self) -> None:
        self._status = dict(self._status, load=self._SUSPECT_LOAD)

    # -- engine duck type (what Router calls) --------------------------------
    @property
    def load(self) -> int:
        return int(self._status.get("load", 0))

    @property
    def weight_version(self) -> int:
        return int(self._status.get("weight_version", 0))

    @property
    def block_size(self) -> int:
        """The remote arena's block size (0 until the first ESTATUS
        answers) — the prefix directory hashes at this granularity."""
        return int(self._status.get("block_size", 0))

    def has_work(self) -> bool:
        return bool(self._status.get("has_work", False))

    def submit(self, prompt: Sequence[int],
               sampling: Optional[SamplingParams] = None, *,
               resume: Optional[SpillEntry] = None,
               handoff: bool = False,
               traceparent: Optional[str] = None) -> RemoteRequest:
        sampling = sampling or SamplingParams()
        if traceparent is None and resume is not None:
            traceparent = resume.traceparent
        rr = RemoteRequest(prompt, sampling, handoff=handoff,
                           traceparent=traceparent)
        if handoff:
            # PREFILL blocks server-side until the KV is ready — run it
            # on its own connection + thread so dispatch stays snappy
            threading.Thread(target=self._prefill_call, args=(rr,),
                             daemon=True,
                             name=f"prefill-{self.port}").start()
            return rr
        try:
            with self._lock:
                doc = self._client().serving_submit_info(
                    rr.prompt, resume=spill_to_wire(resume)
                    if resume is not None else None,
                    traceparent=rr.traceparent,
                    **_sampling_kw(sampling))
        except Exception as e:                        # noqa: BLE001
            if _is_rejection(e):
                rr.status, rr.error = "rejected", str(e)
                rr.done.set()
                return rr
            # unreachable / flaky transport (retries exhausted): mark
            # the request so the router monitor requeues it onto a
            # peer even while this replica's beats stay fresh — a
            # transient failure must never strand a request forever
            self._drop_client()
            self._mark_suspect()
            rr.status = "transport_failed"
            rr.error = f"transport: {e}"
            return rr
        rr.id = int(doc["id"])
        rr.trace_id = doc.get("trace_id", rr.trace_id)
        if resume is not None and doc.get("resumed"):
            rr.spill = resume          # identity marker the router reads
        rr.status = "dispatched"
        self._pending[rr.id] = rr
        # push first: a healthy subscription delivers the result the
        # step it commits; the eager poll reset only matters when the
        # stream is unavailable (then the poll lane carries the load)
        if not self._subscribe_stream(rr):
            self._reset_result_backoff()
        return rr

    def _prefill_call(self, rr: RemoteRequest) -> None:
        try:
            cli = self._client(fresh=True,
                               timeout=self._swap_timeout_s)
            try:
                doc = cli.serving_prefill(rr.prompt,
                                          traceparent=rr.traceparent,
                                          **_sampling_kw(rr.sampling))
            finally:
                cli.close()
        except Exception as e:                        # noqa: BLE001
            if _is_rejection(e):
                rr.status, rr.error = "rejected", str(e)
                rr.done.set()
                return
            self._mark_suspect()
            rr.status = "transport_failed"    # monitor requeues onto
            rr.error = f"transport: {e}"      # a peer (or back here)
            return
        rr.id = int(doc["id"])
        rr.trace_id = doc.get("trace_id", rr.trace_id)
        if doc.get("done"):
            rr._fill_from(doc["result"])
            rr.done.set()
            return
        rr.tokens = list(doc.get("tokens", []))
        rr.weight_version = int(doc.get("weight_version", 0))
        rr.first_token_s = time.monotonic()
        rr.spill = spill_from_wire(doc["spill"])
        rr.status = "prefilled"       # the router monitor takes it from
        #                               here (evict → stream → requeue)

    def cancel_queued(self, ids=None) -> list[RemoteRequest]:
        want = [rid for rid in (ids if ids is not None
                                else list(self._pending))
                if rid in self._pending and rid >= 0]
        if not want:
            return []
        try:
            with self._lock:
                doc = self._client().serving_cancel_queued(want)
        except Exception:                             # noqa: BLE001
            self._drop_client()
            return []
        out = []
        for c in doc.get("cancelled", []):
            rr = self._pending.pop(int(c["id"]), None)
            if rr is None:
                continue
            rr.status = "cancelled"
            if c.get("spill") is not None:
                rr.spill = spill_from_wire(c["spill"])
            out.append(rr)
        return out

    def evict_request(self, req: RemoteRequest, *,
                      lock_timeout_s: Optional[float] = None
                      ) -> Optional[SpillEntry]:
        if req.spill is not None:
            # the PREFILL round trip already carried the KV
            entry, req.spill = req.spill, None
            req.status = "evicted"
            self._pending.pop(req.id, None)
            return entry
        try:
            with self._lock:
                doc = self._client().serving_evict(
                    req.id, lock_timeout_s=lock_timeout_s,
                    traceparent=getattr(req, "traceparent", None)
                    or telemetry.make_traceparent(req.trace_id))
        except Exception:                             # noqa: BLE001
            self._drop_client()
            return None                # salvage is best-effort
        req.status = doc.get("status", req.status)
        if req.status in ("evicted", "cancelled"):
            self._pending.pop(req.id, None)
        if doc.get("spill") is None:
            return None
        return spill_from_wire(doc["spill"])

    def swap_from_checkpoint(self, path: str, version: int) -> dict:
        """The remote leg of a ``transport="dist_ckpt"`` weight push:
        the engine process loads ``path`` (shared filesystem / blob
        store) onto its own topology and swaps. Own connection — a
        large load must not block status polls."""
        cli = self._client(fresh=True, timeout=self._swap_timeout_s)
        try:
            return cli.serving_swap_weights(
                path, version,
                traceparent=telemetry.current_traceparent())
        finally:
            cli.close()

    # -- fleet-global KV plane (ISSUE 18) ------------------------------------
    def export_prefix(self, tokens) -> Optional[SpillEntry]:
        """KVEXPORT: gather this replica's cached whole-block prefix of
        ``tokens`` into a SpillEntry (None on miss / transport loss —
        a pull is always best-effort, the puller just prefills)."""
        try:
            with self._lock:
                doc = self._client().serving_kv_export(
                    [int(t) for t in tokens])
        except Exception:                             # noqa: BLE001
            self._drop_client()
            return None
        if not doc or doc.get("spill") is None:
            return None
        return spill_from_wire(doc["spill"])

    def import_prefix(self, entry: SpillEntry) -> bool:
        """KVIMPORT: map a peer-exported prefix into the remote
        replica's prefix cache. False = refused (stale weight version,
        layout mismatch, arena full) or transport loss — the caller
        falls back to a plain prefill."""
        try:
            with self._lock:
                doc = self._client().serving_kv_import(
                    spill_to_wire(entry))
        except Exception:                             # noqa: BLE001
            self._drop_client()
            return False
        return bool(doc and doc.get("ok"))

    def _kv_client(self):
        from hetu_tpu.rpc.client import CoordinatorClient
        if self._kv_cli is None:
            # replication is a steady block stream — give it its own
            # socket so big shipments never starve the status poller
            self._kv_cli = CoordinatorClient(
                self.port, host=self.host, token=self._token,
                timeout=self._timeout_s, retries=1, backoff_s=0.02)
        return self._kv_cli

    def kv_put(self, doc: dict) -> None:
        """KVREPL: deliver one replication shipment to the remote
        buddy's :class:`KVReplicaStore`. Raises on transport loss —
        the origin's replication thread absorbs and retries next
        cadence."""
        with self._kv_lock:
            try:
                self._kv_client().serving_kv_put(doc)
            except Exception:
                if self._kv_cli is not None:
                    try:
                        self._kv_cli.close()
                    except OSError:
                        pass
                    self._kv_cli = None
                raise

    def kv_fetch(self, trace_id: str) -> Optional[SpillEntry]:
        """KVFETCH: assemble the buddy-held replica set for
        ``trace_id`` into a resumable SpillEntry (None = no/partial
        coverage — recovery replays from the prompt instead)."""
        try:
            with self._lock:
                doc = self._client().serving_kv_fetch(trace_id)
        except Exception:                             # noqa: BLE001
            self._drop_client()
            return None
        if not doc or doc.get("spill") is None:
            return None
        return spill_from_wire(doc["spill"])

    def set_kv_buddy(self, host: Optional[str], port: int = 0, *,
                     token: Optional[str] = None, origin: str = "",
                     cadence_s: float = 0.02) -> bool:
        """KVBUDDY: point the remote engine's replication stream at a
        buddy replica (``host=None`` disables it)."""
        try:
            with self._lock:
                self._client().serving_kv_buddy(
                    host, port, token=token, origin=origin,
                    cadence_s=cadence_s)
            return True
        except Exception:                             # noqa: BLE001
            self._drop_client()
            return False

    # -- federation scrape (Router._tick → FLEETMETRICS/fleet HEALTHZ) -------
    def metrics_text(self) -> str:
        """This replica's Prometheus exposition page."""
        with self._lock:
            return self._client().metrics_text()

    def healthz(self) -> dict:
        with self._lock:
            return self._client().healthz()

    def dump_obs(self) -> dict:
        """The replica's DUMPOBS bundle (chrome trace + flight ring) —
        what ``tools/fleet_trace.py`` collects for the merge. Fresh
        connection: a big trace dump must not starve status polls."""
        cli = self._client(fresh=True, timeout=self._swap_timeout_s)
        try:
            return cli.dump_obs()
        finally:
            cli.close()

    # -- lifecycle -----------------------------------------------------------
    def start(self, idle_sleep_s: float = 0.0) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop_ev = threading.Event()
        self._thread = threading.Thread(
            target=self._poll_loop, daemon=True,
            name=f"remote-engine-poll-{self.port}")
        self._thread.start()

    def stop(self) -> None:
        if self._stop_ev is not None:
            self._stop_ev.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        try:
            cli = self._client(fresh=True, timeout=2.0)
            try:
                cli.serving_stop_engine()
            finally:
                cli.close()
        except Exception:                             # noqa: BLE001
            pass                       # the process may already be gone
        self._drop_client()
        with self._schan_lock:
            if self._schan is not None:
                try:
                    self._schan.close()
                except Exception:                     # noqa: BLE001
                    pass
                self._schan = None
        with self._kv_lock:
            if self._kv_cli is not None:
                try:
                    self._kv_cli.close()
                except OSError:
                    pass
                self._kv_cli = None

    # -- the poller ----------------------------------------------------------
    def _poll_loop(self) -> None:
        while not self._stop_ev.is_set():
            self._poll_once()
            self._stop_ev.wait(self._poll_s)

    def _poll_once(self) -> bool:
        # ESTATUS coalesced with stream liveness (ISSUE 19 satellite):
        # with a healthy subscription channel the per-tick status poll
        # stretches to heartbeat-only cadence. ESTATUS stays the beat —
        # skipping it only delays the ``last_beat`` stamp by at most
        # ``heartbeat_s``, which must stay well under the router's
        # ``beat_timeout_s`` for SIGKILL reaping to keep its deadline.
        now = time.monotonic()
        ch = self._schan
        if self.use_stream and ch is not None and ch.alive \
                and now < self._next_beat:
            return self._poll_results()
        try:
            with self._lock:
                t0 = time.time()
                self._status = self._client().serving_estatus()
                t1 = time.time()
        except Exception:                             # noqa: BLE001
            self._drop_client()
            self._mark_suspect()
            return False               # no beat: staleness accumulates
        self._next_beat = time.monotonic() + self._heartbeat_s
        srv_ts = self._status.get("ts_unix")
        if srv_ts is not None:
            # NTP-style offset handshake (ISSUE 16): the replica
            # stamped its wall clock mid-RTT, so its offset from ours
            # is its stamp minus the RTT midpoint. Re-measured on every
            # poll — the merge tool reads the freshest value and the
            # skew gauge lets an operator spot a drifting host.
            off = float(srv_ts) - 0.5 * (t0 + t1)
            self.clock_offset_s = off
            name = self._handle.name if self._handle is not None \
                else f":{self.port}"
            telemetry.get_registry().gauge(
                "fleet_clock_skew_seconds",
                "per-replica wall-clock offset vs this process, "
                "measured at each status poll (replica label)").set(
                round(off, 6), replica=name)
        if self._handle is not None:
            self._handle.last_beat = time.monotonic()
        return self._poll_results()

    def _poll_results(self) -> bool:
        """The RESULT lane: streamed requests are skipped (push owns
        them); a request whose stream was lost first tries a
        resubscribe-at-offset, then polls — loudly counted either
        way."""
        if time.monotonic() < self._next_result_poll:
            return True                # RESULT lane is backing off
        adopted = polled = 0
        for rid, rr in list(self._pending.items()):
            if rr.done.is_set() or rr.status in ("prefilled",
                                                 "evicted",
                                                 "cancelled"):
                continue
            if getattr(rr, "_stream_ok", False):
                continue               # the push lane owns this one
            if self.use_stream and rid >= 0 \
                    and not getattr(rr, "_stream_denied", False) \
                    and self._subscribe_stream(rr, resume=True):
                continue               # back on the push lane, resumed
            #                            exactly at len(rr.tokens)
            polled += 1
            try:
                with self._lock:
                    doc = self._client().serving_result(rid,
                                                        timeout_ms=0)
            except Exception:                         # noqa: BLE001
                self._drop_client()
                return False
            if doc is None:
                # the poll cycle burned a RESULT round trip for nothing
                # — the empty-poll fraction is the case for streaming
                # RESULT (ROADMAP); bench.py --fleet records it
                telemetry.get_registry().counter(
                    "router_result_poll_empty_total",
                    "RESULT polls that returned PEND (wasted round "
                    "trips — the streaming-RESULT motivation)").inc()
                continue
            rr._fill_from(doc)
            self._pending.pop(rid, None)
            rr.done.set()
            adopted += 1
        if adopted:
            self._reset_result_backoff()
        elif polled:
            # every in-flight RESULT answered PEND: widen the gap
            self._result_delay = min(self._poll_max_s,
                                     self._result_delay * 2)
            self._next_result_poll = time.monotonic() \
                + self._result_delay
        return True

    def _reset_result_backoff(self) -> None:
        self._result_delay = self._poll_s
        self._next_result_poll = 0.0


def _sampling_kw(sp: SamplingParams) -> dict:
    kw = {"temperature": sp.temperature, "top_k": sp.top_k,
          "top_p": sp.top_p, "eos_id": sp.eos_id,
          "max_tokens": sp.max_tokens, "priority": sp.priority}
    if getattr(sp, "tenant", None) is not None:
        kw["tenant"] = sp.tenant
    if getattr(sp, "adapter", None) is not None:
        kw["adapter"] = sp.adapter
    return kw


def _is_rejection(e: Exception) -> bool:
    """Admission rejections come back as ``ERR rejected: ...`` lines
    the client surfaces as RuntimeError — terminal, not transport."""
    return isinstance(e, RuntimeError) and "rejected" in str(e)


# -- the replica handle -------------------------------------------------------


class RemoteReplicaHandle(ReplicaHandle):
    """A :class:`ReplicaHandle` whose replica lives in ANOTHER process.

    Liveness inverts: there is no loop thread to watch, so
    ``loop_alive()``/``loop_died()`` are always False and death comes
    exclusively from **heartbeat staleness** — the proxy's poller
    stamps ``last_beat`` on every successful status round trip, and the
    router's existing ``beat_timeout_s`` check declares the replica
    dead when the beats stop (process SIGKILLed, host gone, network
    partitioned). Registration itself counts as the first beat, so a
    replica that never answers is reaped after one timeout instead of
    living forever."""

    remote = True

    def __init__(self, name: str, proxy: RemoteEngineProxy):
        super().__init__(name, proxy)        # type: ignore[arg-type]
        proxy._handle = self
        self.last_beat = time.monotonic()    # registration = beat 0

    def loop_alive(self) -> bool:
        return False

    def loop_died(self) -> bool:
        return False

    def status(self) -> dict:
        doc = super().status()
        doc["remote"] = True
        doc["beat_age_s"] = round(
            time.monotonic() - self.last_beat, 3) \
            if self.last_beat is not None else None
        doc["clock_offset_s"] = round(
            getattr(self.engine, "clock_offset_s", 0.0), 6)
        return doc


# -- the engine-process entry point -------------------------------------------


def replica_main() -> int:
    """Entry point of one fleet engine process
    (``python -m hetu_tpu.serving.fleet``, spawned by
    ``rpc/launcher.launch_serving_fleet(remote=True)``).

    Env contract:

    - ``HETU_ENGINE_SPEC``   — ``module:function``; called with the
      replica index, must return a ready ServingEngine (the fleet
      analogue of the launcher's ``build_engine(i)``)
    - ``HETU_REPLICA_INDEX`` — this replica's index (default 0)
    - ``HETU_REPLICA_NAME``  — this replica's fleet name
    - ``HETU_REPLICA_ROLE``  — ``prefill``/``decode``/``both``
      (observability identity only — the router owns actual placement)
    - ``HETU_ENGINE_PORT``   — the line-protocol port to serve on
    - ``HETU_ENGINE_TOKEN``  — optional bearer token
    - ``HETU_TELEMETRY``     — ``1`` turns the tracer/registry on, so
      DUMPOBS bundles carry real spans for ``tools/fleet_trace.py``

    Serves until SIGTERM (clean launcher teardown); SIGKILL is the
    chaos path — the router's heartbeat staleness handles it.
    """
    import importlib
    import os
    import signal

    spec = os.environ["HETU_ENGINE_SPEC"]
    idx = int(os.environ.get("HETU_REPLICA_INDEX", "0"))
    port = int(os.environ["HETU_ENGINE_PORT"])
    name = os.environ.get("HETU_REPLICA_NAME", f"r{idx}")
    token = os.environ.get("HETU_ENGINE_TOKEN", "")
    if os.environ.get("HETU_TELEMETRY", "") not in ("", "0"):
        telemetry.enable(True)
    # stamp fleet identity into the flight recorder BEFORE the engine
    # builds, so even a crash-during-init dump says who it was
    telemetry.get_flight_recorder().set_identity(
        replica=name, role=os.environ.get("HETU_REPLICA_ROLE"))
    mod_name, fn_name = spec.split(":")
    build = getattr(importlib.import_module(mod_name), fn_name)
    engine = build(idx)

    from hetu_tpu.serving.server import ServingServer
    srv = ServingServer(engine, port, token=token)
    srv.start()
    srv.wait_ready()
    get_logger().info(
        f"fleet replica {name} (index {idx}) serving on :{port}")
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    try:
        stop.wait()
    except KeyboardInterrupt:
        pass
    srv.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(replica_main())
