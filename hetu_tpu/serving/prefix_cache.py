"""Radix-tree prefix cache over the paged KV pool.

RadixAttention (SGLang) observation: serving traffic is massively
prefix-shared — system prompts, few-shot preambles, multi-turn
histories — and KV for position ``p`` depends only on tokens
``0..p``, so any request whose prompt extends a cached prefix can MAP
the cached blocks instead of re-prefilling them. This module is the
host-side index that makes that lookup O(prompt):

- a trie whose nodes each own ONE whole block (``block_size`` token
  ids as the edge label, the arena block id as the payload);
- :meth:`match` walks the prompt: every fully-matching block is shared
  into the new request's table (refcount++ via the
  :class:`~hetu_tpu.serving.kv_pool.BlockManager`), and a PARTIAL
  match inside the next block returns a copy-on-write source — the
  engine copies that block device-side and the request's prefill
  starts at the first uncached token;
- :meth:`insert` runs when a request finishes prefilling: its prompt's
  whole blocks become trie nodes (the trie takes a ref, so the blocks
  outlive the request);
- when the free list runs dry, :meth:`evict` LRU-reclaims LEAF nodes
  whose block nobody else holds (refcount == 1) — interior nodes wait
  until their subtree drains, so a cached prefix never dangles.

Everything here is pure host bookkeeping (no jax): block ids flow into
the compiled step as traced table entries, so cache hits, misses and
evictions all re-run ONE program.
"""

from __future__ import annotations

import heapq
from typing import Optional, Sequence

from hetu_tpu.serving.kv_pool import NULL_BLOCK, BlockManager


class _Node:
    """One cached whole block: edge label ``tokens`` (block_size ids),
    payload ``block`` (arena id), LRU stamp ``last_use``, the
    ``version`` of the weights whose forward wrote the block's KV, and
    the ``adapter`` uid that forward ran under (0 = base — an
    attention-targeting LoRA adapter writes DIFFERENT K/V for the same
    tokens, so its spans only ever match requests of the same adapter
    load; see ``serving/tenancy.py``)."""

    __slots__ = ("tokens", "block", "parent", "children", "last_use",
                 "version", "adapter")

    def __init__(self, tokens: tuple, block: int,
                 parent: Optional["_Node"], last_use: int,
                 version: int = 0, adapter: int = 0):
        self.tokens = tokens
        self.block = block
        self.parent = parent
        self.children: list[_Node] = []
        self.last_use = last_use
        self.version = version
        self.adapter = adapter


def _common_prefix_len(a: Sequence[int], b: Sequence[int]) -> int:
    n = 0
    for x, y in zip(a, b):
        if x != y:
            break
        n += 1
    return n


class PrefixCache:
    """Token-id trie mapping whole prompt blocks to arena block ids."""

    def __init__(self, block_size: int, blocks: BlockManager):
        self.block_size = int(block_size)
        self.blocks = blocks
        self._root = _Node((), NULL_BLOCK, None, 0)
        self._clock = 0
        self.hits = 0            # host ledgers (telemetry reads deltas)
        self.evictions = 0
        #: weight generation the cached KV was computed under. A live
        #: weight push bumps this via :meth:`set_version`, which flushes
        #: every stale node — and :meth:`match` ALSO refuses stale nodes
        #: (defense in depth: a missed flush must degrade to a cache
        #: miss, never to serving tokens prefilled under old weights).
        self.weight_version = 0
        self.flushes = 0

    # -- lookup -------------------------------------------------------------
    def match(self, tokens: Sequence[int], adapter: int = 0) -> tuple[
            list[int], Optional[tuple[int, int]]]:
        """Longest cached prefix of ``tokens``.

        Returns ``(shared, partial)``: ``shared`` is the list of arena
        block ids whose whole ``block_size``-token runs match (in
        order), ``partial`` is ``(src_block, n_rows)`` when the match
        continues ``n_rows`` tokens into one more cached block (the
        engine copies it — CoW — because the request will write its own
        rows there). Takes NO refs — the caller shares what it actually
        maps. Touches LRU stamps along the path.

        ``adapter`` is the requesting stream's KV-compat uid: only
        nodes written under the SAME adapter load match (0 = base;
        cross-adapter spans hold different K/V for identical tokens,
        so a mismatched hit would silently serve another tenant's
        activations)."""
        bs = self.block_size
        adapter = int(adapter)
        self._clock += 1
        shared: list[int] = []
        node = self._root
        i = 0
        while len(tokens) - i >= 1:
            key = tuple(tokens[i:i + bs])
            child = None
            if len(key) == bs:
                child = next(
                    (c for c in node.children if c.tokens == key
                     and c.version == self.weight_version
                     and c.adapter == adapter), None)
            if child is not None:
                child.last_use = self._clock
                shared.append(child.block)
                node = child
                i += bs
                continue
            # partial tail: the child sharing the longest token prefix
            # (stale-version nodes hold KV from old weights — never
            # matchable, whole or partial; same for foreign adapters)
            best, best_len = None, 0
            for c in node.children:
                if c.version != self.weight_version \
                        or c.adapter != adapter:
                    continue
                n = _common_prefix_len(c.tokens, key)
                if n > best_len:
                    best, best_len = c, n
            if best is not None:
                best.last_use = self._clock
                return shared, (best.block, best_len)
            break
        return shared, None

    # -- insertion ----------------------------------------------------------
    def insert(self, tokens: Sequence[int], table: Sequence[int],
               adapter: int = 0) -> int:
        """Cache ``tokens``' whole blocks, backed by the arena blocks in
        ``table`` (the request's block table, position-ordered). New
        nodes take a ref on their block so it survives the request's
        release; blocks already cached (the shared ones) are left
        alone. ``adapter`` tags the nodes with the KV-compat uid the
        forward ran under (0 = base). Returns the number of new
        nodes."""
        bs = self.block_size
        adapter = int(adapter)
        self._clock += 1
        node = self._root
        added = 0
        for j in range(len(tokens) // bs):
            key = tuple(tokens[j * bs:(j + 1) * bs])
            child = next(
                (c for c in node.children if c.tokens == key
                 and c.version == self.weight_version
                 and c.adapter == adapter), None)
            if child is None:
                blk = int(table[j])
                if blk == NULL_BLOCK:
                    break
                child = _Node(key, blk, node, self._clock,
                              self.weight_version, adapter)
                node.children.append(child)
                self.blocks.share(blk)      # the trie now holds it too
                added += 1
            child.last_use = self._clock
            node = child
        return added

    # -- eviction -----------------------------------------------------------
    def evict(self, n: int) -> int:
        """Free up to ``n`` blocks by dropping the LRU leaf nodes nobody
        else holds (block refcount == 1 — trie-only). Returns how many
        were actually freed; 0 means every cached block is pinned by a
        live request."""
        freed = 0
        # one DFS seeds a last_use min-heap of the current leaves;
        # parents are promoted lazily as their last child goes. Pinned
        # leaves (refcount > 1) are discarded at pop — refcounts can't
        # drop under us (the engine lock holds and we only release
        # victims), so a discarded pin never becomes evictable here
        heap: list[tuple[int, int, _Node]] = []
        stack = list(self._root.children)
        while stack:
            c = stack.pop()
            if c.children:
                stack.extend(c.children)
            else:
                heapq.heappush(heap, (c.last_use, id(c), c))
        while freed < n and heap:
            _, _, victim = heapq.heappop(heap)
            if victim.children or self.blocks.refs[victim.block] != 1:
                continue
            parent = victim.parent
            parent.children.remove(victim)
            self.blocks.release(victim.block)
            freed += 1
            if parent is not self._root and not parent.children:
                heapq.heappush(heap, (parent.last_use, id(parent),
                                      parent))
        self.evictions += freed
        return freed

    # -- weight-version lifecycle -------------------------------------------
    def set_version(self, version: int) -> int:
        """Adopt a new weight generation and flush every node cached
        under an older one (their KV encodes the OLD weights' forward —
        mapping them after a live weight push would silently serve
        tokens prefilled under stale parameters). Returns the number of
        blocks released back to the free list. No-op at the current
        version."""
        version = int(version)
        if version == self.weight_version:
            return 0
        self.weight_version = version
        return self.flush_stale()

    def flush_stale(self) -> int:
        """Drop every node whose ``version`` predates the current one,
        releasing the trie's ref on each block (a block still mapped by
        a live slot stays resident for that holder — refcounts make the
        flush safe at any moment, drained or not). Stale interior nodes
        take their whole subtree with them: a child's KV attends into
        its parent's positions, so a fresh-version child under a stale
        parent is unreachable anyway (match walks from the root)."""
        freed = 0
        stack = [self._root]
        while stack:
            node = stack.pop()
            keep: list[_Node] = []
            for c in node.children:
                if c.version == self.weight_version:
                    keep.append(c)
                    stack.append(c)
                else:
                    # release the subtree rooted here (DFS, trie refs)
                    sub = [c]
                    while sub:
                        v = sub.pop()
                        sub.extend(v.children)
                        self.blocks.release(v.block)
                        freed += 1
            node.children = keep
        self.flushes += freed
        return freed

    def flush_adapter(self, adapter: int) -> int:
        """Drop every node written under adapter uid ``adapter`` (an
        evicted/replaced adapter's spans: already unmatchable — a new
        load gets a fresh uid — but still pinning blocks; this returns
        them eagerly instead of waiting on LRU pressure). Whole
        subtrees go together: insert walks same-adapter chains, so a
        node's descendants share its tag. Never flushes base (0)."""
        adapter = int(adapter)
        if adapter == 0:
            return 0
        freed = 0
        stack = [self._root]
        while stack:
            node = stack.pop()
            keep: list[_Node] = []
            for c in node.children:
                if c.adapter != adapter:
                    keep.append(c)
                    stack.append(c)
                else:
                    sub = [c]
                    while sub:
                        v = sub.pop()
                        sub.extend(v.children)
                        self.blocks.release(v.block)
                        freed += 1
            node.children = keep
        self.flushes += freed
        return freed

    # -- introspection ------------------------------------------------------
    @property
    def cached_blocks(self) -> int:
        n, stack = 0, list(self._root.children)
        while stack:
            c = stack.pop()
            n += 1
            stack.extend(c.children)
        return n
