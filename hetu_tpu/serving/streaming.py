"""Token push subscriptions (the engine half of the streaming control
plane, ISSUE 19).

A :class:`TokenSubscription` is a bounded queue of token EVENTS for one
request. The producer — ``ServingEngine._pump_stream_subs`` at the end
of every fused-step commit, or the fleet Router's stream bridge — calls
:func:`push_delta`, which folds the request's newly committed tokens
into one event and enqueues it WITHOUT blocking: a slow or dead
consumer overflows its own queue, is marked ``dropped`` (counted), and
degrades to RESULT polling; the step loop never waits on a socket.

Every event carries a per-request MONOTONIC TOKEN OFFSET (``off`` = how
many generated tokens preceded this delta), so a subscriber that
reconnects passes the count it already holds and the replay starts
exactly there — nothing lost, nothing duplicated, across socket drops
AND replica failovers (a KV-resumed request preloads its token list, so
offsets stay globally consistent).

Pure stdlib — importable by the jax-free coordinator.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Optional


def _registry():
    from hetu_tpu import telemetry
    return telemetry.get_registry()


def count_subscribe(mode: str) -> None:
    """``mode="new"`` for a first subscription, ``"resume"`` for a
    resubscribe-at-offset after a stream loss."""
    try:
        _registry().counter(
            "serving_stream_subscribes_total",
            "token-stream subscriptions by mode (new vs "
            "resubscribe-at-offset after a stream loss)").inc(mode=mode)
    except Exception:                                 # noqa: BLE001
        pass


def count_fallback(reason: str) -> None:
    """One subscriber fell back from push to RESULT polling."""
    try:
        _registry().counter(
            "serving_stream_fallbacks_total",
            "stream-loss fallbacks to the RESULT poll lane, by reason "
            "(the poll lane survives only as this loud fallback)").inc(
            reason=reason)
    except Exception:                                 # noqa: BLE001
        pass


class TokenSubscription:
    """Bounded per-subscriber event queue for one request's tokens.

    ``sent`` is the subscription's token cursor: the number of
    generated tokens already folded into events. The producer advances
    it; the consumer (a drainer thread writing frames, or a local
    iterator) only reads events. ``dropped`` flips when the queue
    overflows — the producer stops feeding it and the drainer tells
    the subscriber to fall back to polling.
    """

    def __init__(self, req_id: int, *, offset: int = 0,
                 max_queue: int = 256):
        self.req_id = int(req_id)
        self.sent = max(0, int(offset))
        self._q: queue.Queue = queue.Queue(maxsize=max(1, int(max_queue)))
        self.dropped = False
        self.closed = False
        self._close_ev = threading.Event()

    def emit(self, ev: dict) -> bool:
        """Enqueue one event; never blocks. False = subscriber lost
        (queue full → dropped, or already closed)."""
        if self.dropped or self.closed:
            return False
        try:
            self._q.put_nowait(ev)
            return True
        except queue.Full:
            self.dropped = True
            try:
                _registry().counter(
                    "serving_stream_subscriber_drops_total",
                    "subscriptions dropped because their bounded event "
                    "queue overflowed (slow/dead consumer degraded to "
                    "RESULT polling — the step loop never stalls)").inc()
            except Exception:                         # noqa: BLE001
                pass
            return False

    def get(self, timeout: Optional[float] = None) -> Optional[dict]:
        """Dequeue the next event (None on timeout)."""
        try:
            if timeout is None:
                return self._q.get_nowait()
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None

    def close(self) -> None:
        self.closed = True
        self._close_ev.set()


def delta_event(req, sub: TokenSubscription, *,
                now: Optional[float] = None) -> Optional[dict]:
    """Build the next event for ``sub`` from ``req``'s current state
    and advance the cursor; None when nothing new happened.

    ``req`` is duck-typed (engine Request / RemoteRequest /
    RouterRequest): ``id``, ``trace_id``, ``tokens``, ``status``,
    ``done``, ``result()``. Terminal states fold the full ``result()``
    (the trailing timing payload) into the final frame; an out-of-band
    exit (evicted / cancelled / P-D handoff park) emits ``end`` so the
    subscriber falls back — the router's requeue owns the request now.
    """
    n = len(req.tokens)
    terminal = req.done.is_set()
    interrupted = (not terminal) and req.status in (
        "evicted", "cancelled", "prefilled")
    if n <= sub.sent and not terminal and not interrupted:
        return None
    toks = [int(t) for t in list(req.tokens)[sub.sent:n]]
    ev = {"req": int(req.id), "trace": req.trace_id,
          "off": sub.sent, "toks": toks,
          "first": sub.sent == 0 and n > 0,
          "done": bool(terminal),
          "ts": round(time.monotonic() if now is None else now, 6)}
    sub.sent = n
    if terminal:
        ev["result"] = req.result()
    elif interrupted:
        ev["end"] = req.status
    return ev


def push_delta(req, sub: TokenSubscription, *,
               now: Optional[float] = None) -> Optional[dict]:
    """``delta_event`` + enqueue + accounting; closes the subscription
    on its terminal frame. Returns the event (even if the enqueue was
    refused — the caller can tell from ``sub.dropped``)."""
    ev = delta_event(req, sub, now=now)
    if ev is None:
        return None
    if sub.emit(ev):
        try:
            reg = _registry()
            reg.counter(
                "serving_stream_events_total",
                "token events pushed into subscriber queues (one per "
                "request per step with news)").inc()
            if ev["toks"]:
                reg.counter(
                    "serving_stream_tokens_total",
                    "tokens delivered via push subscriptions (vs the "
                    "RESULT poll lane)").inc(len(ev["toks"]))
        except Exception:                             # noqa: BLE001
            pass
    if ev.get("done") or ev.get("end"):
        sub.close()
    return ev
