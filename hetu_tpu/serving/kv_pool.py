"""Slot-pooled KV cache: one fixed-shape arena for request churn.

The training side already solved "dynamic work on static shapes" twice
(fixed KV buffers + ``dynamic_update_slice`` in ``models/generation``,
fixed-capacity expert buffers in MoE); this module applies the same idiom
to SERVING. Instead of one cache per request (vLLM allocates pages, the
reference dynamically concats KV), the pool is a single
``(layers, slots, max_len, kv_heads, head_dim)`` arena allocated once:

- a request of ANY length maps onto one free slot — admission is a host
  bookkeeping operation, never an allocation, so the engine step keeps
  one compiled signature across arbitrary request churn;
- per-slot depth lives in the engine's control vectors (``pos``), and
  the per-row causal mask guarantees a reused slot never attends a
  previous tenant's stale rows (every attended position was written by
  the current request first);
- the fp32/bf16/int8 layouts are exactly
  ``generation.init_kv_caches`` — the int8 pool quarters decode's HBM
  bandwidth (the serving bottleneck) with per-(position, head) scales.

Sizing is delegated to the memory-plane ledger
(:func:`hetu_tpu.engine.memory.size_kv_pool`): slots are whatever HBM
remains next to the weights, so the scheduler's admission gate and the
planner price bytes with the same arithmetic.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from hetu_tpu.models.generation import init_kv_caches


def cache_dtype_name(dtype) -> str:
    """Canonical ledger name for a cache dtype (fp32 | bf16 | int8)."""
    if dtype == jnp.int8:
        return "int8"
    if dtype == jnp.bfloat16:
        return "bf16"
    return "fp32"


class KVPool:
    """The slot arena plus its shape metadata (free-slot bookkeeping
    belongs to the scheduler; the pool is just bytes)."""

    def __init__(self, model, slots: int, max_len: int,
                 cache_dtype=jnp.float32):
        max_positions = getattr(getattr(model, "cfg", None),
                                "max_positions", None)
        if max_positions is not None and max_len > max_positions:
            raise ValueError(
                f"pool max_len {max_len} exceeds the model's "
                f"max_positions {max_positions}")
        self.slots = int(slots)
        self.max_len = int(max_len)
        self.cache_dtype = cache_dtype
        self.caches = init_kv_caches(model, self.slots, self.max_len,
                                     cache_dtype)

    @classmethod
    def sized_for(cls, model, *, hbm_budget_bytes: float, max_len: int,
                  cache_dtype=jnp.float32, tp: int = 1,
                  max_slots: Optional[int] = None) -> "KVPool":
        """Build the largest pool the HBM budget allows (ledger-sized)."""
        from hetu_tpu.engine.memory import size_kv_pool
        slots = size_kv_pool(model.cfg,
                             hbm_budget_bytes=hbm_budget_bytes,
                             max_len=max_len,
                             cache_dtype=cache_dtype_name(cache_dtype),
                             tp=tp)
        if max_slots is not None:
            slots = min(slots, max_slots)
        return cls(model, slots, max_len, cache_dtype)

    @property
    def quantized(self) -> bool:
        return len(self.caches) == 4

    def nbytes(self) -> int:
        return sum(int(x.size) * x.dtype.itemsize for x in self.caches)
