"""Block-paged KV cache: one fixed-shape arena, indexed through block
tables.

PR 5's slot arena ((layers, slots, max_len, hkv, d)) solved "dynamic
work on static shapes" but allocated every slot its WORST CASE: a
10-token request in a 2048-token slot wastes 99.5% of its bytes, and a
shared system prompt is stored once per slot. This module is the
PagedAttention answer (vLLM, SOSP'23) mapped onto the jit-once TPU
discipline:

- the arena is ``(layers, n_blocks, block_size, kv_heads, head_dim)``,
  allocated once; a request maps onto a per-slot BLOCK TABLE (fixed
  ``max_len/block_size`` width, padded with the null block 0), and the
  compiled step indexes KV through a gather on the table
  (``ops.attention.gather_block_rows``) — tables are DATA, never
  shapes, so block churn never recompiles;
- blocks are refcounted (:class:`BlockManager`): the radix-tree prefix
  cache (``serving/prefix_cache.py``) maps one physical block into many
  slots' tables, so a fleet-wide system prompt is prefilled once and
  costs one set of pages total;
- the fp32/bf16/int8 layouts are exactly ``generation.init_kv_caches``
  with (batch, max_len) := (n_blocks, block_size) — the int8 pool
  quarters decode's HBM bandwidth with per-(position, head) scales, and
  quantized blocks are shared bit-for-bit like fp blocks.

Sizing is delegated to the memory-plane ledger
(:func:`hetu_tpu.engine.memory.size_kv_blocks`): blocks are whatever
HBM remains next to the weights, so the scheduler's free-block
admission gate and the planner price bytes with the same arithmetic.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Optional

import jax.numpy as jnp
import numpy as np

from hetu_tpu.models.generation import init_kv_caches

#: block table entries point here when a position is unallocated; the
#: null block is never handed out and never written, so its rows stay
#: exact zeros (masked by every live row's causal offset anyway)
NULL_BLOCK = 0


def cache_dtype_name(dtype) -> str:
    """Canonical ledger name for a cache dtype (fp32 | bf16 | int8)."""
    if dtype == jnp.int8:
        return "int8"
    if dtype == jnp.bfloat16:
        return "bf16"
    return "fp32"


class BlockManager:
    """Host-side free list + refcounts over the paged arena.

    Pure bookkeeping (no jax): the device only ever sees block ids as
    traced table entries. A block's refcount is the number of HOLDERS —
    slots whose table maps it, plus the prefix-cache trie when a node
    caches it. ``release`` returns it to the free list at zero; blocks
    are never zeroed on reuse (the per-row causal mask guarantees a
    reused block is written before it is attended).
    """

    def __init__(self, n_blocks: int):
        if n_blocks < 2:
            raise ValueError("need at least one non-null block")
        self.n_blocks = int(n_blocks)
        self.free: deque[int] = deque(range(1, self.n_blocks))
        self.refs = np.zeros(self.n_blocks, np.int32)

    def alloc(self) -> Optional[int]:
        """Pop a free block (refcount 1), or None when the pool is dry
        (the caller evicts prefix-cache leaves and retries)."""
        if not self.free:
            return None
        b = self.free.popleft()
        self.refs[b] = 1
        return b

    def share(self, block: int) -> None:
        """Add a holder to an already-live block (prefix hit / trie)."""
        if block == NULL_BLOCK:
            raise ValueError("cannot share the null block")
        if self.refs[block] <= 0:
            raise ValueError(f"share of dead block {block}")
        self.refs[block] += 1

    def release(self, block: int) -> None:
        """Drop one holder; the block frees when the last one leaves."""
        if block == NULL_BLOCK:
            return
        self.refs[block] -= 1
        if self.refs[block] < 0:
            raise ValueError(f"double release of block {block}")
        if self.refs[block] == 0:
            self.free.append(block)

    @property
    def free_blocks(self) -> int:
        return len(self.free)

    @property
    def blocks_in_use(self) -> int:
        return self.n_blocks - 1 - len(self.free)


class KVPool:
    """The block-paged arena plus its shape metadata (block/refcount
    bookkeeping belongs to :class:`BlockManager` and the scheduler; the
    pool is just bytes).

    ``slots`` remains the engine's max CONCURRENCY (the width of the
    control vectors and block tables); capacity in bytes is now
    ``n_blocks`` — by default one null block plus ``slots`` worst-case
    requests' worth, but prefix sharing means the effective capacity in
    requests is higher.
    """

    def __init__(self, model, slots: int, max_len: int,
                 cache_dtype=jnp.float32, block_size: Optional[int] = None,
                 n_blocks: Optional[int] = None,
                 table_len: Optional[int] = None):
        max_positions = getattr(getattr(model, "cfg", None),
                                "max_positions", None)
        if max_positions is not None and max_len > max_positions:
            raise ValueError(
                f"pool max_len {max_len} exceeds the model's "
                f"max_positions {max_positions}")
        self.slots = int(slots)
        self.max_len = int(max_len)
        self.block_size = int(block_size) if block_size else self.max_len
        if self.max_len % self.block_size != 0:
            raise ValueError(
                f"max_len {max_len} must be a multiple of block_size "
                f"{self.block_size} (block tables have a fixed "
                f"max_len/block_size width)")
        # table_len > max_len widens every slot's BLOCK TABLE (control
        # ints, not arena bytes) so the CP-prefill lane can map requests
        # beyond one slot's admission budget (ServingEngine long_max_len)
        self.table_len = int(table_len) if table_len else self.max_len
        if self.table_len < self.max_len \
                or self.table_len % self.block_size != 0:
            raise ValueError(
                f"table_len {self.table_len} must be a multiple of "
                f"block_size {self.block_size} and >= max_len "
                f"{self.max_len}")
        if max_positions is not None and self.table_len > max_positions:
            raise ValueError(
                f"table_len {self.table_len} exceeds the model's "
                f"max_positions {max_positions}")
        self.blocks_per_slot = self.max_len // self.block_size
        self.table_width = self.table_len // self.block_size
        # default arena: slots worst-case NORMAL requests, plus (when a
        # wide table enables the long lane) headroom for one worst-case
        # LONG request beyond a slot's share
        self.n_blocks = int(n_blocks) if n_blocks else (
            1 + self.slots * self.blocks_per_slot
            + (self.table_width - self.blocks_per_slot))
        if self.n_blocks <= self.table_width:
            raise ValueError(
                f"n_blocks {self.n_blocks} cannot hold even one "
                f"worst-case request ({self.table_width} blocks "
                f"+ the null block)")
        self.cache_dtype = cache_dtype
        #: weight generation whose forward wrote the arena's live
        #: blocks. Bumped by ``ServingEngine.swap_params`` on a live
        #: weight push (HotSPa train→serve): the engine only swaps
        #: drained (no slot holds blocks), and the prefix cache flushes
        #: its stale residents, so every block written after the bump
        #: belongs to the new generation — the tag is how audits (and
        #: the version-tagged prefix trie) tell the two apart.
        self.weight_version = 0
        # the paged arena reuses the generation layouts with
        # (batch, max_len) := (n_blocks, block_size)
        self.caches = init_kv_caches(model, self.n_blocks,
                                     self.block_size, cache_dtype)

    @classmethod
    def sized_for(cls, model, *, hbm_budget_bytes: float, max_len: int,
                  cache_dtype=jnp.float32, tp: int = 1,
                  max_slots: Optional[int] = None,
                  block_size: Optional[int] = None,
                  table_len: Optional[int] = None) -> "KVPool":
        """Build the largest pool the HBM budget allows (ledger-sized:
        whole worst-case slots, so admission can never strand a request
        that passed the budget gate)."""
        from hetu_tpu.engine.memory import size_kv_pool
        slots = size_kv_pool(model.cfg,
                             hbm_budget_bytes=hbm_budget_bytes,
                             max_len=max_len,
                             cache_dtype=cache_dtype_name(cache_dtype),
                             tp=tp)
        if max_slots is not None:
            slots = min(slots, max_slots)
        # budget-derived arenas stay exactly budget-sized: a wide table
        # (long lane) widens the control ints, never the arena bytes —
        # the long request's blocks come out of the budgeted pool
        eff_bs = int(block_size) if block_size else int(max_len)
        n_blocks = (1 + slots * (int(max_len) // eff_bs)) \
            if table_len else None
        return cls(model, slots, max_len, cache_dtype,
                   block_size=block_size, table_len=table_len,
                   n_blocks=n_blocks)

    @property
    def quantized(self) -> bool:
        return len(self.caches) == 4

    def nbytes(self) -> int:
        return sum(int(x.size) * x.dtype.itemsize for x in self.caches)


# -- resumable preemption: the host spill arena ------------------------------


@dataclasses.dataclass
class SpillEntry:
    """One preempted request's KV, parked in host memory.

    ``data`` holds the request's first ``n_blocks`` table blocks per
    cache leaf (``(layers, n_blocks, block_size, ...)`` numpy — valid
    rows ``0..pos-1``; the tail block's trailing rows are rewound
    speculation garbage and ride along harmlessly, the same way they
    do on-device). Resume maps the data back into freshly allocated
    arena blocks — zero prefill-lane work — provided the target pool
    still speaks the same layout AND the same ``weight_version`` (KV
    encodes the forward of the weights that wrote it; resuming it
    under swapped weights would splice two models' states)."""

    req_id: int
    data: tuple                      # per-leaf np arrays (L, nb, bs, ..)
    n_blocks: int
    block_size: int
    pos: int                         # next KV write index at spill time
    last_tok: int                    # sampled, not yet fed
    tokens: list                     # emitted so far (replayed on a
    #                                  cross-engine resume's Request)
    weight_version: int
    traceparent: Optional[str] = None  # originating trace context — a
    #                                  decode-tier resume adopts it so
    #                                  the cross-process spans share one
    #                                  trace_id (ISSUE 16); absent on
    #                                  wire docs from older peers
    key_state: Optional[object] = None  # (KW,) uint32 raw PRNG key
    #                                  state at spill time — a sampled
    #                                  request must resume its commit
    #                                  key stream exactly where it
    #                                  stopped or its replay diverges
    adapter: int = 0                 # adapter KV-compat uid the forward
    #                                  ran under (0 = base; see
    #                                  serving/tenancy.py) — resuming a
    #                                  tenant's KV under a different (or
    #                                  reloaded) adapter would splice
    #                                  two adapters' activations

    def nbytes(self) -> int:
        return sum(int(a.nbytes) for a in self.data)

    def compatible_with(self, pool: "KVPool", weight_version: int,
                        adapter: int = 0) -> bool:
        """Can this spill resume into ``pool`` at ``weight_version``
        under adapter KV-compat uid ``adapter``?"""
        if self.weight_version != int(weight_version) \
                or self.adapter != int(adapter) \
                or self.block_size != pool.block_size:
            return False
        if len(self.data) != len(pool.caches):
            return False
        return all(a.shape[0] == c.shape[0]
                   and a.shape[2:] == tuple(c.shape[2:])
                   and a.dtype == c.dtype
                   for a, c in zip(self.data, pool.caches))


class HostSpillArena:
    """Bounded host-memory parking lot for preempted requests' KV.

    Capacity is counted in ARENA BLOCKS (the same unit the device pool
    allocates and :func:`hetu_tpu.engine.memory.size_spill_arena`
    prices from a host-byte budget), so the scheduler's preemption
    planner can gate an eviction with the same arithmetic the resume
    will be charged. ``max_blocks=None`` = unbounded (the default for
    in-process fleets where host RAM dwarfs the arena).

    ``peer`` chains a second tier behind this one (device→host→peer):
    when the host tier is full, the LEAST-RECENTLY-SPILLED entries are
    demoted whole into the peer store, and an oversized entry that
    cannot fit the host tier at all passes straight through. ``pop``
    and ``get`` look through to the peer, so callers never care which
    tier holds an entry. Any object speaking the arena's
    put/pop/get/can_fit/``blocks_held`` surface works as a peer —
    another ``HostSpillArena`` in-process, or a wire-backed store."""

    def __init__(self, max_blocks: Optional[int] = None,
                 peer: Optional["HostSpillArena"] = None):
        self.max_blocks = int(max_blocks) if max_blocks else None
        self._entries: dict[int, SpillEntry] = {}
        self._peer = peer
        self.blocks_held = 0
        self.spilled_total = 0           # host ledgers (telemetry syncs)
        self.resumed_total = 0
        self.demoted_total = 0           # blocks pushed down the chain
        self.promoted_total = 0          # blocks pulled back up

    def attach_peer(self, peer) -> None:
        self._peer = peer

    def _demotion_plan(self, n_blocks: int):
        """Entry ids to demote (oldest first) so a put of ``n_blocks``
        fits the host tier, ``None`` if no placement exists. A put that
        fits as-is plans ``[]``; an entry wider than the whole host
        tier plans a pass-through (also ``[]``) if the peer takes it."""
        if self.max_blocks is None \
                or self.blocks_held + n_blocks <= self.max_blocks:
            return []
        if self._peer is None:
            return None
        if n_blocks > self.max_blocks:      # pass straight through
            return [] if self._peer.can_fit(n_blocks) else None
        plan, freed = [], 0
        need = self.blocks_held + n_blocks - self.max_blocks
        for rid, e in self._entries.items():     # insertion order = LRU
            if freed >= need:
                break
            plan.append(rid)
            freed += e.n_blocks
        if freed < need or not self._peer.can_fit(freed):
            return None
        return plan

    def can_fit(self, n_blocks: int) -> bool:
        return self._demotion_plan(int(n_blocks)) is not None

    def put(self, entry: SpillEntry) -> None:
        plan = self._demotion_plan(entry.n_blocks)
        if plan is None:
            raise ValueError(
                f"spill arena full: {self.blocks_held} + "
                f"{entry.n_blocks} blocks exceed max_blocks="
                f"{self.max_blocks}")
        if entry.req_id in self:
            raise ValueError(f"request {entry.req_id} already spilled")
        for rid in plan:
            old = self._entries.pop(rid)
            self.blocks_held -= old.n_blocks
            self._peer.put(old)
            self.demoted_total += old.n_blocks
        if self.max_blocks is not None \
                and entry.n_blocks > self.max_blocks:
            self._peer.put(entry)        # oversized: pass-through
            self.demoted_total += entry.n_blocks
        else:
            self._entries[entry.req_id] = entry
            self.blocks_held += entry.n_blocks
        self.spilled_total += entry.n_blocks

    def pop(self, req_id: int, *, resumed: bool = True
            ) -> Optional[SpillEntry]:
        """Remove an entry: ``resumed=True`` counts it in the resume
        ledger (a real map-back); ``resumed=False`` is a detach (the
        router pulled the request to a peer — that engine's resume
        counts it there). Looks through to the peer tier."""
        entry = self._entries.pop(req_id, None)
        if entry is None and self._peer is not None:
            entry = self._peer.pop(req_id, resumed=False)
            if entry is not None:
                self.promoted_total += entry.n_blocks
        elif entry is not None:
            self.blocks_held -= entry.n_blocks
        if entry is not None and resumed:
            self.resumed_total += entry.n_blocks
        return entry

    def get(self, req_id: int) -> Optional[SpillEntry]:
        entry = self._entries.get(req_id)
        if entry is None and self._peer is not None:
            entry = self._peer.get(req_id)
        return entry

    def tier_counts(self) -> dict:
        """Blocks held per tier, for the ``spill_tier_blocks`` gauge."""
        out = {"host": self.blocks_held}
        if self._peer is not None:
            out["peer"] = int(self._peer.blocks_held)
        return out

    def __contains__(self, req_id: int) -> bool:
        return req_id in self._entries \
            or (self._peer is not None and req_id in self._peer)

    def __len__(self) -> int:
        return len(self._entries) \
            + (len(self._peer) if self._peer is not None else 0)
