"""Continuous-batching inference engine: one jit, any request churn.

The static-batch ``models.generation.generate`` compiles one program per
(batch, prompt length) — admitting a request means retracing, the exact
control-plane tax PR 2 spent a subsystem killing on the training side.
This engine is the serving-plane answer, built from the two techniques
that turn a decode loop into a serving engine, mapped onto TPU idioms:

- **iteration-level scheduling** (Orca, OSDI'22): the unit of work is
  ONE engine iteration — one decode token for every active slot plus
  one chunk of prefill for the admitting request — so new requests join
  and finished ones leave between iterations, never mid-batch;
- **slot-pooled KV** (the fixed-shape cousin of vLLM's PagedAttention,
  SOSP'23): requests of any length live in one preallocated arena
  (:class:`~hetu_tpu.serving.kv_pool.KVPool`) indexed by per-slot
  control vectors, so the compiled step sees ONE signature forever.

The fused step is jitted once: chunked prefill (``lax.cond``-gated, a
fixed-size chunk written into the admitting slot via dynamic slices)
and the all-slot decode (per-row KV writes + per-row causal offsets —
``ParallelAttention._decode``'s slot mode) run in the same program, with
per-slot ``SamplingParams`` as traced operands. Request churn therefore
never recompiles — audited with the PR 2 ``record_trace`` counter
(``trace_counts()["serving_step"]`` stays at its initial compile count,
asserted in ``tests/test_serving.py``).

TP-sharded serving rides the existing ``Strategy``/``make_plan`` path:
pass ``plan=`` and the step traces under ``plan.act`` against sharded
params, exactly like ``generate`` under a tp mesh.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from hetu_tpu import telemetry
from hetu_tpu.engine.train_step import record_trace
from hetu_tpu.models import generation
from hetu_tpu.serving.kv_pool import KVPool
from hetu_tpu.serving.scheduler import Request, SamplingParams, Scheduler
from hetu_tpu.telemetry.flight import HangWatchdog, flight_record
from hetu_tpu.telemetry.slo import SLOEngine, default_serving_rules

#: per-request Perfetto tracks: synthetic tids offset far above real
#: thread ids so request timelines never collide with thread tracks
REQ_TRACK_BASE = 1 << 40


def sample_slots(logits, temperature, top_k, top_p, rng):
    """Per-slot sampling with TRACED knobs: (S, V) logits + (S,) params
    → (S,) int32 tokens. Mirrors ``generation._sample`` semantics
    (greedy at temperature 0, top-k keeps values >= the kth, nucleus
    keeps the smallest prefix whose prior mass < top_p) but every knob
    is data, not Python — one compile covers every request mix."""
    S, V = logits.shape
    greedy = jnp.argmax(logits, axis=-1)
    t = jnp.where(temperature > 0.0, temperature, 1.0)
    scaled = logits / t[:, None].astype(logits.dtype)
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    kth = jnp.take_along_axis(
        sorted_desc, jnp.clip(top_k - 1, 0, V - 1)[:, None], axis=1)
    keep_k = (top_k <= 0)[:, None] | (scaled >= kth)
    masked = jnp.where(keep_k, scaled, -jnp.inf)
    # the k-mask only replaces a value-SUFFIX of the sorted order with
    # -inf, so the sorted masked distribution is derivable — no second
    # O(V log V) sort on the decode hot path
    sd = jnp.where((top_k <= 0)[:, None] | (sorted_desc >= kth),
                   sorted_desc, -jnp.inf)
    probs = jax.nn.softmax(sd, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = cum - probs < top_p[:, None]    # mass *before* this token
    cutoff = jnp.min(jnp.where(keep, sd, jnp.inf), axis=-1,
                     keepdims=True)
    use_p = ((top_p > 0.0) & (top_p < 1.0))[:, None]
    masked = jnp.where(use_p & (masked < cutoff), -jnp.inf, masked)
    drawn = jax.vmap(jax.random.categorical)(
        jax.random.split(rng, S), masked)
    return jnp.where(temperature == 0.0, greedy, drawn).astype(jnp.int32)


class ServingEngine:
    """Slot-pooled continuous-batching engine over one model + params.

    Offline: :meth:`generate_many`. Online: :meth:`submit` +
    :meth:`result` with the :meth:`start` background loop (the
    ``rpc/py_server.py`` front end drives exactly that pair).
    """

    def __init__(self, model, params, *, slots: Optional[int] = None,
                 max_len: int = 256, prefill_chunk: int = 16,
                 cache_dtype=jnp.float32,
                 hbm_budget_bytes: Optional[float] = None,
                 plan=None, seed: int = 0,
                 counter_sample_every: int = 32,
                 watchdog: bool = False, watchdog_factor: float = 8.0,
                 watchdog_min_timeout_s: float = 30.0,
                 slo: Union[bool, SLOEngine, None] = None,
                 slo_every_s: float = 1.0):
        if slots is None:
            if hbm_budget_bytes is None:
                raise ValueError("pass slots= or hbm_budget_bytes=")
            tp = plan.strategy.tp if plan is not None else 1
            self.pool = KVPool.sized_for(
                model, hbm_budget_bytes=hbm_budget_bytes,
                max_len=max_len, cache_dtype=cache_dtype, tp=tp)
        else:
            self.pool = KVPool(model, slots, max_len, cache_dtype)
        self.model = model
        self.params = params
        self.prefill_chunk = int(prefill_chunk)
        if self.pool.max_len % self.prefill_chunk != 0:
            # a final chunk may only run past the prompt, never past the
            # arena — dynamic_update_slice would CLAMP the start index
            # and silently corrupt the preceding rows otherwise
            raise ValueError(
                f"max_len {self.pool.max_len} must be a multiple of "
                f"prefill_chunk {self.prefill_chunk}")
        self.scheduler = Scheduler(self.pool.slots, self.pool.max_len)
        self._plan = plan
        self._counter_sample_every = counter_sample_every

        S = self.pool.slots
        self._pos = np.zeros(S, np.int32)        # next KV write index
        self._last_tok = np.zeros(S, np.int32)   # sampled, not yet fed
        self._active = np.zeros(S, bool)         # decoding slots
        self._temp = np.zeros(S, np.float32)
        self._topk = np.zeros(S, np.int32)
        self._topp = np.zeros(S, np.float32)
        self._slot_req: list[Optional[Request]] = [None] * S
        self._prefill: Optional[dict] = None     # the admitting request
        self._key = jax.random.key(seed)
        self._iter = 0
        self._next_id = 0
        self._requests_by_id: dict[int, Request] = {}  # RPC poll map
        self._lock = threading.RLock()
        # serializes whole engine ITERATIONS: step() mutates _prefill
        # and passes pool.caches to a buffer-DONATING jit — two drivers
        # (the start() background loop + a direct run_until_drained)
        # must never interleave an iteration
        self._step_lock = threading.Lock()
        self._stop: Optional[threading.Event] = None
        self._thread: Optional[threading.Thread] = None
        # production-observability side-band: a hang watchdog fed by the
        # background loop, and an SLO engine evaluated on its cadence
        # (slo=True installs the default TTFT/TPOT/step rules; pass a
        # pre-configured SLOEngine for custom objectives)
        self.watchdog: Optional[HangWatchdog] = HangWatchdog(
            name="serving", factor=watchdog_factor,
            min_timeout_s=watchdog_min_timeout_s,
            registry=telemetry.get_registry()) if watchdog else None
        if slo is True:
            self.slo: Optional[SLOEngine] = default_serving_rules(
                SLOEngine(telemetry.get_registry()))
        else:
            self.slo = slo or None
        self._slo_every_s = float(slo_every_s)
        self._slo_last_eval = 0.0
        self._fn = self._build_step()

    # -- the jit-once fused step --------------------------------------------
    def _build_step(self):
        model = self.model
        C = self.prefill_chunk

        def step(params, caches, ctl, pf, key, it):
            record_trace("serving_step")    # churn must never re-enter
            rng = jax.random.fold_in(key, it)
            rng_dec, rng_pf = jax.random.split(rng)

            # one decode token for EVERY slot; free/prefilling slots
            # compute garbage that the slot mask keeps out of the pool
            # and the host ignores. cond-gated so prefill-only
            # iterations (cold admission) skip the discarded forward.
            def do_decode(caches):
                logits, caches = generation.decode(
                    model, params, ctl["last_tok"][:, None],
                    ctl["pos"][:, None], caches,
                    slot_mask=ctl["active"])
                return caches, sample_slots(
                    logits[:, 0], ctl["temp"], ctl["topk"],
                    ctl["topp"], rng_dec)

            def no_decode(caches):
                return caches, jnp.zeros(
                    (ctl["pos"].shape[0],), jnp.int32)

            caches, emitted = jax.lax.cond(
                ctl["active"].any(), do_decode, no_decode, caches)

            # one chunk of prefill for the admitting slot (cond keeps
            # idle iterations from paying the chunk's compute)
            def do_prefill(caches):
                slot = pf["slot"]
                sc = jax.tree.map(
                    lambda c: jax.lax.dynamic_slice_in_dim(
                        c, slot, 1, axis=1), caches)
                pos = (pf["start"]
                       + jnp.arange(C, dtype=jnp.int32))[None]
                h = model.embed(params, pf["tokens"][None],
                                positions=pos)
                h, sc = model.blocks.decode(params["blocks"], h, sc,
                                            positions=pos)
                caches = jax.tree.map(
                    lambda c, s_: jax.lax.dynamic_update_slice_in_dim(
                        c, s_, slot, axis=1), caches, sc)
                # request's FIRST token: head on the last REAL row only
                # (pad rows of a partial final chunk sit beyond it)
                h_last = jax.lax.dynamic_slice_in_dim(
                    h, pf["valid"] - 1, 1, axis=1)
                h_last = model.hidden_norm(params, h_last)
                w = generation._head_weight(model, params)
                lg = jnp.einsum("bse,ve->bsv",
                                h_last.astype(jnp.float32),
                                w.astype(jnp.float32))[:, 0]
                first = sample_slots(
                    lg, ctl["temp"][slot][None],
                    ctl["topk"][slot][None], ctl["topp"][slot][None],
                    rng_pf)[0]
                return caches, first

            def no_prefill(caches):
                return caches, jnp.int32(0)

            caches, first_tok = jax.lax.cond(
                pf["run"], do_prefill, no_prefill, caches)
            return caches, emitted, first_tok

        return jax.jit(step, donate_argnums=(1,))

    # -- submission ---------------------------------------------------------
    def submit(self, prompt: Sequence[int],
               sampling: Optional[SamplingParams] = None) -> Request:
        """Queue one request (FCFS). Returns the live Request — poll
        ``req.done`` / :meth:`result`, or drive :meth:`step` yourself."""
        sampling = sampling or SamplingParams()
        with self._lock:
            req = Request(id=self._next_id,
                          prompt=np.asarray(prompt, np.int32).ravel(),
                          sampling=sampling, submit_s=time.monotonic())
            self._next_id += 1
            admitted = self.scheduler.submit(req)
        reg = telemetry.get_registry()
        reg.counter("serving_requests_total",
                    "serving requests by outcome").inc(
            outcome="submitted" if admitted else "rejected")
        flight_record("serving_submit", req=req.id, trace=req.trace_id,
                      prompt_len=len(req.prompt),
                      outcome="queued" if admitted else "rejected")
        self._record_gauges()
        return req

    def result(self, req: Request,
               timeout: Optional[float] = None) -> Optional[dict]:
        """Wait for ``req`` to finish; None on timeout."""
        if not req.done.wait(timeout):
            return None
        return req.result()

    # -- the host loop ------------------------------------------------------
    def has_work(self) -> bool:
        with self._lock:
            return bool(self.scheduler.queue) or self._active.any() \
                or self._prefill is not None

    def step(self) -> bool:
        """One engine iteration; False when there was nothing to do.
        Safe to call while the :meth:`start` loop runs (iterations are
        serialized), though one driver is the intended mode."""
        with self._step_lock:
            return self._step_locked()

    def _step_locked(self) -> bool:
        t0 = time.monotonic()
        with self._lock:
            if self._prefill is None:
                adm = self.scheduler.next_admission()
                if adm is not None:
                    req, slot = adm
                    sp = req.sampling
                    self._temp[slot] = sp.temperature
                    self._topk[slot] = sp.top_k
                    self._topp[slot] = sp.top_p
                    self._slot_req[slot] = req
                    self._prefill = {"req": req, "slot": slot, "off": 0}
                    flight_record("serving_admit", req=req.id,
                                  trace=req.trace_id, slot=slot,
                                  queued_s=round(
                                      time.monotonic() - req.submit_s, 4))
            pf_host = self._prefill
            active_prev = np.nonzero(self._active)[0]
            if pf_host is None and active_prev.size == 0:
                return False
            ctl = {"pos": jnp.asarray(self._pos),
                   "last_tok": jnp.asarray(self._last_tok),
                   "active": jnp.asarray(self._active),
                   "temp": jnp.asarray(self._temp),
                   "topk": jnp.asarray(self._topk),
                   "topp": jnp.asarray(self._topp)}
            C = self.prefill_chunk
            chunk = np.zeros(C, np.int32)
            if pf_host is not None:
                req, off = pf_host["req"], pf_host["off"]
                part = req.prompt[off:off + C]
                chunk[:len(part)] = part
                pf = {"run": np.True_,
                      "slot": np.int32(pf_host["slot"]),
                      "start": np.int32(off),
                      "valid": np.int32(len(part)),
                      "tokens": chunk}
                pf_last = off + len(part) >= len(req.prompt)
                pf_valid = len(part)
            else:
                pf = {"run": np.False_, "slot": np.int32(0),
                      "start": np.int32(0), "valid": np.int32(1),
                      "tokens": chunk}
                pf_last = False
                pf_valid = 0

        ctx = self._plan.act if self._plan is not None \
            else contextlib.nullcontext()
        with ctx:
            caches, emitted, first_tok = self._fn(
                self.params, self.pool.caches, ctl, pf, self._key,
                np.int32(self._iter))
        self.pool.caches = caches
        em = np.asarray(emitted)
        now = time.monotonic()

        reg = telemetry.get_registry()
        with self._lock:
            self._iter += 1
            # decode results for the slots that were active going in
            for r in active_prev:
                self._on_token(int(r), int(em[r]), now, reg)
            # prefill progress
            if pf_host is not None:
                pf_host["off"] += pf_valid
                pf_host["req"].mark("prefill_chunk", dur_s=now - t0,
                                    ts_s=t0)
                reg.counter("serving_tokens_total",
                            "serving tokens by kind").inc(
                    pf_valid, kind="prompt")
                if pf_last:
                    req, slot = pf_host["req"], pf_host["slot"]
                    self._pos[slot] = len(req.prompt)
                    self._active[slot] = True
                    req.status = "decode"
                    req.first_token_s = now
                    req.mark("first_token", ts_s=now)
                    ttft = now - req.submit_s
                    reg.histogram(
                        "serving_ttft_seconds",
                        "time submit -> first token").observe(ttft)
                    if self.slo is not None:
                        self.slo.observe("serving_ttft_seconds", ttft)
                    self._on_token(slot, int(first_tok), now, reg)
                    self._prefill = None
            self._record_gauges()
        step_s = time.monotonic() - t0
        reg.histogram("serving_step_seconds",
                      "one fused engine iteration").observe(step_s)
        if self.slo is not None:
            self.slo.observe("serving_step_seconds", step_s)
        if self._counter_sample_every and \
                self._iter % self._counter_sample_every == 0:
            telemetry.get_tracer().record_counters(reg.snapshot())
        return True

    def _on_token(self, slot: int, tok: int, now: float, reg) -> None:
        """Record one sampled token for ``slot`` (caller holds lock):
        append, advance the slot cursor, finish on EOS / budget."""
        req = self._slot_req[slot]
        req.tokens.append(tok)
        self._last_tok[slot] = tok
        # the cursor only advances once the token is FED (next decode
        # writes its KV at the current pos) — pos was set by prefill
        if req.status == "decode" and len(req.tokens) > 1:
            self._pos[slot] += 1
        reg.counter("serving_tokens_total",
                    "serving tokens by kind").inc(kind="generated")
        sp = req.sampling
        hit_eos = sp.eos_id is not None and tok == sp.eos_id
        if hit_eos or len(req.tokens) >= sp.max_tokens:
            self._finish(slot, now, reg)

    def _finish(self, slot: int, now: float, reg) -> None:
        req = self._slot_req[slot]
        req.status = "done"
        req.finish_s = now
        req.mark("finish", ts_s=now)
        self._active[slot] = False
        self._slot_req[slot] = None
        self.scheduler.release(slot)
        reg.counter("serving_requests_total",
                    "serving requests by outcome").inc(
            outcome="completed")
        n = len(req.tokens)
        if n > 1 and req.first_token_s is not None:
            tpot = (now - req.first_token_s) / (n - 1)
            reg.histogram("serving_tpot_seconds",
                          "per-output-token time after the first").observe(
                tpot)
            if self.slo is not None:
                self.slo.observe("serving_tpot_seconds", tpot)
        flight_record("serving_finish", req=req.id, trace=req.trace_id,
                      slot=slot, tokens=n)
        self._emit_request_trace(req)
        req.done.set()

    def _emit_request_trace(self, req: Request) -> None:
        """Render the request's lifecycle as its own Perfetto track:
        one span per phase (queued / prefill chunks / decode), on a
        synthetic tid named after the ``trace_id``. Host-side, only
        when the tracer is on — the fused step never sees any of it."""
        tracer = telemetry.get_tracer()
        if not tracer.enabled:
            return
        # request events use time.monotonic; the tracer epoch is
        # perf_counter-based — bridge via the current offset (both are
        # monotonic clocks, so the offset is constant)
        off = (time.perf_counter() - tracer.epoch) - time.monotonic()
        tid = REQ_TRACK_BASE + req.id
        tracer.name_track(tid, f"req {req.trace_id}")

        def span(name, start, dur, **attrs):
            tracer.complete(name, max(dur, 0.0), cat="request",
                            ts_s=max(start + off, 0.0), tid=tid,
                            trace_id=req.trace_id, req=req.id, **attrs)

        admit = next((t for p, t, _ in req.events if p == "admit"), None)
        if admit is not None:
            span("queued", req.submit_s, admit - req.submit_s)
        for phase, ts, dur in req.events:
            if phase == "prefill_chunk":
                span("prefill_chunk", ts, dur)
        if req.first_token_s is not None and req.finish_s is not None:
            span("decode", req.first_token_s,
                 req.finish_s - req.first_token_s,
                 tokens=len(req.tokens))

    def _record_gauges(self) -> None:
        reg = telemetry.get_registry()
        reg.gauge("serving_queue_depth",
                  "requests waiting for a slot").set(self.scheduler.depth)
        reg.gauge("serving_slot_occupancy",
                  "fraction of KV-pool slots in use").set(
            self.scheduler.occupancy)

    def run_until_drained(self, max_steps: int = 1_000_000) -> int:
        """Drive :meth:`step` until queue + slots are empty; returns the
        number of iterations run."""
        n = 0
        while self.has_work():
            if n >= max_steps:
                raise RuntimeError(
                    f"serving engine not drained after {max_steps} "
                    f"iterations")
            self.step()
            n += 1
        return n

    # -- offline API --------------------------------------------------------
    def generate_many(
            self, prompts: Sequence[Sequence[int]],
            sampling: Union[SamplingParams, Sequence[SamplingParams],
                            None] = None) -> list[list[int]]:
        """Submit every prompt, run to drain, return per-request tokens
        (continuous batching under the hood — arrival order and slot
        assignment do not change any request's tokens)."""
        if sampling is None or isinstance(sampling, SamplingParams):
            sampling = [sampling or SamplingParams()] * len(prompts)
        reqs = [self.submit(p, sp) for p, sp in zip(prompts, sampling)]
        bad = [r for r in reqs if r.status == "rejected"]
        if bad:
            # fail FAST and loud (a silent [] is indistinguishable from
            # a legitimate empty generation); un-queue the siblings so
            # the engine is left clean
            with self._lock:
                for r in reqs:
                    if r.status == "queued":
                        try:
                            self.scheduler.queue.remove(r)
                        except ValueError:
                            pass
                        r.status = "cancelled"
                        r.error = "batch aborted: sibling rejected"
                        r.done.set()
            raise ValueError(
                f"{len(bad)} request(s) rejected at admission: "
                + "; ".join(f"#{r.id}: {r.error}" for r in bad[:3]))
        self.run_until_drained()
        return [list(r.tokens) for r in reqs]

    # -- background loop (online front ends) --------------------------------
    def start(self, idle_sleep_s: float = 0.002) -> None:
        if self._thread is not None:
            return
        self._stop = threading.Event()
        if self.watchdog is not None:
            self.watchdog.start()

        def loop():
            while not self._stop.is_set():
                busy = self.step()
                # a beat per loop turn (idle included): the watchdog
                # watches for a WEDGED iteration, not an empty queue
                if self.watchdog is not None:
                    self.watchdog.beat()
                if self.slo is not None:
                    now = time.monotonic()
                    if now - self._slo_last_eval >= self._slo_every_s:
                        self._slo_last_eval = now
                        for a in self.slo.evaluate():
                            from hetu_tpu.utils.logging import get_logger
                            get_logger().warning(
                                f"SLO alert: {a.message}")
                if not busy:
                    self._stop.wait(idle_sleep_s)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=10.0)
        self._thread = None
        if self.watchdog is not None:
            self.watchdog.stop()
