"""Continuous-batching inference engine: one jit, any request churn.

The static-batch ``models.generation.generate`` compiles one program per
(batch, prompt length) — admitting a request means retracing, the exact
control-plane tax PR 2 spent a subsystem killing on the training side.
This engine is the serving-plane answer, built from the techniques that
turn a decode loop into a serving engine, mapped onto TPU idioms:

- **iteration-level scheduling** (Orca, OSDI'22): the unit of work is
  ONE engine iteration — one decode token for every active slot plus a
  fixed token budget of prefill — so new requests join and finished
  ones leave between iterations, never mid-batch;
- **block-paged KV** (vLLM's PagedAttention, SOSP'23): requests live in
  a ``(layers, n_blocks, block_size, hkv, d)`` arena indexed through
  per-slot BLOCK TABLES (:class:`~hetu_tpu.serving.kv_pool.KVPool`),
  so bytes are allocated per block, not per worst-case slot;
- **radix-tree prefix caching** (SGLang's RadixAttention): admission
  maps a cached prompt prefix's blocks into the new slot's table
  (refcounted, CoW for a partial tail block —
  :mod:`~hetu_tpu.serving.prefix_cache`) and prefill starts at the
  first uncached token — a fleet-wide system prompt is prefilled once;
- **packed multi-request prefill**: the prefill lane carries a fixed
  ``prefill_chunk``-token budget PACKED from every admitting request
  (cu_seqlens-style per-token slot/position operands), so a burst of
  arrivals shares each iteration's prefill bandwidth instead of
  serializing one admission per iteration — TTFT p99 stops growing
  linearly with queue depth;
- **CP-sharded long-prompt prefill** (``long_max_len=``, the shape
  plane's serving half): prompts whose worst case exceeds one slot's
  ``max_len`` budget stop being rejected — they admit into a
  wide-block-table slot and prefill as ONE training-mode forward
  (ring/ulysses over the plan's cp axis when ``cp > 1``,
  ``StackedBlocks.prefill``) whose per-layer KV scatters straight into
  the paged arena; decode then rides the normal fused step. Lane
  prompt lengths snap to a geometric bucket ladder, so the lane owns
  at most ``n_buckets`` executables
  (``record_trace("serving_cp_prefill")``) while the fused step keeps
  its single compile;
- **speculative decoding** (``spec_depth=k``, Leviathan et al.): the
  decode lane becomes a VERIFY lane — each active slot feeds its last
  token plus up to k drafted tokens as ``k+1`` q rows spanning
  positions ``pos..pos+k`` (the per-row causal offsets
  ``attention_reference(q_offset=array)`` already speaks), so one
  forward checks k guesses and commits every leading match plus one
  bonus token. Draft tokens and per-slot depths are DATA (the step
  compiles once for any draft mix, including depth 0 = classic
  decode); accepted tokens are ordinary paged writes, rejected
  suffixes just rewind ``pos`` (blocks are refcounted, nothing is
  zeroed — the stale rows are overwritten before anything can attend
  them). Drafts come from :mod:`~hetu_tpu.serving.speculative`: the
  self-drafting n-gram/prompt-lookup index by default, or a small
  model from the zoo (``draft_model=``). Greedy output is
  token-identical to non-speculative decode for EVERY
  acceptance/rejection pattern — a draftsman can only cost speed;
- **QoS + resumable preemption**: ``SamplingParams.priority`` classes
  with deficit-weighted admission (``Scheduler``), and when slots or
  blocks run dry an urgent arrival PREEMPTS a strictly-lower-priority
  running request — its KV blocks spill to a host arena
  (:class:`~hetu_tpu.serving.kv_pool.HostSpillArena`, a table edit
  plus one device→host gather), and resume maps them back into fresh
  blocks with ZERO prefill-lane work. The router's death-requeue and
  the weight publisher's drains ride the same spill entries
  (``Router``/``WeightPublisher``), so a killed replica's mid-decode
  requests resume on peers instead of re-prefilling.

The fused step is jitted once: CoW block copies, the all-slot decode
(per-row KV writes + per-row causal offsets —
``ParallelAttention._decode``'s paged slot mode) and the packed prefill
lane run in the same program, with per-slot ``SamplingParams``, block
tables, pack layouts and prefix offsets all as traced operands — DATA,
never shapes. Request churn, cache hits and evictions therefore never
recompile — audited with the PR 2 ``record_trace`` counter
(``trace_counts()["serving_step"]`` stays at its initial compile count,
asserted in ``tests/test_serving.py`` / ``tests/test_paged_serving.py``).

TP-sharded serving rides the existing ``Strategy``/``make_plan`` path:
pass ``plan=`` and the step traces under ``plan.act`` against sharded
params, exactly like ``generate`` under a tp mesh.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from hetu_tpu import telemetry
from hetu_tpu.engine.train_step import record_trace
from hetu_tpu.models import generation
from hetu_tpu.serving.kv_pool import (
    BlockManager, HostSpillArena, KVPool, SpillEntry,
)
from hetu_tpu.serving.prefix_cache import PrefixCache
from hetu_tpu.serving.scheduler import Request, SamplingParams, Scheduler
from hetu_tpu.serving.speculative import (
    ModelDraftsman, NgramDraftsman, adjust_logits, check_draft_depth,
    check_sampled_draft, speculative_verify,
)
from hetu_tpu.serving.tenancy import AdapterArenaFull
from hetu_tpu.telemetry.flight import HangWatchdog, flight_record
from hetu_tpu.telemetry.slo import SLOEngine, default_serving_rules
from hetu_tpu.telemetry.spans import REQ_TRACK_BASE  # noqa: F401 — re-export


def sample_slots(logits, temperature, top_k, top_p, rng):
    """Per-slot sampling with TRACED knobs: (S, V) logits + (S,) params
    → (S,) int32 tokens. Mirrors ``generation._sample`` semantics
    (greedy at temperature 0, top-k keeps values >= the kth, nucleus
    keeps the smallest prefix whose prior mass < top_p) but every knob
    is data, not Python — one compile covers every request mix. The
    masking arithmetic lives in ``speculative.adjust_logits`` so the
    rejection-sampling verify lane's target distribution p is bitwise
    THIS sampler's."""
    S = logits.shape[0]
    greedy = jnp.argmax(logits, axis=-1)
    masked = adjust_logits(logits, temperature, top_k, top_p)
    drawn = jax.vmap(jax.random.categorical)(
        jax.random.split(rng, S), masked)
    return jnp.where(temperature == 0.0, greedy, drawn).astype(jnp.int32)


class ServingEngine:
    """Slot-pooled continuous-batching engine over one model + params.

    Offline: :meth:`generate_many`. Online: :meth:`submit` +
    :meth:`result` with the :meth:`start` background loop (the
    ``rpc/py_server.py`` front end drives exactly that pair).
    """

    def __init__(self, model, params, *, slots: Optional[int] = None,
                 max_len: int = 256, prefill_chunk: int = 16,
                 cache_dtype=jnp.float32,
                 hbm_budget_bytes: Optional[float] = None,
                 block_size: Optional[int] = None,
                 kv_blocks: Optional[int] = None,
                 prefix_cache: bool = True,
                 long_max_len: Optional[int] = None,
                 spec_depth: int = 0, draft: str = "ngram",
                 draft_ngram: int = 3,
                 draft_model=None, draft_params=None,
                 preempt: bool = True,
                 spill_host_budget_bytes: Optional[float] = None,
                 spill_peer=None,
                 class_weights: Optional[dict] = None,
                 attn_kernel: str = "auto",
                 prefill_attn: str = "auto",
                 w8a8="off",
                 plan=None, seed: int = 0,
                 counter_sample_every: int = 32,
                 watchdog: bool = False, watchdog_factor: float = 8.0,
                 watchdog_min_timeout_s: float = 30.0,
                 slo: Union[bool, SLOEngine, None] = None,
                 slo_every_s: float = 1.0,
                 tenancy=None):
        # -- multi-tenant adapter plane (serving/tenancy.py):
        # tenancy=True mounts a default TenantPlane; pass a configured
        # one for custom arena size / rank / QoS policies. None is the
        # historical single-tenant engine, bit for bit.
        if tenancy is True:
            from hetu_tpu.serving.tenancy import TenantPlane
            tenancy = TenantPlane()
        self.tenancy = tenancy or None
        if block_size is None:
            # default paging: 16-token blocks when they divide max_len,
            # else one block per slot (degenerate = PR 5 slot arena)
            block_size = 16 if max_len % 16 == 0 else max_len
        # CP-prefill lane (long_max_len): prompts whose worst case
        # exceeds one slot's max_len budget stop being rejected — they
        # admit into a wide-table slot and their prefill runs as ONE
        # training-mode forward (ring/ulysses over the plan's cp axis
        # when cp > 1) whose per-layer KV scatters straight into the
        # paged arena; decode then proceeds in the normal fused step.
        # The lane's prompt lengths snap to a small geometric bucket
        # ladder so its executable count is bounded (the
        # record_trace("serving_cp_prefill") audit: <= n lane buckets).
        self._cp = plan.strategy.cp if plan is not None else 1
        self._cp_zigzag = (
            plan is not None and self._cp > 1
            and plan.strategy.effective_cp_layout == "zigzag")
        self._cp_buckets = None
        if long_max_len is not None:
            long_max_len = int(long_max_len)
            mult = (2 * self._cp) if self._cp_zigzag \
                else max(self._cp, 1)
            if long_max_len % mult != 0:
                raise ValueError(
                    f"long_max_len {long_max_len} must be a multiple "
                    f"of {mult} (cp sharding alignment: cp={self._cp}, "
                    f"{'zigzag' if self._cp_zigzag else 'contiguous'})")
            from hetu_tpu.data.bucket import SeqLenBuckets
            start = -(-int(max_len) // mult) * mult
            sizes = []
            v = max(start, mult)
            while v < long_max_len:
                sizes.append(v)
                v *= 2
            sizes.append(long_max_len)
            self._cp_buckets = SeqLenBuckets(sizes=sizes,
                                             multiple_of=mult)
        if slots is None:
            if hbm_budget_bytes is None:
                raise ValueError("pass slots= or hbm_budget_bytes=")
            if kv_blocks is not None:
                raise ValueError(
                    "kv_blocks= conflicts with hbm_budget_bytes= "
                    "sizing (the budget already fixes the arena) — "
                    "pass slots= alongside kv_blocks=")
            if self.tenancy is not None:
                # the adapter arena lives in the same HBM budget the
                # KV arena is sized from — price it FIRST so the
                # admission arithmetic stays honest (engine/memory
                # ledger, like the CP-prefill activation check below)
                from hetu_tpu.engine.memory import size_adapter_arena
                arena = size_adapter_arena(
                    model.cfg, r=self.tenancy.r,
                    max_adapters=self.tenancy.max_adapters)
                if arena >= 0.5 * hbm_budget_bytes:
                    raise ValueError(
                        f"adapter arena ({self.tenancy.max_adapters} "
                        f"pages x rank {self.tenancy.r}) needs "
                        f"~{arena / 1e9:.2f}GB — more than half the "
                        f"{hbm_budget_bytes / 1e9:.2f}GB HBM budget; "
                        f"shrink max_adapters / the arena rank, or "
                        f"raise the budget")
                hbm_budget_bytes = hbm_budget_bytes - arena
            tp = plan.strategy.tp if plan is not None else 1
            self.pool = KVPool.sized_for(
                model, hbm_budget_bytes=hbm_budget_bytes,
                max_len=max_len, cache_dtype=cache_dtype, tp=tp,
                block_size=block_size, table_len=long_max_len)
            if long_max_len is not None:
                # admission-gate honesty: the lane's one-pass prefill
                # carries real activation bytes the slot arithmetic
                # never priced — the ledger must confirm they fit in
                # the budget's headroom next to the arena
                from hetu_tpu.engine.memory import cp_prefill_act_bytes
                act = cp_prefill_act_bytes(model.cfg,
                                           seq_len=long_max_len,
                                           cp=self._cp)
                if act > 0.1 * hbm_budget_bytes:
                    raise ValueError(
                        f"CP-prefill activations at long_max_len="
                        f"{long_max_len} need ~{act / 1e9:.2f}GB — more "
                        f"than the {0.1 * hbm_budget_bytes / 1e9:.2f}GB "
                        f"headroom the {hbm_budget_bytes / 1e9:.2f}GB "
                        f"budget leaves next to the KV arena; raise cp, "
                        f"shrink long_max_len, or raise the budget")
        else:
            # kv_blocks decouples CONCURRENCY from worst-case memory:
            # slots is how many requests decode in parallel (cheap —
            # control vectors + table rows), kv_blocks is the arena's
            # actual byte budget. Oversubscribed slots (slots *
            # blocks_per_slot > kv_blocks - 1) are the PagedAttention
            # win: short requests reserve only their own ceil((P +
            # max_tokens)/block_size) blocks, so the same bytes that
            # held S worst-case slots run more than S live requests —
            # admission's free-block gate keeps it sound.
            self.pool = KVPool(model, slots, max_len, cache_dtype,
                               block_size=block_size, n_blocks=kv_blocks,
                               table_len=long_max_len)
        self.model = model
        self.params = params
        #: weight generation currently loaded — bumped by
        #: :meth:`swap_params` (the HotSPa train→serve push path);
        #: every request is tagged with the version it was admitted
        #: under, and the KV pool / prefix cache carry the same tag so
        #: stale prefills can never survive a swap
        self.weight_version = 0
        self.prefill_chunk = int(prefill_chunk)  # PACK budget/iteration
        self.blocks = BlockManager(self.pool.n_blocks)
        self.prefix_cache: Optional[PrefixCache] = PrefixCache(
            self.pool.block_size, self.blocks) if prefix_cache else None
        self.scheduler = Scheduler(
            self.pool.slots, self.pool.max_len, blocks=self.blocks,
            prefix_cache=self.prefix_cache,
            block_size=self.pool.block_size,
            long_max_len=long_max_len, class_weights=class_weights)
        self._plan = plan
        self._counter_sample_every = counter_sample_every

        # -- speculation plane (ISSUE 11): draft depth is a SHAPE knob
        # (the verify lane's width), per-slot effective depth is data —
        # spec_depth=0 keeps the lane at the classic one-row decode
        self.spec_depth = check_draft_depth(spec_depth, max_len)
        self._draftsman = None
        if draft_model is not None:
            if self.spec_depth == 0:
                raise ValueError(
                    "draft_model without spec_depth — pass spec_depth=k "
                    "to enable the verify lane")
            self._draftsman = ModelDraftsman(
                draft_model, draft_params, slots=self.pool.slots,
                max_len=max_len, spec_depth=self.spec_depth,
                target_vocab=model.cfg.vocab_size)
        elif self.spec_depth:
            if draft != "ngram":
                raise ValueError(f"unknown draft source {draft!r} "
                                 f"(ngram, or pass draft_model=)")
            self._draftsman = NgramDraftsman(self.pool.slots,
                                             ngram=draft_ngram)
        # -- QoS preemption: host spill arena, priced in the same
        # blocks the device pool allocates (engine/memory ledger)
        self.preempt = bool(preempt)
        if spill_host_budget_bytes is not None:
            from hetu_tpu.engine.memory import size_spill_arena
            from hetu_tpu.serving.kv_pool import cache_dtype_name
            max_blocks = size_spill_arena(
                model.cfg, host_budget_bytes=spill_host_budget_bytes,
                block_size=self.pool.block_size,
                cache_dtype=cache_dtype_name(cache_dtype),
                tp=plan.strategy.tp if plan is not None else 1)
        else:
            max_blocks = None
        # ``spill_peer`` chains a second spill tier behind the host
        # arena (device→host→peer, ISSUE 18): any object with the
        # arena's put/pop/get/can_fit surface — another HostSpillArena
        # in-process, or a wire-backed store. LRU demotion + promotion
        # live in the arena; ``engine/memory.size_spill_tiers`` prices
        # both tiers in the same arena blocks.
        self.spill_arena = HostSpillArena(max_blocks, peer=spill_peer)
        self._resume_pending: list[dict] = []    # admitted spill-resumes

        # -- decode-KV replication (ISSUE 18): a background thread
        # streams newly committed blocks of decoding slots to a
        # rendezvous-chosen buddy (the router wires the sink);
        # ``kv_replica_store`` is OUR buddy-side accumulator for peers
        # replicating here. Jax-free import — fleet.py has no jax.
        from hetu_tpu.serving.fleet import KVReplicaStore
        self.kv_replica_store = KVReplicaStore()
        self._repl_sink = None          # callable(doc) or None = off
        self._repl_origin = ""
        self._repl_cadence_s = 0.02
        self._repl_sent: dict[int, tuple] = {}   # req id -> (blocks, tid)
        self._repl_thread: Optional[threading.Thread] = None
        self._repl_stop: Optional[threading.Event] = None

        S = self.pool.slots
        W = self.pool.table_width
        self._pos = np.zeros(S, np.int32)        # next KV write index
        self._last_tok = np.zeros(S, np.int32)   # sampled, not yet fed
        self._active = np.zeros(S, bool)         # decoding slots
        self._temp = np.zeros(S, np.float32)
        self._topk = np.zeros(S, np.int32)
        self._topp = np.zeros(S, np.float32)
        self._bt = np.zeros((S, W), np.int32)    # per-slot block tables
        # device-resident mirrors of the control vectors + block tables:
        # rebuilt from the np mirrors only when an admission / prefill
        # completion / finish dirtied them — steady decode iterations
        # reuse the compiled step's own (pos, last_tok) outputs and
        # upload NOTHING
        self._ctl_dev: Optional[dict] = None
        self._bt_dev = None
        self._ctl_dirty = True
        self._slot_req: list[Optional[Request]] = [None] * S
        # -- adapter arena (serving/tenancy.py): device-resident
        # stacked A/B pages per projection — (L, P, in, r) /
        # (L, P, r, out), page 0 all-zero (base). The registry rewrites
        # SINGLE pages via functional .at[:, page].set, so adapter
        # load/evict/hot-swap never changes a shape and never retraces
        # the fused step; _adapter_page maps slot -> page and rides ctl
        # as traced data.
        self._adapter_page = np.zeros(S, np.int32)
        self._lora_pages: dict = {}
        self._throttle_logged: set = set()   # reqs in a throttle episode
        self._wait_logged: set = set()       # reqs waiting on the arena
        self._qos_admitted: set = set()      # req ids that paid on_admit
        if self.tenancy is not None:
            self._lora_pages = self._init_adapter_arena()
            self.tenancy.registry.on_page_write = self._write_adapter_page
            self.scheduler.admission_gate = self._admission_gate
        self._prefilling: list[dict] = []        # FCFS in-flight prefills
        self._cp_pending: list[dict] = []        # admitted CP-lane reqs
        #: max requests that can FINISH prefill in one iteration (each
        #: needs >= 1 pack token) — the prefill lane's head/sample width
        self._fin_cap = max(1, min(S, self.prefill_chunk))
        self._evictions_synced = 0               # scheduler ledger → ctr
        self._key = jax.random.key(seed)
        # per-slot commit-key state (raw jax.random.key_data layout):
        # the sampled lane's traced PRNG stream — one split consumed
        # per committed token, exactly generate()'s discipline, so an
        # identical-seed sampled request replays bit-for-bit. Admission
        # seeds it (SamplingParams.seed, else engine seed + req id);
        # the fused step returns the advanced state every iteration.
        self._kw = int(jax.random.key_data(self._key).shape[-1])
        self._key_state = np.zeros((S, self._kw), np.uint32)
        self._iter = 0
        self._next_id = 0
        self._requests_by_id: dict[int, Request] = {}  # RPC poll map
        self._lock = threading.RLock()
        # serializes whole engine ITERATIONS: step() mutates _prefill
        # and passes pool.caches to a buffer-DONATING jit — two drivers
        # (the start() background loop + a direct run_until_drained)
        # must never interleave an iteration
        self._step_lock = threading.Lock()
        # push subscriptions (ISSUE 19): req id → (request, [subs]);
        # fed enqueue-only at the end of every step, drained by the
        # coordinator's per-connection writer threads OFF the step lock
        self._stream_subs: dict[int, tuple] = {}
        self._stream_lock = threading.Lock()
        self._stop: Optional[threading.Event] = None
        self._thread: Optional[threading.Thread] = None
        # production-observability side-band: a hang watchdog fed by the
        # background loop, and an SLO engine evaluated on its cadence
        # (slo=True installs the default TTFT/TPOT/step rules; pass a
        # pre-configured SLOEngine for custom objectives)
        self.watchdog: Optional[HangWatchdog] = HangWatchdog(
            name="serving", factor=watchdog_factor,
            min_timeout_s=watchdog_min_timeout_s,
            registry=telemetry.get_registry()) if watchdog else None
        if slo is True:
            self.slo: Optional[SLOEngine] = default_serving_rules(
                SLOEngine(telemetry.get_registry()))
        else:
            self.slo = slo or None
        self._slo_every_s = float(slo_every_s)
        self._slo_last_eval = 0.0

        # -- kernel plane (ISSUE 14): decode attention dispatch is
        # arena-layout-aware (the same call serves fp32/bf16/int8 —
        # the kernel streams int8 pages + scales and dequantizes per
        # tile) and resolved ONCE here: the choice is baked into the
        # compiled step, so the 1-compile audit is untouched.
        from hetu_tpu.ops.attention import resolve_decode_kernel
        tp = plan.strategy.tp if plan is not None else 1
        _attn_mod = model.blocks.block.attn
        self.attn_kernel = resolve_decode_kernel(
            attn_kernel, tp=tp, site="serving_decode",
            num_heads=_attn_mod.num_heads,
            num_kv_heads=_attn_mod.num_kv_heads)
        # prefill lanes: "flash" packs the chunk as ONE row — intra-pack
        # flash attention with segment isolation, LSE-combined with each
        # token's arena history through its block table; "reference" is
        # the historical per-token paged lane. "flash_pallas" forces the
        # Pallas intra kernel (interpret on CPU — quick-tier coverage).
        if prefill_attn == "auto":
            prefill_attn = "flash" if jax.default_backend() == "tpu" \
                else "reference"
        if prefill_attn not in ("reference", "flash", "flash_pallas"):
            raise ValueError(
                f"prefill_attn must be auto|reference|flash|"
                f"flash_pallas, got {prefill_attn!r}")
        self.prefill_attn = prefill_attn
        self._pack_impl = "pallas" if (
            prefill_attn == "flash_pallas"
            or (prefill_attn == "flash"
                and jax.default_backend() == "tpu")) else "reference"
        # W8A8 decode-FFN compute: per-layer A/B as a (layers,) bool
        # baked into the step. Gated on the int8 arena — an operator
        # who priced the KV at 8 bits has already accepted 8-bit error
        # on the decode path; off by default on CPU ("auto").
        L = model.blocks.num_layers
        if w8a8 in (None, False, "off"):
            self._w8a8_mask = None
        else:
            if w8a8 == "auto":
                on = self.pool.quantized \
                    and jax.default_backend() == "tpu"
                mask = np.ones(L, bool) if on else None
            else:
                if not self.pool.quantized:
                    raise ValueError(
                        "w8a8 needs the int8 arena (cache_dtype="
                        "jnp.int8): the quantized-compute lane is "
                        "gated on pools already accepting 8-bit error")
                if w8a8 in (True, "on"):
                    mask = np.ones(L, bool)
                else:                     # iterable of layer indices
                    mask = np.zeros(L, bool)
                    mask[np.asarray(list(w8a8), int)] = True
            self._w8a8_mask = jnp.asarray(mask) if mask is not None \
                else None
        # pre-quantized W8A8 weight tree: the decode lane's weights
        # never change between steps, so quantize ONCE here (and again
        # on every swap_params — stale int8 weights would silently
        # serve old parameters) instead of per fused step
        self._w8a8_wq = self._prequantize_decode_weights()

        self._fn = self._build_step()
        self._cp_fn = self._build_cp_prefill() \
            if self._cp_buckets is not None else None
        self._spill_fn, self._resume_fn = self._build_spill_resume()

    # -- KV spill / resume (resumable preemption) ---------------------------
    def _build_spill_resume(self):
        """Two tiny jits over the arena, both operating on a fixed
        ``table_width`` lane of block ids (DATA — one compile each,
        audited like the fused step):

        - spill: gather a request's blocks ``(L, W, bs, ...)`` for the
          device→host copy (pad lanes gather the null block and are
          sliced off host-side);
        - resume: scatter host-refilled block data into FRESH block
          ids (pad lanes target ``n_blocks`` → dropped). Donates the
          arena (the old buffer is dead the moment the new one lands).
        """
        def spill(caches, blk_ids):
            record_trace("serving_kv_spill")
            return jax.tree.map(
                lambda c: jnp.take(c, blk_ids, axis=1), caches)

        def resume(caches, data, blk_ids):
            record_trace("serving_kv_resume")
            return jax.tree.map(
                lambda c, d: c.at[:, blk_ids].set(
                    d.astype(c.dtype), mode="drop"), caches, data)

        return (jax.jit(spill), jax.jit(resume, donate_argnums=(0,)))

    def _prequantize_decode_weights(self):
        """Build the decode lane's pre-quantized W8A8 weight tree from
        the CURRENT params (None when the lane is off). The tree rides
        the fused step as a traced operand — not a closure — so
        :meth:`swap_params` only has to rebuild the tree, never the
        compiled step."""
        if self._w8a8_mask is None:
            return None
        mlp = self.model.blocks.block.mlp
        return mlp.prequantize(self.params["blocks"]["mlp"],
                               stacked=True)

    # -- the jit-once fused step --------------------------------------------
    def _build_step(self):
        model = self.model
        R = self._fin_cap
        K = self.spec_depth
        kern = self.attn_kernel
        w8a8_mask = self._w8a8_mask
        flash_lane = self.prefill_attn != "reference"
        pack_impl = self._pack_impl
        # the draftsman's q rows: host-only draftsmen (and no
        # draftsman) propose deterministically, so q is the one-hot of
        # the draft — synthesized on-device; a device draftsman ships
        # its sampled softmax rows through spec["q"]
        host_q = self._draftsman is None \
            or getattr(self._draftsman, "host_only", True)

        def step(params, caches, ctl, pf, bt, cow, spec, wq, lora):
            record_trace("serving_step")    # churn must never re-enter

            # copy-on-write block copies for this iteration's partial
            # prefix hits: dst indexes are the arena size (dropped) on
            # unused lanes, and the whole pass is cond-gated — the
            # common decode-only iteration never pays the per-leaf
            # gather/scatter. The copies land BEFORE any lane writes.
            def apply_cow(cs):
                def one(c):
                    src = jnp.take(c, cow["src"], axis=1)
                    return c.at[:, cow["dst"]].set(src, mode="drop")
                return jax.tree.map(one, cs)

            caches = jax.lax.cond(cow["run"], apply_cow,
                                  lambda cs: cs, caches)

            # the decode lane is a VERIFY lane (speculative decoding):
            # every slot feeds its last token plus up to K drafted
            # tokens as K+1 q rows spanning positions pos..pos+K — one
            # forward both writes their KV and yields each row's target
            # distribution, and ``speculative_verify`` runs the
            # rejection-sampling acceptance rule per slot: draft i
            # survives with prob min(1, p/q) (exactly the greedy
            # leading-match rule at temperature 0, where q is one-hot),
            # and the first rejection resamples from the normalized
            # residual max(0, p - q) — so the committed stream is
            # distributed exactly as sequential sampling. Per-slot
            # draft depth (spec["len"]) is DATA: depth 0 reduces to the
            # classic one-token decode, bit for bit. Rows past a slot's
            # depth are masked from writing (row_mask) — their
            # positions may lie beyond the blocks its table owns.
            # Free/prefilling slots compute garbage that the masks keep
            # out of the pool and the host ignores; cond-gated so
            # prefill-only iterations skip the discarded forward.
            def do_decode(caches):
                lane = jnp.arange(K + 1)[None, :]
                tok_in = jnp.concatenate(
                    [ctl["last_tok"][:, None], spec["tok"]], axis=1)
                positions = ctl["pos"][:, None] + lane
                row_valid = (lane <= spec["len"][:, None]) \
                    & ctl["active"][:, None]
                # multi-tenant BGMV: every token row carries its slot's
                # adapter arena page as DATA (page 0 = base, bitwise) —
                # adapter load/evict/mixed-tenant churn never retraces
                logits, caches = generation.decode(
                    model, params, tok_in, positions, caches,
                    slot_mask=ctl["active"], block_tables=bt,
                    row_mask=row_valid, attn_kernel=kern,
                    w8a8_mask=w8a8_mask, w8a8_wq=wq,
                    lora={"ids": jnp.broadcast_to(
                        ctl["adapter"][:, None], tok_in.shape),
                        "pages": lora} if lora else None)
                # proposal probs q: host draftsmen propose
                # deterministically — their q is the one-hot of the
                # draft, synthesized here so the host never ships a
                # (S, K, V) table; a device draftsman's sampled
                # softmax rows ride in through spec["q"]
                V = logits.shape[-1]
                if host_q:
                    qprobs = jax.nn.one_hot(spec["tok"], V,
                                            dtype=jnp.float32)
                else:
                    qprobs = spec["q"].astype(jnp.float32)
                committed, ncommit, last_tok, new_kd = jax.vmap(
                    speculative_verify)(
                    logits, spec["tok"], spec["len"], qprobs,
                    ctl["temp"], ctl["topk"], ctl["topp"],
                    ctl["key"])
                # inactive slots must not burn PRNG state — their
                # sampling stream has to match one-shot generate
                new_kd = jnp.where(ctl["active"][:, None],
                                   new_kd, ctl["key"])
                return caches, committed, ncommit, last_tok, new_kd

            def no_decode(caches):
                S = ctl["pos"].shape[0]
                z = jnp.zeros((S,), jnp.int32)
                return (caches, jnp.zeros((S, K + 1), jnp.int32),
                        z, z, ctl["key"])

            caches, committed, ncommit, last_tok, new_kd = jax.lax.cond(
                ctl["active"].any(), do_decode, no_decode, caches)

            # packed prefill: a C-token budget shared by every
            # admitting request — per-token (slot, position) operands
            # are the cu_seqlens of this lane. Each pack token is one
            # batch row of the per-row paged decode: layer l writes
            # every row's K/V before attending, so rows of the same
            # request see their in-pack predecessors exactly like a
            # dense chunk. (cond keeps idle iterations free.)
            def do_prefill(caches):
                if flash_lane:
                    # packed FLASH prefill: the whole chunk as ONE
                    # (1, C) row — intra-pack flash with segment
                    # isolation (ids = slots, -1 pads), LSE-combined
                    # with each token's arena history (positions
                    # < its chunk-start offset) through the paged
                    # read path. KV writes stay per-token scatters.
                    pos = pf["pos"][None, :]                 # (1, C)
                    h = model.embed(params, pf["tokens"][None, :],
                                    positions=pos)
                    h, caches = model.blocks.decode(
                        params["blocks"], h, caches, positions=pos,
                        block_tables=jnp.take(bt, pf["slot"], axis=0),
                        attn_kernel=kern,
                        pack={"segment_ids": pf["seg"][None, :],
                              "hist": pf["hist"],
                              "valid": pf["valid"],
                              "impl": pack_impl},
                        lora={"ids": jnp.take(ctl["adapter"],
                                              pf["slot"])[None, :],
                              "pages": lora} if lora else None)
                    hrow = h[0]                              # (C, E)
                else:
                    pos = pf["pos"][:, None]                 # (C, 1)
                    h = model.embed(params, pf["tokens"][:, None],
                                    positions=pos)
                    h, caches = model.blocks.decode(
                        params["blocks"], h, caches, positions=pos,
                        slot_mask=pf["valid"],
                        block_tables=jnp.take(bt, pf["slot"], axis=0),
                        attn_kernel=kern,
                        lora={"ids": jnp.take(ctl["adapter"],
                                              pf["slot"])[:, None],
                              "pages": lora} if lora else None)
                    hrow = h[:, 0]                           # (C, E)
                # FIRST tokens for the <= R requests whose prefill
                # completes this iteration: head only on their last
                # real rows (never the full pack's vocab projection)
                hf = jnp.take(hrow, pf["fin_row"], axis=0)[:, None]
                hf = model.hidden_norm(params, hf)
                w = generation._head_weight(model, params)
                lg = jnp.einsum("bse,ve->bsv", hf.astype(jnp.float32),
                                w.astype(jnp.float32))[:, 0]
                fs = pf["fin_slot"]

                # first-token sampling mirrors generate's prefill
                # exactly: split the slot's key once, draw with the
                # sub — so an identical-seed request's whole sampling
                # stream is bitwise the one-shot generate stream
                def sample_row(lg_row, temp, tk, tp, kdr):
                    k = jax.random.wrap_key_data(kdr)
                    k, sub = jax.random.split(k)
                    masked = adjust_logits(lg_row, temp, tk, tp)
                    drawn = jax.random.categorical(sub, masked)
                    tok = jnp.where(temp == 0.0,
                                    jnp.argmax(lg_row, axis=-1),
                                    drawn)
                    return (tok.astype(jnp.int32),
                            jax.random.key_data(k))

                firsts, pf_kd = jax.vmap(sample_row)(
                    lg, jnp.take(ctl["temp"], fs),
                    jnp.take(ctl["topk"], fs),
                    jnp.take(ctl["topp"], fs),
                    jnp.take(ctl["key"], fs, axis=0))
                return caches, firsts, pf_kd

            def no_prefill(caches):
                return (caches, jnp.zeros((R,), jnp.int32),
                        jnp.take(ctl["key"], pf["fin_slot"], axis=0))

            caches, first_toks, pf_kd = jax.lax.cond(
                pf["run"], do_prefill, no_prefill, caches)
            # prefill completions ADOPT their post-sample key state:
            # scatter the <= R finished rows' keys over the slot axis
            # (unused fin rows target S and drop)
            S = ctl["pos"].shape[0]
            scat = jnp.where(pf["run"] & pf["fin_valid"],
                             pf["fin_slot"], S)
            new_key = new_kd.at[scat].set(pf_kd, mode="drop")
            # device-resident control advance: every active slot
            # committed ncommit tokens (accepted drafts + the verify
            # token — their KV landed at pos..pos+ncommit-1), so
            # pos+ncommit / last_tok — returned so the host can reuse
            # the control vectors NEXT iteration without re-uploading
            # them (it falls back to a host rebuild only when an
            # admission / prefill completion / finish rewrote control
            # state)
            new_pos = ctl["pos"] + jnp.where(ctl["active"], ncommit, 0)
            new_last = jnp.where(ctl["active"], last_tok,
                                 ctl["last_tok"])
            return (caches, committed, ncommit, first_toks,
                    new_pos, new_last, new_key)

        return jax.jit(step, donate_argnums=(1,))

    # -- the CP-prefill lane ------------------------------------------------
    def _build_cp_prefill(self):
        """jit of the long-prompt one-pass prefill: a TRAINING-mode
        forward (so attention routes through ring/ulysses when the
        plan's cp axis is live) whose per-layer rotary-applied KV
        (``StackedBlocks.prefill``) scatters into the paged arena
        through the slot's wide block table, plus the first sampled
        token from the prompt's last real row.

        Prompt length is a BUCKETED shape (``self._cp_buckets``); the
        real length ``fin_pos + 1`` is data, so one executable per lane
        bucket serves any prompt in it —
        ``record_trace("serving_cp_prefill")`` audits exactly that.
        """
        model = self.model
        n_blk, blk = self.pool.n_blocks, self.pool.block_size
        quant = self.pool.quantized
        # the lane's attention impl: the flash prefill lanes route the
        # training-mode forward through flash_attention_pallas ("auto"
        # lets the dispatch gate check tiling support on the real chip;
        # "pallas" is the explicit/interpret test mode), reference is
        # the dense oracle — the ring/zigzag cp split reuses whichever
        # kernel per shard (ring_attention(impl=...))
        cp_impl = {"reference": "reference", "flash": "auto",
                   "flash_pallas": "pallas"}[self.prefill_attn]

        def cp_prefill(params, caches, tokens, positions, table,
                       fin_pos, temp, topk, topp, key):
            record_trace("serving_cp_prefill")   # <= n lane buckets
            h = model.embed(params, tokens, positions=positions)
            # segment ids split the bucket row into prompt (0) vs pad
            # (1): pad rows — whose KV the scatter drops anyway — stop
            # attending the prompt, and the flash kernel gets the
            # packed-varlen operands data/packing.py standardized
            seg = (positions > fin_pos).astype(jnp.int32)
            h, (ks, vs) = model.blocks.prefill(params["blocks"], h,
                                               positions=positions,
                                               segment_ids=seg,
                                               attn_impl=cp_impl)
            # scatter each layer's (L,) prompt rows into the arena at
            # the rows the slot's table maps; pad rows (beyond the real
            # prompt) target n_blk*blk and drop. Zigzag cp layouts feed
            # PERMUTED rows — positions ride along, so every row still
            # lands at its own absolute index.
            pos = positions[0]
            blk_ids = jnp.take(table[0], pos // blk)
            rows = jnp.where(pos <= fin_pos,
                             blk_ids * blk + pos % blk, n_blk * blk)

            def scat(buf, new):
                flat = buf.reshape((buf.shape[0], n_blk * blk)
                                   + buf.shape[3:])
                flat = flat.at[:, rows].set(new.astype(buf.dtype),
                                            mode="drop")
                return flat.reshape(buf.shape)

            k_new, v_new = ks[:, 0], vs[:, 0]    # (layers, L, hkv, d)
            if quant:
                from hetu_tpu.ops.quantization import quantize_int8
                kq, ksc = quantize_int8(k_new, axis=-1)
                vq, vsc = quantize_int8(v_new, axis=-1)
                caches = (scat(caches[0], kq), scat(caches[1], ksc),
                          scat(caches[2], vq), scat(caches[3], vsc))
            else:
                caches = (scat(caches[0], k_new),
                          scat(caches[1], v_new))
            # first token: head only on the last REAL row (found by
            # position match — layout-permutation proof)
            fin_row = jnp.argmax(pos == fin_pos)
            hf = model.hidden_norm(params, h[:, fin_row][:, None])
            w = generation._head_weight(model, params)
            lg = jnp.einsum("bse,ve->bsv", hf.astype(jnp.float32),
                            w.astype(jnp.float32))[:, 0]
            # per-request key chain, same as the packed lane: split
            # once, draw with the sub, return the advanced state
            k = jax.random.wrap_key_data(key)
            k, sub = jax.random.split(k)
            masked = adjust_logits(lg[0], temp[0], topk[0], topp[0])
            drawn = jax.random.categorical(sub, masked)
            tok = jnp.where(temp[0] == 0.0,
                            jnp.argmax(lg[0], axis=-1), drawn)
            return caches, tok.astype(jnp.int32), \
                jax.random.key_data(k)

        return jax.jit(cp_prefill, donate_argnums=(1,))

    def _prep_cp_prefill_locked(self) -> Optional[dict]:
        """Pop ONE pending CP-lane request and build its host operands
        (caller holds ``self._lock``). One per engine iteration: a
        burst of long prompts interleaves with decode iterations
        instead of starving every active slot back-to-back — the lane's
        analogue of the packed lane's per-iteration chunk budget."""
        if not self._cp_pending:
            return None
        ent = self._cp_pending.pop(0)
        req, slot = ent["req"], ent["slot"]
        P = len(req.prompt)
        L = self._cp_buckets.bucket_for(P)
        tokens = np.zeros((1, L), np.int32)
        tokens[0, :P] = req.prompt
        positions = np.arange(L, dtype=np.int32)[None, :]
        if self._cp_zigzag:
            from hetu_tpu.data.packing import zigzag_permute
            tokens = zigzag_permute(tokens, self._cp, axis=1)
            positions = zigzag_permute(positions, self._cp, axis=1)
        return {"req": req, "slot": slot, "P": P, "bucket": L,
                "tokens": tokens, "positions": positions,
                "table": self._bt[slot:slot + 1].copy(),
                # the slot's admission-seeded commit key (raw state):
                # the CP lane samples the first token from the SAME
                # per-request stream the packed lane would have
                "key": self._key_state[slot].copy()}

    def _exec_cp_prefill(self, job: dict, t0: float, reg) -> None:
        """Run one prepared CP-lane prefill. The device call happens
        WITHOUT ``self._lock`` (submit()/load stay responsive through a
        multi-second cold-bucket compile or a 100k-token forward; the
        operands were snapshotted under the lock, and everything the
        call touches — arena, params, tables — is only ever mutated by
        ``_step_lock`` holders, which we are)."""
        req, slot, P = job["req"], job["slot"], job["P"]
        sp = req.sampling
        ctx = self._plan.act if self._plan is not None \
            else contextlib.nullcontext()
        with ctx:
            caches, tok, kd = self._cp_fn(
                self.params, self.pool.caches, job["tokens"],
                job["positions"], job["table"], np.int32(P - 1),
                np.asarray([sp.temperature], np.float32),
                np.asarray([sp.top_k], np.int32),
                np.asarray([sp.top_p], np.float32), job["key"])
        self.pool.caches = caches
        now = time.monotonic()
        with self._lock:
            self._key_state[slot] = np.asarray(kd)
            self._pos[slot] = P
            self._active[slot] = True
            self._ctl_dirty = True
            req.status = "decode"
            req.first_token_s = now
            req.mark("prefill_chunk", dur_s=now - t0, ts_s=t0)
            req.mark("first_token", ts_s=now)
            ttft = now - req.submit_s
            reg.histogram("serving_ttft_seconds",
                          "time submit -> first token").observe(ttft)
            if self.slo is not None:
                self.slo.observe("serving_ttft_seconds", ttft)
            reg.counter("serving_tokens_total",
                        "serving tokens by kind").inc(P, kind="prompt")
            reg.counter(
                "serving_cp_prefill_requests_total",
                "long prompts prefilled through the CP lane (one "
                "cp-sharded pass instead of rejection)").inc()
            reg.counter(
                "serving_cp_prefill_tokens_total",
                "prompt tokens prefilled through the CP lane").inc(P)
            reg.counter(
                "prefill_attn_kernel_total",
                "prefill-lane executions by attention path (flash "
                "= packed/CP flash lane, reference = per-token "
                "gather math)").inc(
                path="flash" if self.prefill_attn != "reference"
                else "reference")
            flight_record("serving_cp_prefill", req=req.id,
                          trace=req.trace_id, slot=slot, tokens=P,
                          bucket=job["bucket"])
            # no prefix-cache insert: lane blocks stay private to the
            # slot (long-prompt prefix sharing is future work)
            self._on_token(slot, int(tok), now, reg)

    # -- resumable preemption (QoS) -----------------------------------------
    def _plan_preemption_locked(self) -> Optional[dict]:
        """Decide whether this iteration evicts a running request for a
        blocked more-urgent one (caller holds ``self._lock``, and the
        admission pass has ALREADY run — so a head still queued here
        genuinely could not admit, even net of prefix-cache credit and
        cache eviction). At most one preemption per iteration; the
        spill itself (a device→host gather) runs outside the lock.
        Fires only when (a) that blocked head exists, (b) a STRICTLY
        lower-priority request is decoding, and (c) the host arena can
        hold its blocks — so uniform-priority traffic keeps the
        historical run-to-completion guarantee untouched."""
        if not self.preempt or not self.scheduler.queue:
            return None
        cand = self.scheduler.peek_candidate()
        if cand is None:
            return None
        running = [(s, r) for s, r in enumerate(self._slot_req)
                   if r is not None and self._active[s]]
        slot = self.scheduler.preemption_victim(cand, running)
        if slot is None:
            return None
        nb = max(1, -(-int(self._pos[slot]) // self.pool.block_size))
        if not self.spill_arena.can_fit(nb):
            return None
        return {"req": self._slot_req[slot], "slot": slot, "nb": nb,
                "ids": self._bt[slot].copy()}

    def _spill_blocks(self, ids: np.ndarray, nb: int) -> tuple:
        """Device→host copy of ``nb`` blocks (the compiled gather runs
        over the fixed table width; pad lanes read the null block and
        are sliced off)."""
        lane_ids = np.zeros(self.pool.table_width, np.int32)
        lane_ids[:nb] = ids[:nb]
        ctx = self._plan.act if self._plan is not None \
            else contextlib.nullcontext()
        with ctx:
            gathered = self._spill_fn(self.pool.caches,
                                      jnp.asarray(lane_ids))
        return tuple(np.asarray(g)[:, :nb].copy() for g in gathered)

    def _detach_locked(self, req: Request, slot: int) -> None:
        """Free ``slot`` and everything ``req`` holds on the device
        (caller holds ``self._lock``); the request's fate — requeue,
        resume elsewhere, or drop — is the caller's."""
        self.scheduler.release(slot, table=self._bt[slot])
        self._bt[slot, :] = 0
        self._active[slot] = False
        self._slot_req[slot] = None
        self._adapter_page[slot] = 0
        self._ctl_dirty = True

    def _exec_spill(self, job: dict, reg) -> None:
        """Evict one running request into the host arena and requeue it
        at the head of its class — the resumable half of preemption."""
        req, slot, nb = job["req"], job["slot"], job["nb"]
        data = self._spill_blocks(job["ids"], nb)
        now = time.monotonic()
        with self._lock:
            entry = SpillEntry(
                req_id=req.id, data=data, n_blocks=nb,
                block_size=self.pool.block_size,
                pos=int(self._pos[slot]),
                last_tok=int(self._last_tok[slot]),
                tokens=list(req.tokens),
                weight_version=req.weight_version,
                key_state=self._key_state[slot].copy(),
                adapter=req.kv_adapter)
            self.spill_arena.put(entry)
            req.spill = entry
            req.preemptions += 1
            req.spilled_blocks += nb
            req.mark("preempted", ts_s=now)
            self._detach_locked(req, slot)
            self.scheduler.requeue_preempted(req)
            self.scheduler.preemptions_total += 1
            reg.counter(
                "serving_kv_spilled_blocks_total",
                "KV blocks copied device→host when a request was "
                "preempted (resumable eviction)").inc(nb)
            reg.counter(
                "serving_preemptions_total",
                "running requests evicted for more-urgent arrivals, "
                "by the VICTIM's priority class").inc(
                priority=str(req.sampling.priority))
        flight_record("serving_preempt", req=req.id, trace=req.trace_id,
                      slot=slot, blocks=nb,
                      priority=req.sampling.priority)

    def _exec_resume(self, job: dict, reg) -> None:
        """Map one spilled request's KV back into its freshly allocated
        blocks and flip its slot live — ZERO prefill-lane work (the
        acceptance bar for resumable preemption)."""
        req, slot = job["req"], job["slot"]
        entry = req.spill
        nb = entry.n_blocks
        W = self.pool.table_width
        lane_ids = np.full(W, self.pool.n_blocks, np.int32)  # pad→drop
        lane_ids[:nb] = self._bt[slot, :nb]
        data = []
        for src in entry.data:
            pad = np.zeros((src.shape[0], W) + src.shape[2:], src.dtype)
            pad[:, :nb] = src
            data.append(pad)
        ctx = self._plan.act if self._plan is not None \
            else contextlib.nullcontext()
        with ctx:
            self.pool.caches = self._resume_fn(
                self.pool.caches, tuple(data), jnp.asarray(lane_ids))
        now = time.monotonic()
        with self._lock:
            if self.spill_arena.get(req.id) is entry:
                self.spill_arena.pop(req.id)
            req.spill = None
            req.resumed_blocks += nb
            self._pos[slot] = entry.pos
            self._last_tok[slot] = entry.last_tok
            if entry.key_state is not None:
                # the commit-key stream resumes mid-request: sampling
                # continues bit-for-bit where the spill cut it
                self._key_state[slot] = np.asarray(entry.key_state)
            self._active[slot] = True
            self._ctl_dirty = True
            req.status = "decode"
            if req.first_token_s is None:
                # a cross-engine resume starts a fresh Request: give it
                # a first-token stamp so TPOT math stays defined (no
                # TTFT observation — its real first token happened on
                # the engine it was spilled from)
                req.first_token_s = now
            req.mark("resumed", ts_s=now)
            if self._draftsman is not None:
                self._draftsman.reset(
                    slot, req.prompt.tolist() + list(req.tokens))
            reg.counter(
                "serving_kv_resumed_blocks_total",
                "spilled KV blocks mapped back into fresh arena blocks "
                "on resume (prefill skipped entirely)").inc(nb)
        flight_record("serving_resume", req=req.id, trace=req.trace_id,
                      slot=slot, blocks=nb, pos=entry.pos)

    def evict_request(self, req: Request, *,
                      lock_timeout_s: Optional[float] = None
                      ) -> Optional[SpillEntry]:
        """Force ``req`` out of this engine RIGHT NOW, returning its
        spill entry when it had resident KV (a decoding slot, or a
        not-yet-mapped resume) and None otherwise (queued/prefilling —
        nothing worth moving). The fleet layer's half of resumable
        requeue: ``Router`` calls this on replica death and on
        preemptive drains, then re-dispatches the request — with the
        entry — onto a peer, which resumes it without re-prefilling.
        The request's ``done`` event is NOT set (the router owns its
        completion).

        ``lock_timeout_s`` bounds the wait for the engine's iteration
        lock: a replica declared dead because its step is WEDGED (the
        watchdog scenario) still holds that lock, and a caller that
        blocked on it forever would freeze whatever it holds — the
        router passes a small timeout and degrades to a fresh requeue
        (the pre-spill behavior) when salvage cannot be had."""
        got = self._step_lock.acquire(
            timeout=-1 if lock_timeout_s is None else lock_timeout_s)
        if not got:
            return None
        try:
            entry = self._evict_request_steplocked(req)
        finally:
            self._step_lock.release()
        if req.status in ("evicted", "cancelled"):
            self._stream_interrupt(req)
        if entry is not None and entry.traceparent is None:
            # stamp the originating trace context onto the spill so the
            # decode-tier resume joins the same fleet trace (ISSUE 16)
            entry.traceparent = req.traceparent \
                or telemetry.make_traceparent(req.trace_id)
        if entry is not None and req.handoff:
            # a parked (P/D handoff) request never reaches _finish in
            # this process — emit its queued/prefill spans now so the
            # prefill tier's fragment exists for fleet_trace to merge
            self._emit_request_trace(req)
        return entry

    def _evict_request_steplocked(self, req: Request
                                  ) -> Optional[SpillEntry]:
        spill_plan = None
        with self._lock:
            if req.done.is_set():
                return None
            if req in self.scheduler.queue:
                self.scheduler.queue.remove(req)
                entry = req.spill
                if entry is not None \
                        and self.spill_arena.get(req.id) is entry:
                    self.spill_arena.pop(req.id, resumed=False)
                req.status = "evicted"
                self._release_tenancy(req)
                return entry
            for ent in list(self._resume_pending):
                if ent["req"] is req:
                    self._resume_pending.remove(ent)
                    entry = req.spill
                    if entry is not None and \
                            self.spill_arena.get(req.id) is entry:
                        self.spill_arena.pop(req.id, resumed=False)
                    self._detach_locked(req, ent["slot"])
                    req.status = "evicted"
                    self._release_tenancy(req)
                    return entry
            for ent in list(self._prefilling):
                if ent["req"] is req:
                    self._prefilling.remove(ent)
                    self._detach_locked(req, ent["slot"])
                    req.status = "evicted"
                    self._release_tenancy(req)
                    return None
            for ent in list(self._cp_pending):
                if ent["req"] is req:
                    self._cp_pending.remove(ent)
                    self._detach_locked(req, ent["slot"])
                    req.status = "evicted"
                    self._release_tenancy(req)
                    return None
            slot = req.slot
            # a "prefilled" request is PARKED (P/D handoff): its slot is
            # inactive but still owns the request and its KV blocks —
            # exactly what the prefill tier evicts to stream downstream
            parked = req.status == "prefilled" and slot is not None \
                and self._slot_req[slot] is req
            if slot is None or self._slot_req[slot] is not req \
                    or not (self._active[slot] or parked):
                return None
            nb = max(1, -(-int(self._pos[slot])
                          // self.pool.block_size))
            spill_plan = {"slot": slot, "nb": nb,
                          "ids": self._bt[slot].copy(),
                          "pos": int(self._pos[slot]),
                          "last_tok": int(self._last_tok[slot]),
                          "key_state": self._key_state[slot].copy()}
        # the device gather runs without self._lock (submit()/load
        # stay responsive) but under the iteration lock we hold
        data = self._spill_blocks(spill_plan["ids"],
                                  spill_plan["nb"])
        with self._lock:
            entry = SpillEntry(
                req_id=req.id, data=data,
                n_blocks=spill_plan["nb"],
                block_size=self.pool.block_size,
                pos=spill_plan["pos"],
                last_tok=spill_plan["last_tok"],
                tokens=list(req.tokens),
                weight_version=req.weight_version,
                key_state=spill_plan["key_state"],
                adapter=req.kv_adapter)
            self._detach_locked(req, spill_plan["slot"])
            req.status = "evicted"
            self._release_tenancy(req)
            req.spilled_blocks += spill_plan["nb"]
            telemetry.get_registry().counter(
                "serving_kv_spilled_blocks_total",
                "KV blocks copied device→host when a request was "
                "preempted (resumable eviction)").inc(
                spill_plan["nb"])
        flight_record("serving_evict", req=req.id,
                      trace=req.trace_id, slot=spill_plan["slot"],
                      blocks=spill_plan["nb"])
        return entry

    # -- fleet-global KV plane (ISSUE 18) -----------------------------------
    def export_prefix(self, tokens: Sequence[int], *,
                      lock_timeout_s: Optional[float] = 2.0
                      ) -> Optional[SpillEntry]:
        """Gather this engine's cached whole-block prefix of ``tokens``
        into a :class:`SpillEntry` for a peer pull (the KVEXPORT verb).

        Read-only: the prefix cache keeps its refs and LRU order is the
        only state touched — the gather runs under the iteration lock,
        which freezes all block churn (admission, eviction and the trie
        flush all run step-locked), so no pin/unpin dance is needed.
        None on a whole-block miss or a wedged step (``lock_timeout_s``
        bounds the wait — a pull is best-effort, the puller prefills)."""
        if self.prefix_cache is None:
            return None
        got = self._step_lock.acquire(
            timeout=-1 if lock_timeout_s is None else lock_timeout_s)
        if not got:
            return None
        try:
            with self._lock:
                toks = [int(t) for t in tokens]
                shared, _partial = self.prefix_cache.match(toks)
                nb = min(len(shared), self.pool.table_width)
                if nb == 0:
                    return None
                version = self.weight_version
                ids = np.asarray(shared[:nb], np.int32)
            data = self._spill_blocks(ids, nb)
        finally:
            self._step_lock.release()
        bs = self.pool.block_size
        entry = SpillEntry(
            req_id=-1, data=data, n_blocks=nb, block_size=bs,
            pos=nb * bs, last_tok=0, tokens=toks[:nb * bs],
            weight_version=version)
        flight_record("fleet_kv_export", blocks=nb, tokens=nb * bs)
        return entry

    def import_prefix(self, entry: Optional[SpillEntry], *,
                      lock_timeout_s: Optional[float] = 2.0) -> bool:
        """Map a peer-exported prefix into THIS engine's prefix cache
        (the KVIMPORT verb): allocate fresh arena blocks, scatter the
        wire data in, insert the token runs into the radix trie — from
        here on it is an ordinary same-replica prefix hit (refcounted,
        CoW rules unchanged, LRU-evictable like any cached prefix).

        Refuses — returns False, caller falls back to a plain
        prefill — an entry whose weight version or arena layout does
        not match (:meth:`SpillEntry.compatible_with` is the staleness
        rule: a weight push between export and import MUST degrade to
        a prefill, never silently serve old weights' KV), and degrades
        the same way when no blocks can be freed."""
        if self.prefix_cache is None or entry is None:
            return False
        if not entry.compatible_with(self.pool, self.weight_version):
            flight_record("fleet_kv_import_refused",
                          blocks=entry.n_blocks,
                          entry_version=entry.weight_version,
                          our_version=self.weight_version)
            return False
        toks = [int(t) for t in entry.tokens]
        nb = entry.n_blocks
        if nb < 1 or len(toks) < nb * self.pool.block_size:
            return False
        got = self._step_lock.acquire(
            timeout=-1 if lock_timeout_s is None else lock_timeout_s)
        if not got:
            return False
        try:
            with self._lock:
                shared, _partial = self.prefix_cache.match(toks)
                if len(shared) >= nb:
                    return True          # already fleet-warm here
                new_ids = []
                for _ in range(nb):
                    b = self.blocks.alloc()
                    if b is None and self.prefix_cache.evict(
                            nb - len(new_ids)):
                        b = self.blocks.alloc()
                    if b is None:        # arena genuinely full of
                        for x in new_ids:    # pinned work: no import
                            self.blocks.release(x)
                        return False
                    new_ids.append(b)
            # scatter outside self._lock (submit/load stay responsive)
            # but under the iteration lock we hold — the resume jit
            # DONATES the arena, exactly like _exec_resume
            W = self.pool.table_width
            lane_ids = np.full(W, self.pool.n_blocks, np.int32)
            lane_ids[:nb] = new_ids
            data = []
            for src in entry.data:
                pad = np.zeros((src.shape[0], W) + src.shape[2:],
                               src.dtype)
                pad[:, :nb] = src
                data.append(pad)
            ctx = self._plan.act if self._plan is not None \
                else contextlib.nullcontext()
            with ctx:
                self.pool.caches = self._resume_fn(
                    self.pool.caches, tuple(data),
                    jnp.asarray(lane_ids))
            with self._lock:
                self.prefix_cache.insert(
                    toks[:nb * self.pool.block_size], new_ids)
                # insert() took the trie's own ref on every node it
                # adopted; dropping ours leaves the trie sole holder
                # (LRU-evictable). A block whose token run was cached
                # concurrently goes straight back to the free list.
                for b in new_ids:
                    self.blocks.release(b)
        finally:
            self._step_lock.release()
        flight_record("fleet_kv_import", blocks=nb)
        return True

    # -- decode-KV replication, origin side (ISSUE 18) ----------------------
    def configure_replication(self, sink, *, origin: str = "",
                              cadence_s: float = 0.02) -> None:
        """Point this engine's decode-KV replication stream at ``sink``
        — a callable taking one JSON-safe shipment doc (in-process: the
        buddy's ``KVReplicaStore.put``; cross-process: a KVREPL wire
        closure installed by the KVBUDDY verb). ``sink=None`` stops the
        stream. The router (re)wires this whenever rendezvous buddy
        assignment changes."""
        with self._lock:
            self._repl_sink = sink
            self._repl_origin = origin
            self._repl_cadence_s = float(cadence_s)
            if sink is None:
                self._repl_sent.clear()
        if sink is None:
            if self._repl_stop is not None:
                self._repl_stop.set()
            self._repl_thread = None
            return
        if self._repl_thread is None or not self._repl_thread.is_alive():
            self._repl_stop = threading.Event()
            self._repl_thread = threading.Thread(
                target=self._repl_loop, args=(self._repl_stop,),
                daemon=True, name="serving-kv-repl")
            self._repl_thread.start()

    def _repl_loop(self, stop: threading.Event) -> None:
        while not stop.is_set():
            try:
                self._replicate_once()
            except Exception as e:                    # noqa: BLE001
                from hetu_tpu.utils.logging import get_logger
                get_logger().debug(f"kv replication cadence: {e}")
            stop.wait(self._repl_cadence_s)

    def _replicate_once(self) -> None:
        """One replication cadence: for every decoding slot with a new
        COMPLETE block since its last shipment, ship the delta range
        (plus the partial tail block and a consistent pos/tokens/PRNG
        snapshot, captured in the same step-locked breath), then
        tombstone finished requests on the buddy. The step lock is held
        only for the snapshot + device→host gather — never across the
        sink's wire I/O — and is acquired with a cadence-sized timeout
        so a busy step just skips a beat."""
        if self._repl_sink is None:
            return
        bs = self.pool.block_size
        got = self._step_lock.acquire(timeout=self._repl_cadence_s)
        if not got:
            return
        jobs, drops = [], []
        try:
            with self._lock:
                sink = self._repl_sink
                if sink is None:
                    return
                live_ids = set()
                for slot, req in enumerate(self._slot_req):
                    if req is None or not self._active[slot] \
                            or req.handoff:
                        continue
                    live_ids.add(req.id)
                    pos = int(self._pos[slot])
                    complete = pos // bs
                    rec = self._repl_sent.get(req.id)
                    sent = rec[0] if rec is not None else -1
                    if sent >= 0 and complete <= sent:
                        continue    # no new whole block: nothing to do
                    start = max(0, sent)    # re-ship the old tail block
                    cur = max(1, -(-pos // bs))
                    jobs.append({
                        "req": req, "start": start, "cur": cur,
                        "pos": pos, "complete": complete,
                        "last_tok": int(self._last_tok[slot]),
                        "tokens": list(req.tokens),
                        "key_state": self._key_state[slot].copy(),
                        "ids": self._bt[slot, start:cur].copy()})
                for rid, rec in list(self._repl_sent.items()):
                    if rid not in live_ids:
                        drops.append(rec[1])
                        self._repl_sent.pop(rid, None)
            # device→host gathers still under the step lock (the fused
            # step DONATES the arena — unsynchronized reads race)
            for job in jobs:
                job["data"] = self._spill_blocks(
                    job["ids"], job["cur"] - job["start"])
        finally:
            self._step_lock.release()
        if not jobs and not drops:
            return
        from hetu_tpu.serving.fleet import array_to_wire
        reg = telemetry.get_registry()
        sink = self._repl_sink
        if sink is None:
            return
        for job in jobs:
            req = job["req"]
            doc = {"trace_id": req.trace_id,
                   "origin": self._repl_origin,
                   "req_id": req.id,
                   "weight_version": req.weight_version,
                   "block_size": bs, "pos": job["pos"],
                   "last_tok": job["last_tok"],
                   "tokens": job["tokens"], "start": job["start"],
                   "key_state": array_to_wire(job["key_state"]),
                   "traceparent": req.traceparent
                   or telemetry.make_traceparent(req.trace_id),
                   "data": [array_to_wire(a) for a in job["data"]]}
            try:
                sink(doc)
            except Exception:                         # noqa: BLE001
                continue      # buddy unreachable: same range retries
            with self._lock:
                self._repl_sent[req.id] = (job["complete"],
                                           req.trace_id)
            reg.counter(
                "fleet_kv_replicated_blocks_total",
                "decode-KV blocks streamed to the rendezvous buddy "
                "replica (block-granular cadence — the recovery set "
                "SIGKILL resumes from)").inc(job["cur"] - job["start"])
            flight_record("fleet_kv_replicate", req=req.id,
                          trace=req.trace_id, start=job["start"],
                          blocks=job["cur"] - job["start"],
                          pos=job["pos"])
        for tid in drops:
            try:
                sink({"drop": tid})
            except Exception:                         # noqa: BLE001
                pass          # cap-bounded store ages it out instead

    def prefill_only(self, prompt: Sequence[int],
                     sampling: Optional[SamplingParams] = None, *,
                     timeout_s: Optional[float] = None,
                     traceparent: Optional[str] = None
                     ) -> tuple[Request, Optional[SpillEntry]]:
        """Prefill-tier entry point (P/D disaggregation): admit
        ``prompt``, run its prefill (packed or CP lane) through the
        normal iteration machinery, and return ``(req, entry)`` where
        ``entry`` is the evicted :class:`SpillEntry` holding the
        finished KV blocks + the first token — ready to stream to a
        decode-tier replica's ``submit(resume=entry)``. ``entry`` is
        None when the request FINISHED within its first token (EOS or
        ``max_tokens=1`` — nothing left to decode; ``req.result()`` is
        the answer) or was rejected at admission.

        Works both driven (no background loop: iterations run here)
        and with :meth:`start` running (this just waits)."""
        req = self.submit(prompt, sampling, handoff=True,
                          traceparent=traceparent)
        if req.status == "rejected":
            return req, None
        deadline = None if timeout_s is None \
            else time.monotonic() + timeout_s
        while req.status != "prefilled" and not req.done.is_set():
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"prefill_only: request #{req.id} not prefilled "
                    f"within {timeout_s}s (status {req.status!r})")
            if self._thread is None:
                self.step()
            else:
                time.sleep(0.001)
        if req.done.is_set():
            return req, None
        return req, self.evict_request(req)

    # -- submission ---------------------------------------------------------
    def submit(self, prompt: Sequence[int],
               sampling: Optional[SamplingParams] = None, *,
               resume: Optional[SpillEntry] = None,
               handoff: bool = False,
               traceparent: Optional[str] = None) -> Request:
        """Queue one request (deficit-selected by its priority class;
        pure FCFS when every request shares one class). Returns the
        live Request — poll ``req.done`` / :meth:`result`, or drive
        :meth:`step` yourself.

        ``resume`` attaches a KV spill from a peer engine (the
        router's resumable requeue): when the entry still speaks this
        pool's layout AND weight version, the request admits through
        the resume path — already-emitted tokens preloaded, zero
        prefill-lane work. An incompatible entry (e.g. the fleet
        swapped weights since the spill) silently degrades to a fresh
        replay, which under greedy decoding regenerates the same
        tokens.

        ``handoff`` is the prefill-tier mode (P/D disaggregation): the
        request runs admission + prefill here, then PARKS after its
        first token (status ``"prefilled"``, slot inactive but still
        holding its KV blocks) instead of decoding on — the caller
        (``prefill_only`` / the fleet router) evicts the KV and resumes
        it on a decode-tier replica."""
        sampling = sampling or SamplingParams()
        if sampling.adapter is not None and self.tenancy is None:
            raise ValueError(
                "SamplingParams.adapter without tenancy= — construct "
                "the engine with ServingEngine(..., tenancy=True) and "
                "load_adapter first")
        if sampling.temperature > 0 and self.spec_depth \
                and self._draftsman is not None:
            # sampled speculation runs the rejection-sampling verify
            # lane, which needs the draftsman's proposal probs (q) —
            # fail the submit loudly instead of silently mis-sampling
            check_sampled_draft(self._draftsman)
        if handoff and resume is not None:
            raise ValueError(
                "handoff with resume makes no sense: a resumed "
                "request's KV already exists — submit it to the "
                "decode tier directly")
        # adopt the wire trace context: an explicit traceparent wins,
        # else the spill's (a decode-tier resume inherits the trace the
        # prefill tier stamped into the KV stream) — ISSUE 16
        tp = traceparent or (resume.traceparent
                             if resume is not None else None)
        with self._lock:
            req = Request(id=self._next_id,
                          prompt=np.asarray(prompt, np.int32).ravel(),
                          sampling=sampling, submit_s=time.monotonic(),
                          handoff=bool(handoff))
            self._next_id += 1
            if tp:
                tid, _span = telemetry.parse_traceparent(tp)
                if tid:
                    req.trace_id = tid
                    req.traceparent = tp
            exp_adapter = 0
            if self.tenancy is not None and sampling.adapter is not None:
                registry = self.tenancy.registry
                if not registry.has(sampling.tenant, sampling.adapter):
                    req.status = "rejected"
                    req.error = (f"unknown adapter {sampling.tenant}/"
                                 f"{sampling.adapter} — load_adapter "
                                 f"first")
                    req.done.set()
                    admitted = False
                else:
                    exp_adapter = registry.kv_tag(
                        registry.get(sampling.tenant, sampling.adapter))
                    req.kv_adapter = exp_adapter
            if req.status != "rejected":
                if resume is not None and resume.compatible_with(
                        self.pool, self.weight_version,
                        adapter=exp_adapter):
                    req.spill = resume
                    req.tokens = list(resume.tokens)
                    req.weight_version = resume.weight_version
                admitted = self.scheduler.submit(req)
                if admitted and req.cp_lane \
                        and sampling.adapter is not None:
                    # the CP-prefill lane is base-only (its one-pass
                    # training-mode forward has no BGMV thread yet —
                    # docs/SERVING.md): refuse loudly instead of
                    # serving the base model under the tenant's name
                    self.scheduler.queue.remove(req)
                    req.status = "rejected"
                    req.error = (
                        "adapter requests cannot take the CP-prefill "
                        "lane (base-only long-prompt path) — shorten "
                        "the prompt or raise max_len")
                    req.done.set()
                    admitted = False
        reg = telemetry.get_registry()
        reg.counter("serving_requests_total",
                    "serving requests by outcome").inc(
            outcome="submitted" if admitted else "rejected")
        flight_record("serving_submit", req=req.id, trace=req.trace_id,
                      prompt_len=len(req.prompt),
                      outcome="queued" if admitted else "rejected")
        self._record_gauges()
        return req

    def result(self, req: Request,
               timeout: Optional[float] = None) -> Optional[dict]:
        """Wait for ``req`` to finish; None on timeout."""
        if not req.done.wait(timeout):
            return None
        return req.result()

    # -- push subscriptions (ISSUE 19) --------------------------------------
    def stream_subscribe(self, req: Request, *, offset: int = 0,
                         max_queue: int = 256):
        """Subscribe to ``req``'s token stream from token ``offset``:
        the backlog past the offset is replayed immediately (an
        already-finished request yields its single terminal event),
        then the end-of-step pump feeds newly committed tokens. The
        returned :class:`~hetu_tpu.serving.streaming.TokenSubscription`
        is a bounded queue — a consumer that stops draining is dropped,
        never waited on."""
        from hetu_tpu.serving.streaming import (
            TokenSubscription, push_delta,
        )
        sub = TokenSubscription(req.id, offset=offset,
                                max_queue=max_queue)
        with self._stream_lock:
            push_delta(req, sub)        # replay (possibly terminal)
            if not sub.closed:
                ent = self._stream_subs.get(req.id)
                if ent is None:
                    self._stream_subs[req.id] = (req, [sub])
                else:
                    ent[1].append(sub)
        return sub

    def _pump_stream_subs(self) -> None:
        """End-of-step push: fold each subscribed request's newly
        committed tokens (and finish/interrupt markers) into its
        subscriber queues. Enqueue-only, pure host work — the fused
        step's 1-compile audit is untouched and a slow subscriber
        overflows its own bounded queue instead of stalling the
        iteration (drop-to-poll, counted)."""
        if not self._stream_subs:
            return
        from hetu_tpu.serving.streaming import push_delta
        with self._stream_lock:
            for rid in list(self._stream_subs):
                req, subs = self._stream_subs[rid]
                for sub in subs:
                    push_delta(req, sub)
                live = [s for s in subs
                        if not (s.closed or s.dropped)]
                if live:
                    self._stream_subs[rid] = (req, live)
                else:
                    del self._stream_subs[rid]

    def _stream_interrupt(self, req: Request) -> None:
        """Close ``req``'s subscriptions after an out-of-band exit
        (evict / cancel, which happen between steps): the final delta
        plus an ``end`` marker tells subscribers to fall back — the
        router's requeue owns the request now."""
        if not self._stream_subs:
            return
        from hetu_tpu.serving.streaming import push_delta
        with self._stream_lock:
            ent = self._stream_subs.pop(req.id, None)
        if ent is None:
            return
        for sub in ent[1]:
            push_delta(req, sub)
            sub.close()

    # -- the host loop ------------------------------------------------------
    def has_work(self) -> bool:
        with self._lock:
            return bool(self.scheduler.queue) or self._active.any() \
                or bool(self._prefilling) or bool(self._cp_pending) \
                or bool(self._resume_pending)

    @property
    def load(self) -> int:
        """Instantaneous work on this engine — queued + prefilling +
        decoding requests. The router's least-loaded dispatch reads
        exactly this (it is what the ``serving_queue_depth`` /
        ``serving_slot_occupancy`` gauges sample, as one number)."""
        with self._lock:
            return self.scheduler.depth + len(self._prefilling) \
                + len(self._cp_pending) + len(self._resume_pending) \
                + int(self._active.sum())

    # -- fleet lifecycle (router drain / live weight push) ------------------
    def cancel_queued(self, ids=None) -> list[Request]:
        """Pull QUEUED (not yet admitted) requests out of the scheduler
        and return them — the router's drain path re-dispatches them
        onto peer replicas. ``ids`` restricts the pull to those request
        ids (the router passes the set it owns, so a request submitted
        DIRECTLY to this engine is never orphaned — it stays queued and
        drains through normal admission). Admitted requests are always
        untouched: their KV is resident, so finishing them here is
        strictly cheaper than regenerating elsewhere."""
        with self._lock:
            if ids is None:
                out = list(self.scheduler.queue)
                self.scheduler.queue.clear()
            else:
                out = [r for r in self.scheduler.queue if r.id in ids]
                for r in out:
                    self.scheduler.queue.remove(r)
            # a preempted request leaving the engine takes its spill
            # with it (the peer that resumes it counts the map-back)
            for r in out:
                if r.spill is not None \
                        and self.spill_arena.get(r.id) is r.spill:
                    self.spill_arena.pop(r.id, resumed=False)
                self._release_tenancy(r)
        return out

    def swap_params(self, params, *, version: Optional[int] = None) -> dict:
        """Install a new parameter pytree on a DRAINED engine — the
        replica-local leg of a zero-downtime fleet weight push.

        Grabs the iteration lock (so a live :meth:`start` loop is
        between iterations — it never stops), requires no in-flight
        work (drain first: queued work was re-dispatched by the router,
        admitted work ran out under the old weights), bumps the weight
        generation on the engine + KV pool, and flushes the prefix
        cache's now-stale residents. The caller owns ``params``'s
        placement: pass buffers that nothing will donate later
        (``serving.router.materialize_params``)."""
        with self._step_lock:
            with self._lock:
                if self.scheduler.queue or self._prefilling \
                        or self._cp_pending or self._resume_pending \
                        or self._active.any():
                    raise RuntimeError(
                        "swap_params on a busy engine — drain first "
                        "(cancel_queued + wait for has_work() to clear)"
                        ": in-flight KV was prefilled under the old "
                        "weights")
                self.params = params
                # stale int8 decode weights would silently serve the
                # OLD parameters — re-quantize from the new tree
                self._w8a8_wq = self._prequantize_decode_weights()
                self.weight_version = int(version) \
                    if version is not None else self.weight_version + 1
                self.pool.weight_version = self.weight_version
                flushed = 0
                if self.prefix_cache is not None:
                    flushed = self.prefix_cache.set_version(
                        self.weight_version)
                if flushed:
                    telemetry.get_registry().counter(
                        "serving_prefix_flushed_total",
                        "prefix-cache blocks flushed because their KV "
                        "was computed under superseded weights").inc(
                        flushed)
                self._record_gauges()
        flight_record("weight_swap", version=self.weight_version,
                      flushed_blocks=flushed)
        return {"version": self.weight_version,
                "flushed_blocks": flushed}

    # -- multi-tenant adapter plane (serving/tenancy.py) --------------------
    def _init_adapter_arena(self) -> dict:
        """Zero-filled device pages for every LoRA-targetable stacked
        projection in the param tree: projection name → ``{"A":
        (L, P, in, r), "B": (L, P, r, out)}`` float32, P =
        ``max_adapters``. Page 0 stays all-zero forever — the base
        model's delta is exactly 0.0, and ``lora_apply``'s masked
        select keeps id-0 tokens BITWISE base. MoE FFNs carry no dense
        fc_in/gate/up leaves, so expert weights are never paged —
        attention adapters still apply there."""
        from hetu_tpu.serving.tenancy import DEFAULT_TARGETS
        P, r = self.tenancy.max_adapters, self.tenancy.r
        pages: dict = {}
        blocks = self.params.get("blocks", {})
        for group in ("attn", "mlp"):
            sub = blocks.get(group) if isinstance(blocks, dict) else None
            if not isinstance(sub, dict):
                continue
            for name, node in sub.items():
                if name not in DEFAULT_TARGETS \
                        or not isinstance(node, dict):
                    continue
                w = node.get("weight")
                if w is None or getattr(w, "ndim", 0) != 3:
                    continue
                L, d_in, d_out = w.shape
                pages[name] = {
                    "A": jnp.zeros((L, P, d_in, r), jnp.float32),
                    "B": jnp.zeros((L, P, r, d_out), jnp.float32)}
        if not pages:
            raise ValueError(
                "tenancy= on a model with no LoRA-targetable stacked "
                "projections (expected blocks/attn/{q,k,v,out}_proj "
                "and/or dense-FFN leaves in the param tree)")
        return pages

    def _write_adapter_page(self, page: int, spec) -> None:
        """Registry hook: (re)write one arena page. ``spec`` None
        zeroes the page (evict — a later gather of a freed page must
        read exact zeros, not the evicted tenant's weights). Functional
        ``.at[:, page].set`` builds NEW buffers and rebinds the tree —
        an in-flight fused step keeps its own operands; the next
        iteration picks up the rewrite. Shapes never change, so the
        step never retraces."""
        new = {}
        for name, ab in self._lora_pages.items():
            src = spec.weights.get(name) if spec is not None else None
            if src is None:
                new[name] = {"A": ab["A"].at[:, page].set(0.0),
                             "B": ab["B"].at[:, page].set(0.0)}
            else:
                new[name] = {
                    "A": ab["A"].at[:, page].set(
                        jnp.asarray(src["A"], jnp.float32)),
                    "B": ab["B"].at[:, page].set(
                        jnp.asarray(src["B"], jnp.float32))}
        self._lora_pages = new

    def load_adapter(self, tenant: Optional[str], name: str,
                     weights=None, *, path: Optional[str] = None,
                     version: Optional[int] = None,
                     scaling: float = 1.0) -> dict:
        """Register (or hot-swap) a tenant's LoRA adapter and make it
        arena-resident when a page can be had.

        ``weights`` is projection → ``{"A": (L, in, ra), "B":
        (L, ra, out)}`` host arrays (``peft.lora`` order — pass the
        model's ``tenancy.lora_scaling`` as ``scaling`` for merge
        parity); ``path=`` instead loads a
        :func:`~hetu_tpu.serving.tenancy.save_adapter_distributed`
        checkpoint (version/scaling from its manifest unless
        overridden). Replacing a live version is safe under traffic:
        the old version's page drains when its last in-flight request
        releases, its prefix-cache spans flush eagerly, and the new
        version's fresh uid means no stale KV can ever match."""
        if self.tenancy is None:
            raise RuntimeError(
                "load_adapter on an engine without tenancy= — "
                "construct with ServingEngine(..., tenancy=True)")
        if (weights is None) == (path is None):
            raise ValueError("pass exactly one of weights= or path=")
        if path is not None:
            from hetu_tpu.serving.tenancy import load_adapter_distributed
            weights, fver, scaling = load_adapter_distributed(path)
            if version is None:
                version = fver
        unknown = set(weights) - set(self._lora_pages)
        if unknown:
            raise ValueError(
                f"adapter targets projections this model does not "
                f"page: {sorted(unknown)} (arena pages: "
                f"{sorted(self._lora_pages)})")
        for proj, ab in weights.items():
            pg = self._lora_pages[proj]
            L, _, d_in, _ = pg["A"].shape
            d_out = pg["B"].shape[-1]
            a, b = np.asarray(ab["A"]), np.asarray(ab["B"])
            if a.shape[0] != L or a.shape[1] != d_in \
                    or b.shape[-1] != d_out:
                raise ValueError(
                    f"{proj}: adapter pages {a.shape}/{b.shape} do not "
                    f"fit this model's ({L}, {d_in}, ·)/(·, {d_out}) "
                    f"projection")
        registry = self.tenancy.registry
        with self._lock:
            prev_uid = None
            if registry.has(tenant, name):
                prev_uid = registry.get(tenant, name).uid
            spec = registry.register(tenant, name, weights,
                                     version=version, scaling=scaling)
            flushed = 0
            if prev_uid is not None and self.prefix_cache is not None:
                # the replaced version's cached spans are already
                # unmatchable (fresh uid) but still pin blocks —
                # return them to the free list now
                flushed = self.prefix_cache.flush_adapter(prev_uid)
            try:
                registry.ensure_resident(tenant, name)
            except AdapterArenaFull:
                pass    # loads lazily at this adapter's first admission
        if flushed:
            telemetry.get_registry().counter(
                "serving_prefix_flushed_total",
                "prefix-cache blocks flushed because their KV "
                "was computed under superseded weights").inc(flushed)
        return {"tenant": tenant, "name": name,
                "version": spec.version, "uid": spec.uid,
                "page": spec.page, "flushed_blocks": flushed}

    def evict_adapter(self, tenant: Optional[str], name: str) -> dict:
        """Deregister a tenant's adapter: the arena page frees now when
        idle (else when its last in-flight request releases), and its
        prefix-cache spans return their blocks eagerly."""
        if self.tenancy is None:
            raise RuntimeError(
                "evict_adapter on an engine without tenancy=")
        registry = self.tenancy.registry
        with self._lock:
            uid = None
            if registry.has(tenant, name):
                uid = registry.get(tenant, name).uid
            registry.deregister(tenant, name)
            flushed = 0
            if uid is not None and self.prefix_cache is not None:
                flushed = self.prefix_cache.flush_adapter(uid)
        return {"flushed_blocks": flushed}

    def _admission_gate(self, req: Request) -> bool:
        """Scheduler eligibility filter (installed when tenancy is on;
        the scheduler calls it under the engine lock). False DEFERS the
        request without burning its class's deficit credits: tenant
        token-bucket / slot-cap throttles and adapter-arena-full waits
        — so a throttled tenant's backlog never blocks other tenants.
        Also refreshes ``req.kv_adapter`` so the page plan the
        scheduler prices next matches the adapter version that will
        actually serve the request (a hot-swap between submit and
        admission re-tags it here)."""
        sp = req.sampling
        if req.adapter_ref is not None:
            return True      # preempted resume: already pinned + paid
        reason = None if req.id in self._qos_admitted \
            else self.tenancy.qos.check(sp.tenant)
        if reason is not None:
            if req.id not in self._throttle_logged:
                self._throttle_logged.add(req.id)
                telemetry.get_registry().counter(
                    "tenant_throttled_total",
                    "admissions deferred by tenant QoS (token-bucket "
                    "rate or concurrent-slot cap), one per throttle "
                    "episode").inc(tenant=sp.tenant or "base",
                                   reason=reason)
                flight_record("tenant_throttle", req=req.id,
                              tenant=sp.tenant, reason=reason)
            return False
        if sp.adapter is not None:
            registry = self.tenancy.registry
            if registry.has(sp.tenant, sp.adapter):
                if not registry.resident(sp.tenant, sp.adapter) \
                        and not registry.can_load():
                    # every page pinned by in-flight requests: wait
                    # (loud, once per episode) instead of failing
                    if req.id not in self._wait_logged:
                        self._wait_logged.add(req.id)
                        flight_record("adapter_wait", req=req.id,
                                      tenant=sp.tenant,
                                      adapter=sp.adapter)
                    return False
                req.kv_adapter = registry.kv_tag(
                    registry.get(sp.tenant, sp.adapter))
        return True

    def _bind_adapter_locked(self, req: Request, slot: int) -> bool:
        """Pin the request's tenancy state at admission (caller holds
        the lock): acquire an adapter-page ref — held across preemption,
        so a resume is guaranteed the same uid/page — stamp the slot's
        arena page + the request's KV-compat tag, and pay the tenant's
        QoS admit exactly once per request lifetime. False = the
        adapter vanished between submit and admission (deregistered):
        the request fails loudly and its slot/blocks unwind."""
        sp = req.sampling
        reg_ = telemetry.get_registry()
        if sp.adapter is not None and req.adapter_ref is None:
            try:
                spec = self.tenancy.registry.acquire(sp.tenant,
                                                     sp.adapter)
            except (KeyError, AdapterArenaFull) as err:
                # KeyError: deregistered since submit. AdapterArenaFull
                # is defensive — the admission gate defers requests the
                # arena cannot page, so admission never sees it.
                req.status, req.error = "rejected", str(err)
                self.scheduler.release(
                    slot, table=np.asarray(req.admit["table"],
                                           np.int32))
                reg_.counter("serving_requests_total",
                             "serving requests by outcome").inc(
                    outcome="rejected")
                flight_record("serving_reject", req=req.id,
                              trace=req.trace_id, reason=str(err))
                req.done.set()
                return False
            req.adapter_ref = spec
            req.kv_adapter = self.tenancy.registry.kv_tag(spec)
        self._adapter_page[slot] = req.adapter_ref.page \
            if req.adapter_ref is not None else 0
        if req.id not in self._qos_admitted:
            self._qos_admitted.add(req.id)
            self.tenancy.qos.on_admit(sp.tenant)
            reg_.counter("tenant_requests_total",
                         "admitted serving requests per tenant").inc(
                tenant=sp.tenant or "base")
        self._throttle_logged.discard(req.id)
        self._wait_logged.discard(req.id)
        return True

    def _release_tenancy(self, req: Request) -> None:
        """Drop a request's tenancy holds as it leaves the engine
        (finish, eviction out to the fleet, drain): the adapter-page
        ref and the tenant's QoS slot. The slot's arena-page stamp is
        cleared by ``_detach_locked``/``_finish``. Preemption does NOT
        come through here — a preempted request keeps its ref so its
        resume is guaranteed the same adapter uid."""
        if self.tenancy is None:
            return
        if req.adapter_ref is not None:
            self.tenancy.registry.release(req.adapter_ref)
            req.adapter_ref = None
        if req.id in self._qos_admitted:
            self._qos_admitted.discard(req.id)
            self.tenancy.qos.on_finish(req.sampling.tenant)
        self._throttle_logged.discard(req.id)
        self._wait_logged.discard(req.id)

    def step(self) -> bool:
        """One engine iteration; False when there was nothing to do.
        Safe to call while the :meth:`start` loop runs (iterations are
        serialized), though one driver is the intended mode."""
        with self._step_lock:
            return self._step_locked()

    def _admit_locked(self, now: float, reg) -> list[tuple[int, int]]:
        """Admit every admissible queued request (slots + free blocks
        permitting): map its prefix-cache plan into the slot's block
        table and queue its prefill. Returns this iteration's CoW
        (src, dst) block pairs."""
        cows: list[tuple[int, int]] = []
        while True:
            adm = self.scheduler.next_admission()
            if adm is None:
                break
            req, slot = adm
            if self.tenancy is not None \
                    and not self._bind_adapter_locked(req, slot):
                continue
            req.weight_version = self.weight_version
            sp = req.sampling
            self._temp[slot] = sp.temperature
            self._topk[slot] = sp.top_k
            self._topp[slot] = sp.top_p
            # seed the slot's commit-key stream: an explicit
            # SamplingParams.seed replays bit-for-bit against one-shot
            # generate(rng=jax.random.key(seed)); otherwise derive a
            # per-request stream from the engine seed
            k0 = jax.random.key(int(sp.seed)) if sp.seed is not None \
                else jax.random.fold_in(self._key, req.id)
            self._key_state[slot] = np.asarray(jax.random.key_data(k0))
            self._slot_req[slot] = req
            plan = req.admit
            self._bt[slot, :] = 0
            self._bt[slot, :len(plan["table"])] = plan["table"]
            if plan["cow"] is not None:
                cows.append(plan["cow"])
            if plan.get("resume"):
                # a preempted request coming back: its KV re-maps from
                # the host spill arena — no prefill lane, no cp lane
                self._resume_pending.append({"req": req, "slot": slot})
            elif req.cp_lane:
                # beyond one slot's budget: one cp-sharded prefill pass
                # instead of the packed chunk loop
                self._cp_pending.append({"req": req, "slot": slot})
            else:
                self._prefilling.append(
                    {"req": req, "slot": slot,
                     "off": plan["first_uncached"]})
            if self._draftsman is not None:
                # the slot's draft state belongs to its NEW occupant
                # (resumes re-seed with the full history at map-back)
                self._draftsman.reset(slot, req.prompt.tolist())
            self._ctl_dirty = True           # new sampling params + bt
            hit = req.cached_tokens
            if hit:
                reg.counter("serving_prefix_hit_tokens_total",
                            "prompt tokens served from the prefix "
                            "cache (prefill skipped)").inc(hit)
            reg.counter("serving_prefix_miss_tokens_total",
                        "prompt tokens that had to be prefilled").inc(
                len(req.prompt) - hit)
            flight_record("serving_admit", req=req.id,
                          trace=req.trace_id, slot=slot,
                          cached_tokens=hit, cp_lane=req.cp_lane,
                          queued_s=round(now - req.submit_s, 4))
        ev = self.scheduler.evictions_total
        if ev > self._evictions_synced:
            reg.counter("serving_block_evictions_total",
                        "prefix-cache blocks LRU-evicted to refill the "
                        "free list").inc(ev - self._evictions_synced)
            self._evictions_synced = ev
        return cows

    def _step_locked(self) -> bool:
        t0 = time.monotonic()
        reg = telemetry.get_registry()
        C = self.prefill_chunk
        R = self._fin_cap
        K = self.spec_depth
        S = self.pool.slots
        with self._lock:
            cows = self._admit_locked(t0, reg)
            # preemption runs AFTER admission, so it fires only when
            # the deficit-selected head genuinely could not admit —
            # prefix-cache credit and cache eviction (which _page_plan
            # already spends) admit for free before anyone is evicted
            spill_job = self._plan_preemption_locked()
        if spill_job is not None:
            self._exec_spill(spill_job, reg)
        with self._lock:
            if spill_job is not None:
                # second admission pass picks up the freed slot/blocks
                # in THIS iteration (the urgent head does not wait one)
                cows += self._admit_locked(t0, reg)
            # CP-lane prefills run as their own (bucket-audited)
            # executables before the fused step — at most ONE per
            # iteration, device call OUTSIDE the lock. Spill-resumes
            # follow the same discipline (one per iteration, upload
            # outside the lock).
            cp_job = self._prep_cp_prefill_locked()
            resume_job = self._resume_pending.pop(0) \
                if self._resume_pending else None
        did_aux = spill_job is not None
        if resume_job is not None:
            self._exec_resume(resume_job, reg)
            did_aux = True
        if cp_job is not None:
            self._exec_cp_prefill(cp_job, t0, reg)
            did_aux = True
        with self._lock:
            active_prev = np.nonzero(self._active)[0]
            if not self._prefilling and active_prev.size == 0 \
                    and not cows:
                if did_aux:
                    self._record_gauges()
                return did_aux
            # speculative drafts: per-slot depth + tokens are DATA
            # operands rebuilt every iteration. Depth clamps: never
            # beyond the request's remaining token budget - 1 (so
            # commits can't blow past max_tokens or the slot's
            # allocated blocks). Sampled (temperature > 0) slots
            # speculate too — the rejection-sampling verify lane keeps
            # their output distribution exact (``speculative_verify``).
            # The n-gram index is host-only and proposes here; the
            # model draftsman's DEVICE step runs between the lock
            # windows below (submit()/load stay responsive through it —
            # the iteration lock we hold keeps its inputs frozen).
            d_tok = np.zeros((S, K), np.int32)
            d_len = np.zeros(S, np.int32)
            d_q = None
            if K and self._draftsman is not None \
                    and not self._draftsman.host_only:
                # device draftsman: its q rows ride the spec operand —
                # ALWAYS present so the step's pytree signature (and
                # the 1-compile audit) never depends on churn
                d_q = np.zeros((S, K, self.model.cfg.vocab_size),
                               np.float32)
            model_draft_in = None
            if K and active_prev.size:
                budget = np.zeros(S, np.int32)
                for r in active_prev:
                    req = self._slot_req[r]
                    sp = req.sampling
                    budget[r] = max(0, min(
                        K, sp.max_tokens - len(req.tokens) - 1))
                if self._draftsman is not None and budget.any():
                    if self._draftsman.host_only:
                        for r in active_prev:
                            b = int(budget[r])
                            if b <= 0:
                                continue
                            prop = self._draftsman.propose(int(r), b)
                            if prop:
                                n = min(len(prop), b)
                                d_tok[r, :n] = prop[:n]
                                d_len[r] = n
                    else:
                        seqs: list = [None] * S
                        for r in active_prev:
                            req = self._slot_req[r]
                            seqs[r] = req.prompt.tolist() \
                                + list(req.tokens)
                        model_draft_in = (seqs, self._pos.copy(),
                                          self._active.copy(), budget,
                                          self._temp.copy(),
                                          self._topk.copy(),
                                          self._topp.copy(),
                                          self._key_state.copy())
        if model_draft_in is not None:
            d_tok, d_len, dq = self._draftsman.propose_all(
                *model_draft_in[:4], temps=model_draft_in[4],
                topks=model_draft_in[5], topps=model_draft_in[6],
                keys=model_draft_in[7])
            d_tok = np.asarray(d_tok)
            d_len = np.minimum(np.asarray(d_len), model_draft_in[3])
            d_q = np.asarray(dq, np.float32)
            # a zoo draft model may have a larger vocab than the
            # target: clamp (the draftsman already masks its sampling
            # to the target vocab; this guards legacy draft paths)
            v = getattr(self.model.cfg, "vocab_size", None)
            if v:
                np.clip(d_tok, 0, v - 1, out=d_tok)
        with self._lock:
            if self._ctl_dirty:
                self._ctl_dev = {"pos": jnp.asarray(self._pos),
                                 "last_tok": jnp.asarray(self._last_tok),
                                 "active": jnp.asarray(self._active),
                                 "temp": jnp.asarray(self._temp),
                                 "topk": jnp.asarray(self._topk),
                                 "topp": jnp.asarray(self._topp),
                                 "key": jnp.asarray(self._key_state),
                                 "adapter": jnp.asarray(
                                     self._adapter_page)}
                self._bt_dev = jnp.asarray(self._bt)
                self._ctl_dirty = False
            ctl = self._ctl_dev
            # pack the prefill budget FCFS over in-flight prefills: the
            # oldest request fills first (so a lone request's chunk
            # count matches the PR 5 single-admission engine), the rest
            # share what remains — a burst's TTFT now scales with total
            # prompt tokens / C, not with queue depth
            tokens = np.zeros(C, np.int32)
            tpos = np.zeros(C, np.int32)
            tslot = np.zeros(C, np.int32)
            tvalid = np.zeros(C, bool)
            tseg = np.full(C, -1, np.int32)      # -1 isolates pad lanes
            thist = np.zeros(C, np.int32)        # per-token chunk start
            fin_row = np.zeros(R, np.int32)
            fin_slot = np.zeros(R, np.int32)
            fin_valid = np.zeros(R, bool)        # rows really finishing
            fills: list[tuple[dict, int]] = []   # (entry, n) this iter
            fin_ents: list[dict] = []            # completes this iter
            used = 0
            for ent in self._prefilling:         # empty on the common
                if used >= C:                    # decode-only iteration
                    break
                req, off = ent["req"], ent["off"]
                n = int(min(C - used, len(req.prompt) - off))
                tokens[used:used + n] = req.prompt[off:off + n]
                tpos[used:used + n] = np.arange(off, off + n)
                tslot[used:used + n] = ent["slot"]
                tvalid[used:used + n] = True
                # flash-lane operands: segment id = the slot (one
                # contiguous run per request per pack, so index-causal
                # == position-causal within it); hist = the run's
                # start offset — arena rows below it (earlier chunks,
                # prefix-cache hits) belong to the history part
                tseg[used:used + n] = ent["slot"]
                thist[used:used + n] = off
                if off + n >= len(req.prompt):
                    fin_row[len(fin_ents)] = used + n - 1
                    fin_slot[len(fin_ents)] = ent["slot"]
                    fin_valid[len(fin_ents)] = True
                    fin_ents.append(ent)
                fills.append((ent, n))
                used += n
            pf = {"run": np.bool_(used > 0), "tokens": tokens,
                  "pos": tpos, "slot": tslot, "valid": tvalid,
                  "seg": tseg, "hist": thist, "fin_row": fin_row,
                  "fin_slot": fin_slot, "fin_valid": fin_valid}
            # CoW lanes: unused dst = n_blocks scatters out of bounds
            cow_src = np.zeros(S, np.int32)
            cow_dst = np.full(S, self.pool.n_blocks, np.int32)
            for i, (src, dst) in enumerate(cows):
                cow_src[i], cow_dst[i] = src, dst
            cow = {"run": np.bool_(bool(cows)), "src": cow_src,
                   "dst": cow_dst}
            bt = self._bt_dev

        ctx = self._plan.act if self._plan is not None \
            else contextlib.nullcontext()
        spec = {"tok": d_tok, "len": d_len}
        if d_q is not None:
            spec["q"] = d_q
        with ctx:
            (caches, committed, ncommit, first_toks, pos_dev,
             last_dev, key_dev) = self._fn(
                self.params, self.pool.caches, ctl, pf, bt, cow, spec,
                self._w8a8_wq, self._lora_pages)
        self.pool.caches = caches
        em = np.asarray(committed)               # (S, K+1)
        nc = np.asarray(ncommit)                 # (S,)
        ft = np.asarray(first_toks)
        now = time.monotonic()

        with self._lock:
            self._iter += 1
            # the host mirror of the per-slot commit keys always tracks
            # the device: the step advanced them (verify consumption +
            # prefill first-token draws) for exactly the slots that
            # sampled this iteration
            self._key_state[:] = np.asarray(key_dev)
            if active_prev.size:
                reg.counter(
                    "serving_decode_slot_steps_total",
                    "slot×iteration decode opportunities (each active "
                    "slot in each fused step counts once); 1 + "
                    "accepted/this is the mean tokens committed per "
                    "slot-step — the speculation win, 1.0 without "
                    "drafts").inc(int(active_prev.size))
                reg.counter(
                    "serving_attn_kernel_total",
                    "fused decode/verify steps by attention path "
                    "(paged = Pallas block-table kernel, reference = "
                    "XLA gather)").inc(path=self.attn_kernel)
            if used:
                reg.counter(
                    "prefill_attn_kernel_total",
                    "prefill-lane executions by attention path (flash "
                    "= packed/CP flash lane, reference = per-token "
                    "gather math)").inc(
                    path="flash" if self.prefill_attn != "reference"
                    else "reference")
            # decode results for the slots that were active going in:
            # each commits ncommit tokens (accepted drafts + bonus) —
            # EOS or budget can finish the request mid-commit, in which
            # case the remaining committed tokens are discarded (the
            # _finish path marks control state dirty, so the device's
            # advanced pos is rebuilt from the host mirrors)
            for r in active_prev:
                req = self._slot_req[int(r)]
                n = int(nc[r])
                if req is None or n == 0:
                    continue
                taken = 0
                for j in range(n):
                    self._on_token(int(r), int(em[r, j]), now, reg)
                    taken += 1
                    if self._slot_req[int(r)] is not req:
                        break                    # finished mid-commit
                dr = int(d_len[r])
                if dr:
                    # count only what the request KEPT: of the `taken`
                    # committed tokens, all but the bonus (column
                    # n-1, landed only when taken == n) were accepted
                    # drafts — an EOS mid-commit discards the tail,
                    # and the acceptance ledgers must not claim it
                    kept = min(taken, n - 1)
                    sampled = float(self._temp[r]) > 0.0
                    req.drafted += dr
                    req.accepted += kept
                    reg.counter(
                        "serving_draft_tokens_total",
                        "draft tokens proposed to the verify "
                        "lane").inc(dr)
                    if kept:
                        reg.counter(
                            "serving_accepted_tokens_total",
                            "draft tokens the verify lane accepted "
                            "(committed without their own decode "
                            "iteration)").inc(kept)
                        if sampled:
                            reg.counter(
                                "serving_sampled_accepted_tokens_total",
                                "draft tokens accepted by the "
                                "rejection-sampling verify lane "
                                "(temperature > 0 slots)").inc(kept)
                    if sampled and n - 1 < dr:
                        # the device rejected draft column n-1 and
                        # drew the commit token from the normalized
                        # residual max(0, p - q)
                        reg.counter(
                            "serving_resample_tokens_total",
                            "tokens drawn from the rejection-"
                            "sampling residual after a draft was "
                            "rejected (sampled speculation)").inc(1)
            # prefill progress for every request that got pack tokens
            for ent, n in fills:
                ent["off"] += n
                ent["req"].mark("prefill_chunk", dur_s=now - t0,
                                ts_s=t0)
            if used:
                reg.counter("serving_tokens_total",
                            "serving tokens by kind").inc(
                    used, kind="prompt")
            for i, ent in enumerate(fin_ents):
                req, slot = ent["req"], ent["slot"]
                self._pos[slot] = len(req.prompt)
                self._active[slot] = True
                self._ctl_dirty = True       # slot turned on mid-flight
                req.status = "decode"
                req.first_token_s = now
                req.mark("first_token", ts_s=now)
                ttft = now - req.submit_s
                reg.histogram(
                    "serving_ttft_seconds",
                    "time submit -> first token").observe(ttft)
                if self.slo is not None:
                    self.slo.observe("serving_ttft_seconds", ttft)
                # the finished prompt's whole blocks enter the radix
                # cache (the trie takes refs, so they outlive the slot)
                if self.prefix_cache is not None:
                    self.prefix_cache.insert(req.prompt.tolist(),
                                             self._bt[slot],
                                             adapter=req.kv_adapter)
                self._on_token(slot, int(ft[i]), now, reg)
                self._prefilling.remove(ent)
            # steady decode: adopt the step's own control advance (no
            # host→device upload next iteration). Any event above set
            # _ctl_dirty, which forces a rebuild from the np mirrors.
            if not self._ctl_dirty:
                self._ctl_dev = dict(self._ctl_dev, pos=pos_dev,
                                     last_tok=last_dev, key=key_dev)
            self._record_gauges()
        self._pump_stream_subs()
        step_s = time.monotonic() - t0
        reg.histogram("serving_step_seconds",
                      "one fused engine iteration").observe(step_s)
        if self.slo is not None:
            self.slo.observe("serving_step_seconds", step_s)
        if self._counter_sample_every and \
                self._iter % self._counter_sample_every == 0:
            telemetry.get_tracer().record_counters(reg.snapshot())
        return True

    def _on_token(self, slot: int, tok: int, now: float, reg) -> None:
        """Record one sampled token for ``slot`` (caller holds lock):
        append, advance the slot cursor, finish on EOS / budget."""
        req = self._slot_req[slot]
        req.tokens.append(tok)
        self._last_tok[slot] = tok
        # the cursor only advances once the token is FED (next decode
        # writes its KV at the current pos) — pos was set by prefill
        if req.status == "decode" and len(req.tokens) > 1:
            self._pos[slot] += 1
        if self._draftsman is not None and self._draftsman.host_only:
            self._draftsman.extend(slot, (tok,))
        reg.counter("serving_tokens_total",
                    "serving tokens by kind").inc(kind="generated")
        sp = req.sampling
        hit_eos = sp.eos_id is not None and tok == sp.eos_id
        if hit_eos or len(req.tokens) >= sp.max_tokens:
            self._finish(slot, now, reg)
        elif req.handoff and req.status == "decode":
            # prefill-tier park (P/D disaggregation): the first token
            # landed, so prefill is DONE — stop decoding here. The slot
            # goes inactive but keeps its request and KV blocks; the
            # fleet layer evicts the spill and streams it to a
            # decode-tier replica, which resumes token-for-token.
            self._active[slot] = False
            self._ctl_dirty = True
            req.status = "prefilled"
            req.mark("prefilled", ts_s=now)
            flight_record("serving_prefill_handoff", req=req.id,
                          trace=req.trace_id, slot=slot,
                          prompt_len=len(req.prompt))

    def _finish(self, slot: int, now: float, reg) -> None:
        req = self._slot_req[slot]
        req.status = "done"
        req.finish_s = now
        req.mark("finish", ts_s=now)
        self._active[slot] = False
        self._ctl_dirty = True               # slot turned off
        self._slot_req[slot] = None
        self._adapter_page[slot] = 0
        self._release_tenancy(req)
        # drop this slot's hold on every block it mapped; blocks the
        # prefix cache adopted stay resident (trie refs), the rest free
        self.scheduler.release(slot, table=self._bt[slot])
        self._bt[slot, :] = 0
        reg.counter("serving_requests_total",
                    "serving requests by outcome").inc(
            outcome="completed")
        n = len(req.tokens)
        if n > 1 and req.first_token_s is not None:
            tpot = (now - req.first_token_s) / (n - 1)
            reg.histogram("serving_tpot_seconds",
                          "per-output-token time after the first").observe(
                tpot)
            if self.slo is not None:
                self.slo.observe("serving_tpot_seconds", tpot)
        if req.drafted:
            reg.histogram(
                "serving_draft_acceptance_ratio",
                "per-request accepted/drafted ratio at finish (the "
                "speculation win tracks this), split by verify path "
                "(greedy match vs rejection sampling)").observe(
                req.accepted / req.drafted,
                path="sampled" if req.sampling.temperature > 0
                else "greedy")
        # a finished request can still own a spill entry (preempted,
        # resumed elsewhere... or cancelled paths) — never leak it
        if req.spill is not None \
                and self.spill_arena.get(req.id) is req.spill:
            self.spill_arena.pop(req.id, resumed=False)
            req.spill = None
        flight_record("serving_finish", req=req.id, trace=req.trace_id,
                      slot=slot, tokens=n)
        self._emit_request_trace(req)
        req.done.set()

    def _emit_request_trace(self, req: Request) -> None:
        """Render the request's lifecycle as its own Perfetto track:
        one span per phase (queued / prefill chunks / decode), on a
        synthetic tid named after the ``trace_id``. Host-side, only
        when the tracer is on — the fused step never sees any of it."""
        tracer = telemetry.get_tracer()
        if not tracer.enabled:
            return
        # request events use time.monotonic; the tracer epoch is
        # perf_counter-based — bridge via the current offset (both are
        # monotonic clocks, so the offset is constant)
        off = (time.perf_counter() - tracer.epoch) - time.monotonic()
        tid = REQ_TRACK_BASE + req.id
        tracer.name_track(tid, f"req {req.trace_id}")

        def span(name, start, dur, **attrs):
            tracer.complete(name, max(dur, 0.0), cat="request",
                            ts_s=max(start + off, 0.0), tid=tid,
                            trace_id=req.trace_id, req=req.id, **attrs)

        admit = next((t for p, t, _ in req.events if p == "admit"), None)
        if admit is not None:
            span("queued", req.submit_s, admit - req.submit_s)
        for phase, ts, dur in req.events:
            if phase == "prefill_chunk":
                span("prefill_chunk", ts, dur)
        if req.first_token_s is not None and req.finish_s is not None:
            span("decode", req.first_token_s,
                 req.finish_s - req.first_token_s,
                 tokens=len(req.tokens))

    def _record_gauges(self) -> None:
        reg = telemetry.get_registry()
        reg.gauge("serving_queue_depth",
                  "requests waiting for a slot").set(self.scheduler.depth)
        reg.gauge("serving_slot_occupancy",
                  "fraction of KV-pool slots in use").set(
            self.scheduler.occupancy)
        reg.gauge("serving_kv_blocks_in_use",
                  "live KV blocks (slot tables + prefix cache)").set(
            self.blocks.blocks_in_use)
        reg.gauge("serving_kv_spill_arena_blocks",
                  "KV blocks parked in the host spill arena "
                  "(preempted requests awaiting resume)").set(
            self.spill_arena.blocks_held)
        tiers = dict(self.spill_arena.tier_counts())
        tiers["replica"] = self.kv_replica_store.blocks_held
        g = reg.gauge(
            "spill_tier_blocks",
            "KV blocks parked per spill tier (host arena, peer tier, "
            "buddy replica store) — the tier chain of ISSUE 18")
        for tier, n in tiers.items():
            g.set(n, tier=tier)
        if self.tenancy is not None:
            reg.gauge(
                "adapter_pages_in_use",
                "adapter arena pages holding a resident adapter").set(
                self.tenancy.registry.pages_in_use)

    def run_until_drained(self, max_steps: int = 1_000_000) -> int:
        """Drive :meth:`step` until queue + slots are empty; returns the
        number of iterations run."""
        n = 0
        while self.has_work():
            if n >= max_steps:
                raise RuntimeError(
                    f"serving engine not drained after {max_steps} "
                    f"iterations")
            self.step()
            n += 1
        return n

    # -- offline API --------------------------------------------------------
    def generate_many(
            self, prompts: Sequence[Sequence[int]],
            sampling: Union[SamplingParams, Sequence[SamplingParams],
                            None] = None) -> list[list[int]]:
        """Submit every prompt, run to drain, return per-request tokens
        **in submission order** — requests routinely FINISH out of order
        (short decodes overtake long ones across slot recycling), so
        results are keyed by the submitted Request, never by completion
        order. Continuous batching under the hood: arrival order and
        slot assignment do not change any request's tokens. When the
        :meth:`start` background loop is running, this waits on each
        request instead of stepping the engine from a second thread."""
        if sampling is None or isinstance(sampling, SamplingParams):
            sampling = [sampling or SamplingParams()] * len(prompts)
        reqs = [self.submit(p, sp) for p, sp in zip(prompts, sampling)]
        bad = [r for r in reqs if r.status == "rejected"]
        if bad:
            # fail FAST and loud (a silent [] is indistinguishable from
            # a legitimate empty generation); un-queue the siblings so
            # the engine is left clean
            with self._lock:
                for r in reqs:
                    if r.status == "queued":
                        try:
                            self.scheduler.queue.remove(r)
                        except ValueError:
                            pass
                        r.status = "cancelled"
                        r.error = "batch aborted: sibling rejected"
                        r.done.set()
            raise ValueError(
                f"{len(bad)} request(s) rejected at admission: "
                + "; ".join(f"#{r.id}: {r.error}" for r in bad[:3]))
        if self._thread is not None:
            for r in reqs:          # loop thread owns the iterations
                r.done.wait()
        else:
            self.run_until_drained()
        return [list(r.tokens) for r in reqs]

    # -- background loop (online front ends) --------------------------------
    def start(self, idle_sleep_s: float = 0.002) -> None:
        if self._thread is not None:
            return
        self._stop = threading.Event()
        if self.watchdog is not None:
            self.watchdog.start()

        def loop():
            while not self._stop.is_set():
                busy = self.step()
                # a beat per loop turn (idle included): the watchdog
                # watches for a WEDGED iteration, not an empty queue
                if self.watchdog is not None:
                    self.watchdog.beat()
                if self.slo is not None:
                    now = time.monotonic()
                    if now - self._slo_last_eval >= self._slo_every_s:
                        self._slo_last_eval = now
                        for a in self.slo.evaluate():
                            from hetu_tpu.utils.logging import get_logger
                            get_logger().warning(
                                f"SLO alert: {a.message}")
                if not busy:
                    self._stop.wait(idle_sleep_s)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._repl_stop is not None:   # decode-KV replication stream
            self._repl_stop.set()
            if self._repl_thread is not None:
                self._repl_thread.join(timeout=5.0)
            self._repl_thread = None
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=10.0)
        self._thread = None
        if self.watchdog is not None:
            self.watchdog.stop()
