"""Datasets.

Parity target: ``python/hetu/data`` ``JsonDataset`` + tokenizer hooks
(GPT2 BPE / HF / sentencepiece — here any callable ``str -> list[int]``,
e.g. a ``transformers`` tokenizer's ``encode``).
"""

from __future__ import annotations

import json
from typing import Callable, Iterator, Optional

import numpy as np


class JsonDataset:
    """JSONL file of ``{"text": ...}`` (or pre-tokenized
    ``{"tokens": [...]}``) records."""

    def __init__(self, path: str, *, field: str = "text",
                 tokenizer: Optional[Callable] = None,
                 max_items: Optional[int] = None):
        self.records: list[np.ndarray] = []
        with open(path) as f:
            for i, line in enumerate(f):
                if max_items is not None and i >= max_items:
                    break
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                if "tokens" in rec:
                    toks = rec["tokens"]
                elif tokenizer is not None:
                    toks = tokenizer(rec[field])
                else:
                    raise ValueError(
                        "text records need a tokenizer callable")
                self.records.append(np.asarray(toks, np.int32))

    def __len__(self):
        return len(self.records)

    def __getitem__(self, i) -> np.ndarray:
        return self.records[i]

    def __iter__(self) -> Iterator[np.ndarray]:
        return iter(self.records)


class SyntheticLMDataset:
    """Random-token corpus with a length distribution — for tests and
    benchmarks (stands in for the reference's ci_test fixture data)."""

    def __init__(self, vocab_size: int, num_docs: int = 256, *,
                 min_len: int = 8, max_len: int = 64, seed: int = 0):
        rng = np.random.default_rng(seed)
        self.records = [
            rng.integers(0, vocab_size,
                         size=rng.integers(min_len, max_len + 1),
                         dtype=np.int32)
            for _ in range(num_docs)
        ]

    def __len__(self):
        return len(self.records)

    def __getitem__(self, i):
        return self.records[i]

    def __iter__(self):
        return iter(self.records)
