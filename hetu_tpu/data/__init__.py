"""Data pipeline: datasets, packing, buckets, loaders.

Parity target: ``python/hetu/data`` — ``JsonDataset``, packing buckets
(``bucket.py:8,86,193``), sample- and token-level batch samplers
(``dataloader.py:46,162,244``).
"""

from hetu_tpu.data.packing import PackedBatch, pack_sequences
from hetu_tpu.data.bucket import (
    BucketStats, SeqLenBuckets, ShapeBucketer,
)
from hetu_tpu.data.dataset import JsonDataset, SyntheticLMDataset
from hetu_tpu.data.loader import (
    build_data_loader, sample_batches, token_batches,
)
from hetu_tpu.data.tokenizers import (
    ByteLevelBPETokenizer, HFTokenizer, SentencePieceTokenizer,
    TiktokenTokenizer, train_bpe,
)
from hetu_tpu.data.hydraulis import (
    BucketPlan, DynamicDispatcher, plan_buckets,
)

__all__ = [
    "PackedBatch", "pack_sequences", "SeqLenBuckets",
    "JsonDataset", "SyntheticLMDataset",
    "build_data_loader", "sample_batches", "token_batches",
    "ByteLevelBPETokenizer", "HFTokenizer", "SentencePieceTokenizer",
    "TiktokenTokenizer", "train_bpe",
    "BucketPlan", "DynamicDispatcher", "plan_buckets",
    "ShapeBucketer", "BucketStats",
]
