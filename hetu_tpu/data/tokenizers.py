"""In-tree tokenizers: byte-level BPE (GPT-2 scheme), trainer, HF wrapper.

Parity target: the reference vendors tokenizer wrappers in
``python/hetu/data`` (GPT2 BPE, HuggingFace, sentencepiece, tiktoken).
Here the byte-level BPE encoder/decoder and a small corpus trainer are
implemented natively (no network, no vendored vocab needed); pretrained
vocabularies load from the standard ``vocab.json``/``merges.txt`` files,
and any installed HuggingFace tokenizer can be wrapped.
"""

from __future__ import annotations

import ctypes
import json
import os
import re
from collections import Counter
from typing import Iterable, Optional, Sequence

# ---- native BPE merge core (csrc/bpe.cpp, ctypes) -----------------------
# The merge loop is the encode hot path; like the reference we keep the
# data-plane hot loop native (C++ dataloader / vendored fast tokenizers),
# with the pure-Python implementation as the always-available fallback.
_BPE_CSRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "csrc", "bpe.cpp")
_BPE_LIB: Optional[ctypes.CDLL] = None
_BPE_LIB_FAILED = False


def _bpe_lib() -> Optional[ctypes.CDLL]:
    global _BPE_LIB, _BPE_LIB_FAILED
    if _BPE_LIB is not None or _BPE_LIB_FAILED:
        return _BPE_LIB
    try:
        from hetu_tpu.utils.native import build_native
        so = build_native(_BPE_CSRC, "libbpe.so")
        if so is None:
            raise RuntimeError("native build unavailable")
        lib = ctypes.CDLL(so)
        i32p = ctypes.POINTER(ctypes.c_int32)
        lib.bpe_create.restype = ctypes.c_void_p
        lib.bpe_create.argtypes = [ctypes.c_int64, i32p, i32p, i32p, i32p]
        lib.bpe_free.argtypes = [ctypes.c_void_p]
        lib.bpe_encode.restype = ctypes.c_int32
        lib.bpe_encode.argtypes = [ctypes.c_void_p, i32p, ctypes.c_int32,
                                   i32p]
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.bpe_encode_batch.restype = ctypes.c_int64
        lib.bpe_encode_batch.argtypes = [ctypes.c_void_p, i32p, i64p,
                                         ctypes.c_int32, i32p, i64p]
        _BPE_LIB = lib
    except Exception:
        _BPE_LIB_FAILED = True
    return _BPE_LIB

# GPT-2's pre-tokenization regex (contractions, letter runs, digit runs,
# punctuation runs, whitespace handling) — the published pattern.
_PRETOKEN_RE = re.compile(
    r"'s|'t|'re|'ve|'m|'ll|'d| ?[^\W\d_]+| ?\d+| ?[^\s\w]+|\s+(?!\S)|\s+",
    re.UNICODE)


def bytes_to_unicode() -> dict[int, str]:
    """GPT-2's reversible byte→printable-unicode map: printable ASCII and
    latin-1 glyphs map to themselves, the rest shift to 256+."""
    bs = list(range(ord("!"), ord("~") + 1)) + \
        list(range(ord("¡"), ord("¬") + 1)) + \
        list(range(ord("®"), ord("ÿ") + 1))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, map(chr, cs)))


_B2U = bytes_to_unicode()
_U2B = {v: k for k, v in _B2U.items()}


def _word_to_symbols(word: str) -> tuple[str, ...]:
    return tuple(_B2U[b] for b in word.encode("utf-8"))


class ByteLevelBPETokenizer:
    """GPT-2-style byte-level BPE: lossless on arbitrary text.

    ``vocab``: token string → id. ``merges``: ordered list of symbol
    pairs. Load pretrained files with :meth:`from_files` or build one
    with :func:`train_bpe`.
    """

    def __init__(self, vocab: dict[str, int],
                 merges: Sequence[tuple[str, str]], *,
                 special_tokens: Optional[dict[str, int]] = None):
        self.vocab = dict(vocab)
        self.merge_ranks = {tuple(m): i for i, m in enumerate(merges)}
        self.special = dict(special_tokens or {})
        self.id_to_token = {v: k for k, v in self.vocab.items()}
        self.id_to_token.update({v: k for k, v in self.special.items()})
        self._cache: dict[str, tuple[str, ...]] = {}
        self._id_cache: dict[str, list[int]] = {}
        # bound the per-word caches: high-cardinality text (numbers,
        # URLs, hashes) would otherwise grow them without limit in a
        # long-running dataloader
        self._cache_limit = 1 << 18
        self._native = None
        self._init_native(merges)

    def _init_native(self, merges) -> None:
        """Build the id-level merge table for the C++ encode core.

        Degrades silently to the Python merge loop when the toolchain is
        missing or any merge side falls outside the vocab."""
        lib = _bpe_lib()
        if lib is None:
            return
        try:
            left = [self.vocab[a] for a, b in merges]
            right = [self.vocab[b] for a, b in merges]
            merged = [self.vocab[a + b] for a, b in merges]
        except KeyError:
            return
        n = len(merges)
        arr = lambda xs: (ctypes.c_int32 * len(xs))(*xs)
        rank = list(range(n))
        handle = lib.bpe_create(n, arr(left), arr(right), arr(merged),
                                arr(rank))
        if handle:
            self._native = (lib, handle)

    def __del__(self):
        native = getattr(self, "_native", None)
        if native:
            lib, handle = native
            try:
                lib.bpe_free(handle)
            except Exception:
                pass

    # -- construction --------------------------------------------------------
    @classmethod
    def from_files(cls, vocab_json: str, merges_txt: str, **kw):
        with open(vocab_json) as f:
            vocab = json.load(f)
        merges = []
        with open(merges_txt) as f:
            for line in f:
                line = line.rstrip("\n")
                if not line or line.startswith("#version"):
                    continue
                a, b = line.split(" ")
                merges.append((a, b))
        # specials saved alongside (save() writes them); an explicit
        # special_tokens kwarg wins
        sp_file = os.path.join(os.path.dirname(vocab_json),
                               "special_tokens.json")
        if "special_tokens" not in kw and os.path.exists(sp_file):
            with open(sp_file) as f:
                kw["special_tokens"] = json.load(f)
        return cls(vocab, merges, **kw)

    def save(self, directory: str):
        os.makedirs(directory, exist_ok=True)
        with open(os.path.join(directory, "vocab.json"), "w") as f:
            json.dump(self.vocab, f)
        merges = sorted(self.merge_ranks, key=self.merge_ranks.get)
        with open(os.path.join(directory, "merges.txt"), "w") as f:
            f.write("#version: 0.2\n")
            for a, b in merges:
                f.write(f"{a} {b}\n")
        if self.special:
            with open(os.path.join(directory, "special_tokens.json"),
                      "w") as f:
                json.dump(self.special, f)

    # -- BPE core ------------------------------------------------------------
    def _bpe(self, word: str) -> tuple[str, ...]:
        if word in self._cache:
            return self._cache[word]
        symbols = list(_word_to_symbols(word))
        while len(symbols) > 1:
            pairs = [(symbols[i], symbols[i + 1])
                     for i in range(len(symbols) - 1)]
            ranked = [(self.merge_ranks[p], i) for i, p in enumerate(pairs)
                      if p in self.merge_ranks]
            if not ranked:
                break
            best_rank = min(r for r, _ in ranked)
            pair = None
            merged = []
            i = 0
            while i < len(symbols):
                if i < len(symbols) - 1 and \
                        self.merge_ranks.get(
                            (symbols[i], symbols[i + 1])) == best_rank:
                    merged.append(symbols[i] + symbols[i + 1])
                    i += 2
                else:
                    merged.append(symbols[i])
                    i += 1
            symbols = merged
        out = tuple(symbols)
        self._cache[word] = out
        return out

    # -- public API ----------------------------------------------------------
    @property
    def vocab_size(self) -> int:
        return len(self.vocab) + len(self.special)

    def encode(self, text: str) -> list[int]:
        # special tokens split first so their literal text maps to the
        # reserved ids (matching decode's treatment)
        segments = [text]
        for sp in sorted(self.special, key=len, reverse=True):
            segments = [piece
                        for seg in segments
                        for piece in self._split_keep(seg, sp)]
        # evict BEFORE scanning cache membership — clearing inside
        # _encode_words would invalidate placeholder words this call
        # already saw in the cache and left out of `pending`
        if len(self._id_cache) > self._cache_limit:
            self._id_cache.clear()
        if len(self._cache) > self._cache_limit:
            self._cache.clear()
        ids = []
        pending: list[str] = []     # uncached words, encode-order
        for seg in segments:
            if seg in self.special:
                ids.append(self.special[seg])
                continue
            for word in _PRETOKEN_RE.findall(seg):
                if word not in self._id_cache:
                    pending.append(word)
                ids.append(word)    # placeholder, resolved below
        if pending:
            self._encode_words(pending)
        out: list[int] = []
        for item in ids:
            if isinstance(item, int):
                out.append(item)
            else:
                out.extend(self._id_cache[item])
        return out

    def _encode_words(self, words: list[str]) -> None:
        """Fill ``_id_cache`` for ``words`` — one batched native call
        (csrc/bpe.cpp) so ctypes overhead amortizes over the whole text;
        pure-Python merge loop as the fallback. Eviction happens in
        encode() (before membership scans), never here."""
        uniq = list(dict.fromkeys(words))
        if self._native is None:
            for w in uniq:
                self._id_cache[w] = [self.vocab[t] for t in self._bpe(w)]
            return
        lib, handle = self._native
        syms: list[int] = []
        offsets = [0]
        for w in uniq:
            syms.extend(self.vocab[c] for c in _word_to_symbols(w))
            offsets.append(len(syms))
        n = len(syms)
        buf_in = (ctypes.c_int32 * max(n, 1))(*syms)
        buf_off = (ctypes.c_int64 * len(offsets))(*offsets)
        buf_out = (ctypes.c_int32 * max(n, 1))()
        buf_out_off = (ctypes.c_int64 * len(offsets))()
        lib.bpe_encode_batch(handle, buf_in, buf_off, len(uniq),
                             buf_out, buf_out_off)
        for i, w in enumerate(uniq):
            self._id_cache[w] = buf_out[buf_out_off[i]:buf_out_off[i + 1]]

    @staticmethod
    def _split_keep(seg: str, sp: str) -> list[str]:
        if seg == sp:
            return [seg]
        out = []
        parts = seg.split(sp)
        for i, part in enumerate(parts):
            if part:
                out.append(part)
            if i < len(parts) - 1:
                out.append(sp)
        return out

    def decode(self, ids: Iterable[int]) -> str:
        # bytes accumulate across tokens before utf-8 decoding — BPE merges
        # may split a multi-byte character between tokens
        parts: list[str] = []
        buf = bytearray()
        special_ids = set(self.special.values())
        for i in ids:
            i = int(i)
            if i in special_ids:
                if buf:
                    parts.append(buf.decode("utf-8", errors="replace"))
                    buf = bytearray()
                parts.append(self.id_to_token[i])
            else:
                buf.extend(_U2B[c] for c in self.id_to_token[i])
        if buf:
            parts.append(buf.decode("utf-8", errors="replace"))
        return "".join(parts)

    def __call__(self, text: str) -> list[int]:
        return self.encode(text)


def train_bpe(corpus: Iterable[str], vocab_size: int, *,
              special_tokens: Sequence[str] = ("<|endoftext|>",)
              ) -> ByteLevelBPETokenizer:
    """Train byte-level BPE merges on a corpus (standard greedy BPE:
    repeatedly merge the most frequent adjacent pair).

    Byte alphabet (256) is the base vocabulary; merges are added until
    ``vocab_size`` (minus specials) is reached or no pair repeats.
    """
    n_merges = vocab_size - 256 - len(special_tokens)
    if n_merges < 0:
        raise ValueError("vocab_size must be >= 256 + #special_tokens")
    words = Counter()
    for text in corpus:
        for w in _PRETOKEN_RE.findall(text):
            words[w] += 1
    seqs = {w: list(_word_to_symbols(w)) for w in words}

    merges: list[tuple[str, str]] = []
    for _ in range(n_merges):
        pair_counts: Counter = Counter()
        for w, syms in seqs.items():
            c = words[w]
            for i in range(len(syms) - 1):
                pair_counts[(syms[i], syms[i + 1])] += c
        if not pair_counts:
            break
        pair, cnt = pair_counts.most_common(1)[0]
        if cnt < 2:
            break
        merges.append(pair)
        new_sym = pair[0] + pair[1]
        for w, syms in seqs.items():
            i, out = 0, []
            while i < len(syms):
                if i < len(syms) - 1 and (syms[i], syms[i + 1]) == pair:
                    out.append(new_sym)
                    i += 2
                else:
                    out.append(syms[i])
                    i += 1
            seqs[w] = out

    vocab = {c: i for i, c in enumerate(_B2U.values())}
    for a, b in merges:
        vocab[a + b] = len(vocab)
    special = {t: len(vocab) + i for i, t in enumerate(special_tokens)}
    return ByteLevelBPETokenizer(vocab, merges, special_tokens=special)


class HFTokenizer:
    """Wrapper for an installed HuggingFace tokenizer (reference:
    ``python/hetu/data`` HF wrapper). Local files only — no downloads."""

    def __init__(self, name_or_path: str, **kw):
        from transformers import AutoTokenizer
        self.tk = AutoTokenizer.from_pretrained(
            name_or_path, local_files_only=True, **kw)

    @property
    def vocab_size(self) -> int:
        return len(self.tk)

    def encode(self, text: str) -> list[int]:
        return self.tk.encode(text)

    def decode(self, ids) -> str:
        return self.tk.decode(ids)

    def __call__(self, text: str) -> list[int]:
        return self.encode(text)


class TiktokenTokenizer:
    """Wrapper for an OpenAI tiktoken encoding (reference:
    ``python/hetu/data`` tiktoken wrapper). ``allowed_special`` follows
    tiktoken semantics; defaults to allowing every registered special
    token (the pretraining-corpus case)."""

    def __init__(self, encoding: str = "gpt2", *,
                 allowed_special="all"):
        try:
            import tiktoken
        except ImportError as e:
            raise ImportError(
                "TiktokenTokenizer needs the optional `tiktoken` "
                "package") from e
        self.tk = tiktoken.get_encoding(encoding)
        self._allowed = allowed_special

    @property
    def vocab_size(self) -> int:
        return self.tk.n_vocab

    def encode(self, text: str) -> list[int]:
        # tiktoken natively understands the literal "all"
        return self.tk.encode(text,
                              allowed_special=self._allowed or set())

    def decode(self, ids) -> str:
        return self.tk.decode(list(int(i) for i in ids))

    def __call__(self, text: str) -> list[int]:
        return self.encode(text)


class SentencePieceTokenizer:
    """Wrapper for a sentencepiece model file (reference:
    ``python/hetu/data`` sentencepiece wrapper). Import-gated: raises a
    clear error when the optional dependency is absent."""

    def __init__(self, model_path: str):
        try:
            import sentencepiece as spm
        except ImportError as e:
            raise ImportError(
                "SentencePieceTokenizer needs the optional `sentencepiece`"
                " package") from e
        self.tk = spm.SentencePieceProcessor(model_file=model_path)

    @property
    def vocab_size(self) -> int:
        return self.tk.vocab_size()

    def encode(self, text: str) -> list[int]:
        return self.tk.encode(text)

    def decode(self, ids) -> str:
        return self.tk.decode(list(int(i) for i in ids))

    def __call__(self, text: str) -> list[int]:
        return self.encode(text)
