"""Sequence-length buckets — the static-shape answer to dynamic seq lens.

The reference handles varying sequence lengths with symbolic shapes
(``hetu/core/symbol.h:19,95,160``) propagated through shape plans
(``DeduceShapePlan``, ``define_and_run_graph.cc:303``). Under XLA every
shape is a compilation, so the TPU-native equivalent is a small set of
bucket lengths: each batch is padded/packed to its bucket and jit caches
one executable per bucket (SURVEY §7.3 item 4).
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Sequence

import numpy as np


class SeqLenBuckets:
    """Map raw lengths to a fixed set of bucket lengths."""

    def __init__(self, sizes: Sequence[int] | None = None, *,
                 min_len: int = 128, max_len: int = 8192,
                 multiple_of: int = 1):
        if sizes is None:
            sizes, s = [], min_len
            while s <= max_len:
                sizes.append(s)
                s *= 2
        sizes = sorted(set(int(s) for s in sizes))
        for s in sizes:
            if s % multiple_of != 0:
                raise ValueError(
                    f"bucket size {s} not a multiple of {multiple_of} "
                    f"(cp/block alignment)")
        self.sizes = sizes

    def bucket_for(self, length: int) -> int:
        for s in self.sizes:
            if length <= s:
                return s
        return self.sizes[-1]

    def group(self, lengths: Iterable[int]) -> dict[int, list[int]]:
        """indices grouped by bucket size."""
        out: dict[int, list[int]] = {}
        for i, L in enumerate(lengths):
            out.setdefault(self.bucket_for(L), []).append(i)
        return out


# -- the shape plane's batch-side half ---------------------------------------

#: segment id for pad tokens a ShapeBucketer appends. Any value works
#: (pad sits AFTER every real token in a row, so causal masking already
#: keeps it out of real outputs); a huge constant makes the intent
#: unmistakable in dumps and can never collide with a real segment.
PAD_SEGMENT = 2 ** 30 - 1


@dataclasses.dataclass
class BucketStats:
    """Token accounting across every batch a ShapeBucketer fitted."""

    batches: int = 0
    real_tokens: int = 0     # supervised/real tokens dispatched
    raw_tokens: int = 0      # rows x raw width (what pad-to-max feeds)
    bucket_tokens: int = 0   # rows x bucket width (what we actually feed)
    truncated_tokens: int = 0  # real tokens CUT because a row exceeded
    #                            the largest ladder bucket (warned once)

    @property
    def pad_fraction_before(self) -> float:
        """Pad waste of the batches AS GIVEN (the pad-to-max baseline)."""
        return 1.0 - self.real_tokens / self.raw_tokens \
            if self.raw_tokens else 0.0

    @property
    def pad_fraction_after(self) -> float:
        """Pad waste after snapping to the bucket ladder."""
        return 1.0 - self.real_tokens / self.bucket_tokens \
            if self.bucket_tokens else 0.0

    def to_record(self) -> dict:
        return {"kind": "shape_plane", "batches": self.batches,
                "real_tokens": self.real_tokens,
                "raw_tokens": self.raw_tokens,
                "bucket_tokens": self.bucket_tokens,
                "truncated_tokens": self.truncated_tokens,
                "pad_fraction_before": round(self.pad_fraction_before, 4),
                "pad_fraction_after": round(self.pad_fraction_after, 4)}


class ShapeBucketer:
    """Snap ragged host batches onto the bucket ladder.

    The trainer-side half of the shape plane (docs/PERFORMANCE.md "Shape
    plane"): given a host batch whose sequence width reflects the raw
    loader padding, find the max REAL length across rows, snap it to the
    ladder, and slice/pad every seq-dim array to that bucket — so the
    jitted train step sees at most ``len(buckets.sizes)`` distinct
    shapes per epoch (the re-trace audit's bound) while pad FLOPs drop
    from pad-to-max to pad-to-bucket.

    Real lengths come from ``labels != ignore_index`` when labels are
    present (the one signal that is unambiguous for LM batches — pad_id
    can be a real token id), else from ``input_ids != pad_id``.

    Telemetry (when enabled): ``data_real_tokens_total``,
    ``data_padding_tokens_total``, ``data_raw_tokens_total`` and
    ``data_bucket_hits_total{bucket=}``; :attr:`stats` accumulates the
    same accounting unconditionally for bench/tests.
    """

    #: batch keys that carry a sequence dim (axis 1) and move together
    SEQ_KEYS = ("input_ids", "labels", "positions", "segment_ids")

    def __init__(self, buckets: SeqLenBuckets, *, pad_id: int = 0,
                 ignore_index: int = -100):
        self.buckets = buckets
        self.pad_id = pad_id
        self.ignore_index = ignore_index
        self.stats = BucketStats()
        self._warned_truncation = False

    @property
    def n_buckets(self) -> int:
        return len(self.buckets.sizes)

    def lengths(self, batch: dict) -> np.ndarray:
        """Per-row real lengths (int array of shape (rows,))."""
        labels = batch.get("labels")
        if labels is not None:
            valid = np.asarray(labels) != self.ignore_index
        else:
            valid = np.asarray(batch["input_ids"]) != self.pad_id
        # length = last real index + 1; all-pad rows are length 0
        rev = valid[:, ::-1]
        any_real = valid.any(axis=1)
        return np.where(any_real,
                        valid.shape[1] - rev.argmax(axis=1), 0)

    def bucket_for_batch(self, batch: dict) -> int:
        return self.buckets.bucket_for(
            max(1, int(self.lengths(batch).max(initial=0))))

    def fit(self, batch: dict) -> dict:
        """Return ``batch`` with every seq-dim array sliced/padded to
        the bucket of its max real length (other keys untouched)."""
        lens = self.lengths(batch)
        need = max(1, int(lens.max(initial=0)))
        L = self.buckets.bucket_for(need)
        rows, w = batch["input_ids"].shape[:2]
        if need > L:
            # bucket_for clamps to the ladder top: rows longer than the
            # largest bucket LOSE their tail tokens. That can be the
            # intended max-seq-len discipline, but it must never be
            # silent — warn once and count every cut token.
            cut = int(np.maximum(lens - L, 0).sum())
            self.stats.truncated_tokens += cut
            if not self._warned_truncation:
                self._warned_truncation = True
                import warnings
                warnings.warn(
                    f"batch has rows up to {need} real tokens but the "
                    f"largest seq bucket is {L} — truncating to {L} "
                    f"(this warning fires once; "
                    f"stats.truncated_tokens keeps counting). Add a "
                    f"larger bucket to train on the full sequences.",
                    stacklevel=2)
            from hetu_tpu import telemetry
            if telemetry.enabled():
                telemetry.get_registry().counter(
                    "data_truncated_tokens_total",
                    "real tokens cut because a row exceeded the "
                    "largest seq-len bucket").inc(cut)
        out = dict(batch)
        if L != w:
            pad_vals = {"input_ids": self.pad_id,
                        "labels": self.ignore_index,
                        "positions": 0, "segment_ids": PAD_SEGMENT}
            for k in self.SEQ_KEYS:
                v = out.get(k)
                if v is None:
                    continue
                v = np.asarray(v)
                if L < w:
                    out[k] = v[:, :L]
                else:
                    padded = np.full(v.shape[:1] + (L,) + v.shape[2:],
                                     pad_vals[k], v.dtype)
                    padded[:, :w] = v
                    out[k] = padded
        real = int(np.minimum(lens, L).sum())
        self.stats.batches += 1
        self.stats.real_tokens += real
        self.stats.raw_tokens += rows * w
        self.stats.bucket_tokens += rows * L
        from hetu_tpu import telemetry
        if telemetry.enabled():
            reg = telemetry.get_registry()
            reg.counter(
                "data_real_tokens_total",
                "real (non-pad) tokens dispatched to train steps").inc(
                real)
            reg.counter(
                "data_padding_tokens_total",
                "pad tokens dispatched after bucket snapping (the "
                "residual padding tax)").inc(rows * L - real)
            reg.counter(
                "data_raw_tokens_total",
                "tokens the raw loader batches carried before bucket "
                "snapping (the pad-to-max baseline)").inc(rows * w)
            reg.counter(
                "data_bucket_hits_total",
                "batches routed to each seq-len bucket").inc(
                bucket=str(L))
        return out
