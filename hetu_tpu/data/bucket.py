"""Sequence-length buckets — the static-shape answer to dynamic seq lens.

The reference handles varying sequence lengths with symbolic shapes
(``hetu/core/symbol.h:19,95,160``) propagated through shape plans
(``DeduceShapePlan``, ``define_and_run_graph.cc:303``). Under XLA every
shape is a compilation, so the TPU-native equivalent is a small set of
bucket lengths: each batch is padded/packed to its bucket and jit caches
one executable per bucket (SURVEY §7.3 item 4).
"""

from __future__ import annotations

from typing import Iterable, Sequence


class SeqLenBuckets:
    """Map raw lengths to a fixed set of bucket lengths."""

    def __init__(self, sizes: Sequence[int] | None = None, *,
                 min_len: int = 128, max_len: int = 8192,
                 multiple_of: int = 1):
        if sizes is None:
            sizes, s = [], min_len
            while s <= max_len:
                sizes.append(s)
                s *= 2
        sizes = sorted(set(int(s) for s in sizes))
        for s in sizes:
            if s % multiple_of != 0:
                raise ValueError(
                    f"bucket size {s} not a multiple of {multiple_of} "
                    f"(cp/block alignment)")
        self.sizes = sizes

    def bucket_for(self, length: int) -> int:
        for s in self.sizes:
            if length <= s:
                return s
        return self.sizes[-1]

    def group(self, lengths: Iterable[int]) -> dict[int, list[int]]:
        """indices grouped by bucket size."""
        out: dict[int, list[int]] = {}
        for i, L in enumerate(lengths):
            out.setdefault(self.bucket_for(L), []).append(i)
        return out
