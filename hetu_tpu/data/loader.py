"""Batch samplers and the data-loader pipeline.

Parity target: ``python/hetu/data/dataloader.py`` — ``build_data_loader``
(:46) with sample-level (:162) and token-level (:244) batch samplers, and
the packing path through ``Bucket``.
"""

from __future__ import annotations

from typing import Iterable, Iterator, Optional, Sequence

import numpy as np

from hetu_tpu.data.packing import PackedBatch, pack_sequences, pad_batch


def sample_batches(n_items: int, batch_size: int, *, shuffle: bool = True,
                   drop_last: bool = True, seed: int = 0
                   ) -> Iterator[list[int]]:
    """Index batches of a fixed number of samples."""
    idx = np.arange(n_items)
    if shuffle:
        np.random.default_rng(seed).shuffle(idx)
    for i in range(0, n_items, batch_size):
        b = idx[i:i + batch_size].tolist()
        if drop_last and len(b) < batch_size:
            break
        yield b


def token_batches(lengths: Sequence[int], max_tokens: int, *,
                  shuffle: bool = True, seed: int = 0
                  ) -> Iterator[list[int]]:
    """Index batches bounded by a token budget (reference token-level
    sampler, ``dataloader.py:244``)."""
    idx = np.arange(len(lengths))
    if shuffle:
        np.random.default_rng(seed).shuffle(idx)
    batch: list[int] = []
    total = 0
    for i in idx:
        L = int(lengths[int(i)])
        if batch and total + L > max_tokens:
            yield batch
            batch, total = [], 0
        batch.append(int(i))
        total += L
    if batch:
        yield batch


def build_data_loader(dataset, *, seq_len: int, batch_rows: int,
                      pack: bool = True, pad_id: int = 0, cp: int = 1,
                      cp_layout: str = "zigzag",
                      max_tokens: Optional[int] = None,
                      shuffle: bool = True, drop_last: bool = True,
                      seed: int = 0) -> Iterator[dict]:
    """Yield model-ready batches of exactly ``batch_rows`` rows ×
    ``seq_len`` tokens (static shapes for jit).

    ``pack=True`` packs multiple documents per row with segment ids;
    ``max_tokens`` switches to the token-budget sampler. ``cp``/
    ``cp_layout`` validate seq_len divisibility up-front (zigzag needs
    ``seq_len % (2*cp) == 0``) so a mismatch fails at data-prep time, not
    at the first ``shard_batch``; pass the Strategy's values.
    """
    lengths = [len(dataset[i]) for i in range(len(dataset))]
    if max_tokens is not None:
        sampler = token_batches(lengths, max_tokens, shuffle=shuffle,
                                seed=seed)
    else:
        sampler = sample_batches(len(dataset), batch_rows, shuffle=shuffle,
                                 drop_last=drop_last, seed=seed)

    pending: list[PackedBatch] = []
    rows_ids = []
    rows_labels = []
    rows_pos = []
    rows_segs = []

    def drain():
        nonlocal rows_ids, rows_labels, rows_pos, rows_segs
        while len(rows_ids) >= batch_rows:
            out = {
                "input_ids": np.stack(rows_ids[:batch_rows]),
                "labels": np.stack(rows_labels[:batch_rows]),
                "positions": np.stack(rows_pos[:batch_rows]),
                "segment_ids": np.stack(rows_segs[:batch_rows]),
            }
            rows_ids = rows_ids[batch_rows:]
            rows_labels = rows_labels[batch_rows:]
            rows_pos = rows_pos[batch_rows:]
            rows_segs = rows_segs[batch_rows:]
            yield out

    for batch_idx in sampler:
        seqs = [dataset[i] for i in batch_idx]
        pb = (pack_sequences(seqs, seq_len, pad_id=pad_id, cp=cp,
                             cp_layout=cp_layout)
              if pack else pad_batch(seqs, seq_len, pad_id=pad_id))
        rows_ids.extend(pb.input_ids)
        rows_labels.extend(pb.labels)
        rows_pos.extend(pb.positions)
        rows_segs.extend(pb.segment_ids)
        yield from drain()
    if rows_ids and not drop_last:
        # final partial batch (dynamic row count — caller opted in)
        yield {
            "input_ids": np.stack(rows_ids),
            "labels": np.stack(rows_labels),
            "positions": np.stack(rows_pos),
            "segment_ids": np.stack(rows_segs),
        }
