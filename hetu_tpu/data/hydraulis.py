"""Hydraulis-style dynamic sequence-length planning.

Parity target: ``/root/reference/examples/hydraulis/strategy/{static,
new_dynamic,new_planning,cost_model}.py`` — given the corpus' length
distribution, plan *per-bucket* batch composition (rows per micro-batch at
each padded length) and a per-bucket parallel strategy (long buckets get
context parallelism / remat) so every dispatched step costs roughly the
same and pad waste stays low. The TPU twist: each (bucket, strategy) pair
is one cached jit executable (``data.bucket.SeqLenBuckets``), so the plan
also bounds the number of compilations.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Optional, Sequence

import numpy as np

from hetu_tpu.data.bucket import SeqLenBuckets
from hetu_tpu.parallel.strategy import Strategy


def preferred_cp_impl(seq_len: int, cp: int, num_heads: int,
                      table_path: Optional[str] = None) -> str:
    """Pick ring vs Ulysses for one (seq, cp) bucket.

    Measured-profile-first: when ``workloads/out/cp_compare.json`` exists
    (written by ``workloads/cp_compare.py``), the nearest measured
    (cp, seq) winner decides. Without a same-backend measurement the
    default is RING, unconditionally: every measured cell to date
    (CPU mesh, cp∈{2,4}, seq∈{512..32k}) has ring 2.3–3× faster, so
    Ulysses is demoted to experimental — selected only where a
    measurement on THIS backend shows it winning (high head count /
    short seq is its theorized regime; `workloads/cp_compare.py` carries
    those rows for the TPU window to decide).
    """
    if num_heads % cp != 0:
        return "ring"                    # ulysses illegal
    import os as _os
    path = table_path or _os.path.join(
        _os.path.dirname(_os.path.dirname(_os.path.dirname(
            _os.path.abspath(__file__)))), "workloads", "out",
        "cp_compare.json")
    loaded = _load_cp_table(path)
    if loaded is not None:
        backend, table = loaded
        import jax
        # a table measured on another fabric must not decide (the
        # committed CPU-mesh table would otherwise silently steer TPU
        # bucket planning)
        if backend != jax.default_backend():
            _warn_stale_table(path, backend, jax.default_backend())
        else:
            # heads-tagged rows (the high-head TPU block) only decide
            # for their own head count; untagged rows are generic
            rows = [r for r in table if r["cp"] == cp
                    and r.get("heads") in (None, num_heads)]
            if rows:
                best = min(rows, key=lambda r: abs(r["seq"] - seq_len))
                # measured point must be within 4x in seq — beyond that
                # the winner is extrapolation, not measurement
                if max(best["seq"], seq_len) <= 4 * min(best["seq"],
                                                        seq_len):
                    return best["winner"]
    return "ring"


_WARNED_TABLES: set = set()


def _warn_stale_table(path: str, table_backend: str, here: str) -> None:
    """One-time notice that a winners table is being IGNORED — e.g. a
    pre-backend-field table (backend "unknown") or one measured on a
    different fabric. Silent discard would leave real measurements dead
    with no hint to re-run cp_compare.py."""
    if path in _WARNED_TABLES:
        return
    _WARNED_TABLES.add(path)
    import warnings
    warnings.warn(
        f"cp winners table {path} was measured on backend "
        f"{table_backend!r} but this process runs {here!r} — ignoring it "
        f"(re-run workloads/cp_compare.py here to refresh)",
        stacklevel=3)


def _load_cp_table(path: str):
    """(backend, results) from the winners table via the shared
    measured-defaults loader (``core.measured`` memoizes on mtime+size —
    plan_buckets calls preferred_cp_impl per bucket × cp candidate)."""
    from hetu_tpu.core.measured import read_measured
    data = read_measured("cp_compare.json", path=path)
    if not isinstance(data, dict) or "results" not in data:
        return None
    return (data.get("backend", "unknown"), data["results"])


@dataclasses.dataclass(frozen=True)
class BucketPlan:
    """Dispatch recipe for one bucket length."""

    bucket_len: int
    batch_rows: int          # rows per dispatched batch at this length
    strategy: Strategy
    est_step_ms: float       # cost-model estimate (0 when no model given)

    @property
    def tokens(self) -> int:
        return self.bucket_len * self.batch_rows


def plan_buckets(lengths: Iterable[int], *,
                 buckets: SeqLenBuckets,
                 token_budget: int,
                 dims_base=None, topo=None,
                 max_cp: int = 1,
                 base_strategy: Optional[Strategy] = None,
                 row_multiple: int = 1,
                 cp_impl: Optional[str] = None,
                 hbm_budget_bytes: Optional[float] = None
                 ) -> dict[int, BucketPlan]:
    """Choose per-bucket rows + strategy for a roughly constant token
    budget per dispatch.

    ``dims_base``/``topo`` (galvatron ``ModelDims``/``TPUTopology``)
    enable cost-model-guided cp/remat per bucket; without them the plan is
    token-budget only. Only buckets that appear in ``lengths`` get plans.
    ``row_multiple``: round rows up to this multiple (the consumer's dp
    degree — batch dims must divide over the mesh). ``cp_impl``:
    "ring"/"ulysses" pins the implementation for every cp>1 candidate;
    None (default) selects per bucket via :func:`preferred_cp_impl`
    (an explicit pin is the only way to express intent — the dataclass
    default on ``base_strategy`` is indistinguishable from unset).
    ``hbm_budget_bytes``: per-device HBM ceiling — every candidate is
    ALSO priced through the memory ledger at ITS bucket's seq-len
    (``engine.memory.estimate_breakdown``), so a long bucket cannot
    select a (cp, remat) pair whose activations only fit at the short
    buckets' lengths (the admission gate and the planner read the same
    arithmetic).
    """
    lengths = list(lengths)
    present = sorted(buckets.group(lengths))
    base = base_strategy or Strategy()
    plans: dict[int, BucketPlan] = {}
    for L in present:
        rows = max(1, token_budget // L)
        if rows % row_multiple:
            rows += row_multiple - rows % row_multiple
        strategy, est = base, 0.0
        if dims_base is not None and topo is not None:
            from hetu_tpu.tools.galvatron.cost_model import estimate
            best = None
            cps = [1]   # cp=1 (remat-only) candidates need no extra devices
            cp = 2
            while cp <= max_cp and L % (2 * cp) == 0 \
                    and cp <= topo.num_devices:
                cps.append(cp)
                cp *= 2
            for cp in cps:
                impl = base.cp_impl
                if cp > 1:
                    impl = cp_impl if cp_impl is not None else \
                        preferred_cp_impl(L, cp, dims_base.num_heads)
                for remat in ("none", "full"):
                    cand = dataclasses.replace(
                        base, cp=cp, remat=remat, cp_impl=impl,
                        dp=max(1, topo.num_devices // (cp * base.tp
                                                       * base.pp)))
                    dims = dataclasses.replace(
                        dims_base, seq_len=L,
                        global_batch=max(rows, cand.dp))
                    c = estimate(dims, cand, topo)
                    if hbm_budget_bytes is not None:
                        from hetu_tpu.engine.memory import (
                            estimate_breakdown)
                        if estimate_breakdown(dims, cand).peak_bytes \
                                > hbm_budget_bytes:
                            continue
                    if c.fits(topo) and (best is None
                                         or c.step_time < best[0]):
                        best = (c.step_time, cand)
            if best is not None:
                est, strategy = best[0] * 1e3, best[1]
        plans[L] = BucketPlan(L, rows, strategy, est)
    return plans


@dataclasses.dataclass
class DispatchStats:
    batches: int = 0
    real_tokens: int = 0
    padded_tokens: int = 0

    @property
    def pad_fraction(self) -> float:
        return 1.0 - self.real_tokens / self.padded_tokens \
            if self.padded_tokens else 0.0


class DynamicDispatcher:
    """Group samples by bucket and emit fixed-shape batches per plan.

    The reference's Hydraulis dispatcher composes each global batch from
    per-bucket sub-batches matched to strategies; here each emitted batch
    carries its :class:`BucketPlan` so the trainer can route it to the
    right (bucket, strategy) jit. Rows shorter than the bucket are padded
    with ``pad_id`` and label ``ignore_index``.

    ``pack=True`` adds sequence PACKING on top of bucketing: documents
    short enough for the ``pack_len`` bucket (default: the largest
    planned bucket) are first-fit packed into its rows
    (``data.packing.pack_sequences`` — per-token segment ids + reset
    positions, loss masks at segment boundaries, so the packed batch
    trains identically to the same docs padded separately), cutting pad
    waste below what per-doc bucketing can reach — a row holds many
    docs, so only fill inefficiency pads. Docs longer than ``pack_len``
    still dispatch through their own (unpacked) buckets. Packed batches
    carry ``positions`` + ``segment_ids``; the emitted shapes stay fixed
    per bucket, so the compile bound is unchanged.
    """

    def __init__(self, plans: dict[int, BucketPlan], *,
                 pad_id: int = 0, ignore_index: int = -100,
                 pack: bool = False, pack_len: Optional[int] = None):
        self.plans = plans
        self.pad_id = pad_id
        self.ignore_index = ignore_index
        self.pack = pack
        self.pack_len = int(pack_len) if pack_len else \
            (max(plans) if plans else 0)
        if pack and self.pack_len not in plans:
            raise ValueError(
                f"pack_len {self.pack_len} has no BucketPlan "
                f"(available: {sorted(plans)})")
        self.stats = DispatchStats()

    def batches(self, seqs: Sequence[np.ndarray], *,
                drop_remainder: bool = False):
        """Yield ``(batch_dict, plan)`` per full sub-batch, largest
        buckets first (long-seq steps dominate; failing fast on them
        matters)."""
        buckets = SeqLenBuckets(sizes=sorted(self.plans))
        by_bucket: dict[int, list[int]] = {}
        packable: list[int] = []
        for i, s in enumerate(seqs):
            # +1: LM shift consumes one token
            L = buckets.bucket_for(max(0, len(s) - 1))
            if self.pack and len(s) <= self.pack_len:
                packable.append(i)
            else:
                by_bucket.setdefault(L, []).append(i)
        for L in sorted(by_bucket, reverse=True):
            plan = self.plans[L]
            idxs = by_bucket[L]
            for k in range(0, len(idxs), plan.batch_rows):
                group = idxs[k:k + plan.batch_rows]
                if len(group) < plan.batch_rows and drop_remainder:
                    break
                yield self._emit(seqs, group, plan), plan
        if packable:
            yield from self._emit_packed(seqs, packable,
                                         self.plans[self.pack_len],
                                         drop_remainder=drop_remainder)

    def _emit_packed(self, seqs, idxs, plan: BucketPlan, *,
                     drop_remainder: bool = False):
        """First-fit pack the docs into ``plan.bucket_len`` rows, then
        chunk the packed rows into fixed (batch_rows, bucket_len)
        batches (short final chunks pad with all-ignored rows unless
        ``drop_remainder``)."""
        from hetu_tpu.data.packing import pack_sequences
        L, R = plan.bucket_len, plan.batch_rows
        packed = pack_sequences([np.asarray(seqs[i])[:L] for i in idxs],
                                L, pad_id=self.pad_id,
                                ignore_index=self.ignore_index)
        n = packed.input_ids.shape[0]
        for k in range(0, n, R):
            rows = min(R, n - k)
            if rows < R and drop_remainder:
                break
            batch = {}
            pads = {"input_ids": self.pad_id,
                    "labels": self.ignore_index,
                    "positions": 0, "segment_ids": 0}
            for key, arr in packed.as_batch().items():
                out = np.full((R, L), pads[key], arr.dtype)
                out[:rows] = arr[k:k + rows]
                batch[key] = out
            self.stats.batches += 1
            self.stats.real_tokens += int(
                (batch["labels"] != self.ignore_index).sum())
            self.stats.padded_tokens += R * L
            yield batch, plan

    def _emit(self, seqs, group, plan: BucketPlan) -> dict:
        L = plan.bucket_len
        n = plan.batch_rows
        ids = np.full((n, L), self.pad_id, np.int32)
        labels = np.full((n, L), self.ignore_index, np.int32)
        for r, i in enumerate(group):
            s = np.asarray(seqs[i])[:L + 1]
            t = len(s) - 1
            if t <= 0:
                continue
            ids[r, :t] = s[:-1]
            labels[r, :t] = s[1:]
            self.stats.real_tokens += t
        self.stats.batches += 1
        self.stats.padded_tokens += n * L
        return {"input_ids": ids, "labels": labels}


def naive_pad_fraction(seqs: Sequence[np.ndarray], max_len: int) -> float:
    """Pad waste of the fixed-max-length baseline (for comparison)."""
    real = sum(min(max(0, len(s) - 1), max_len) for s in seqs)
    return 1.0 - real / (len(seqs) * max_len) if seqs else 0.0
