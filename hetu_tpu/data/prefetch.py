"""Async device prefetcher: overlap host batch prep + H2D transfer with
the previous step's compute.

Role parity: the reference's async C++ dataloader (``hetu/graph/data/
dataloader.h:18`` batched async feeder) and its dedicated H2D stream
(stream plan index 3, ``core/stream.h``). TPU-native form: a background
thread runs the (numpy-producing) host iterator and eagerly issues
``plan.shard_batch`` — jax device transfers are async, so by the time the
training loop asks for batch N+1 its transfer has already been riding
alongside step N's compute. A bounded queue applies back-pressure so at
most ``buffer_size`` batches of HBM are pinned.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterable, Iterator, Optional


class DevicePrefetcher:
    """Wrap a host batch iterable; yields device-resident batches.

    ``place`` defaults to the plan's ``shard_batch``; pass a custom
    callable for non-dict batches. The background thread dies with the
    consumer (daemon) and propagates iterator exceptions at ``__next__``.
    """

    _SENTINEL = object()

    def __init__(self, batches: Iterable[Any], place: Callable[[Any], Any],
                 *, buffer_size: int = 2,
                 max_items: Optional[int] = None):
        if buffer_size < 1:
            raise ValueError("buffer_size must be >= 1")
        self._q: queue.Queue = queue.Queue(maxsize=buffer_size)
        self._err: Optional[BaseException] = None
        self._place = place
        self._stopped = False
        self._done = False
        self._thread = threading.Thread(
            target=self._producer, args=(iter(batches), max_items),
            daemon=True)
        self._thread.start()

    def _put(self, item) -> bool:
        """Blocking put that aborts when the consumer closed us."""
        while not self._stopped:
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _producer(self, it: Iterator[Any], max_items) -> None:
        try:
            # ``max_items`` caps how far we read — checked BEFORE each
            # ``next`` so a shared iterator loses nothing: an eager pull
            # past the consumer's step budget would silently drop batches
            # from a chained train() call
            n = 0
            while not self._stopped and \
                    (max_items is None or n < max_items):
                try:
                    batch = next(it)
                except StopIteration:
                    break
                # device_put inside shard_batch is async — this enqueues
                # the H2D copies without blocking on them
                if not self._put(self._place(batch)):
                    return
                n += 1
        except BaseException as e:   # propagate to the consumer
            self._err = e
        finally:
            self._put(self._SENTINEL)

    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            raise StopIteration   # iterator contract: keep raising
        item = self._q.get()
        if item is self._SENTINEL:
            self._done = True
            if self._err is not None:
                err, self._err = self._err, None
                raise err
            raise StopIteration
        return item

    def close(self) -> None:
        self._stopped = True      # _put() aborts within its timeout
        self._done = True
        # release any staged device batches immediately
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass


def prefetch_to_device(batches: Iterable[Any], plan, *,
                       buffer_size: int = 2) -> DevicePrefetcher:
    """Prefetch ``batches`` through ``plan.shard_batch`` (TrainPlan)."""
    return DevicePrefetcher(batches, plan.shard_batch,
                            buffer_size=buffer_size)
