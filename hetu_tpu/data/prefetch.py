"""Async device prefetcher: overlap host batch prep + H2D transfer with
the previous step's compute.

Role parity: the reference's async C++ dataloader (``hetu/graph/data/
dataloader.h:18`` batched async feeder) and its dedicated H2D stream
(stream plan index 3, ``core/stream.h``). TPU-native form: a background
thread runs the (numpy-producing) host iterator and eagerly issues
``plan.shard_batch`` — jax device transfers are async, so by the time the
training loop asks for batch N+1 its transfer has already been riding
alongside step N's compute. A bounded queue applies back-pressure so at
most ``buffer_size`` batches of HBM are pinned.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterable, Iterator, List, Optional

from hetu_tpu import telemetry

_SENTINEL = object()

# consumer waits shorter than this are queue handoff noise, not stalls
_STALL_SPAN_THRESHOLD_S = 1e-3


def _producer_loop(q: "queue.Queue", place: Callable[[Any], Any],
                   it: Iterator[Any], max_items: Optional[int],
                   stop: threading.Event, err_box: List[BaseException]):
    """Module-level so the thread holds NO reference to the prefetcher —
    an abandoned DevicePrefetcher stays collectable and its ``__del__``
    can stop this loop (a bound-method target would pin ``self`` and leak
    the thread plus every staged device batch)."""

    def put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    try:
        # ``max_items`` caps how far we read — checked BEFORE each
        # ``next`` so a shared iterator loses nothing: an eager pull past
        # the consumer's step budget would silently drop batches from a
        # chained train() call
        n = 0
        while not stop.is_set() and (max_items is None or n < max_items):
            try:
                batch = next(it)
            except StopIteration:
                break
            # device_put inside shard_batch is async — this enqueues the
            # H2D copies without blocking on them
            if not put(place(batch)):
                return
            n += 1
    except BaseException as e:   # propagate to the consumer
        err_box.append(e)
    finally:
        put(_SENTINEL)


class DevicePrefetcher:
    """Wrap a host batch iterable; yields device-resident batches.

    ``place`` defaults to the plan's ``shard_batch``; pass a custom
    callable for non-dict batches. Usable as a context manager; an
    abandoned instance is garbage-collected (``__del__`` stops the
    producer). Producer exceptions surface at ``__next__``.
    """

    def __init__(self, batches: Iterable[Any], place: Callable[[Any], Any],
                 *, buffer_size: int = 2,
                 max_items: Optional[int] = None):
        if buffer_size < 1:
            raise ValueError("buffer_size must be >= 1")
        self._q: queue.Queue = queue.Queue(maxsize=buffer_size)
        self._err_box: List[BaseException] = []
        self._stop = threading.Event()
        self._done = False
        self._thread = threading.Thread(
            target=_producer_loop,
            args=(self._q, place, iter(batches), max_items, self._stop,
                  self._err_box),
            daemon=True)
        self._thread.start()

    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            raise StopIteration   # iterator contract: keep raising
        if telemetry.enabled():
            # time the blocking get: the consumer waiting here IS the
            # data stall (the producer fell behind the step loop)
            t0 = time.perf_counter()
            item = self._q.get()
            wait = time.perf_counter() - t0
            reg = telemetry.get_registry()
            reg.counter("data_stall_seconds",
                        "train loop blocked waiting for batches").inc(wait)
            reg.gauge("data_queue_depth",
                      "staged batches after this fetch").set(
                          self._q.qsize())
            if wait > _STALL_SPAN_THRESHOLD_S:
                telemetry.get_tracer().complete(
                    "stall", wait, where="prefetch")
        else:
            item = self._q.get()
        if item is _SENTINEL:
            self._done = True
            if self._err_box:
                raise self._err_box.pop()
            raise StopIteration
        return item

    def close(self) -> None:
        self._stop.set()          # producer aborts within its put timeout
        self._done = True
        # join BEFORE draining: a producer blocked in put() could
        # otherwise succeed after the drain and leave one staged device
        # batch pinned in the queue until GC. Short timeout: the join
        # only needs to cover a put() already in flight (0.1s poll); a
        # producer stuck in next(it) can't enqueue after _stop anyway,
        # and __del__ → close() must not stall GC.
        if self._thread is not threading.current_thread():
            self._thread.join(timeout=0.3)
        # release any staged device batches immediately
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def prefetch_to_device(batches: Iterable[Any], plan, *,
                       buffer_size: int = 2,
                       max_items: Optional[int] = None) -> DevicePrefetcher:
    """Prefetch ``batches`` through ``plan.shard_batch`` (TrainPlan)."""
    return DevicePrefetcher(batches, plan.shard_batch,
                            buffer_size=buffer_size, max_items=max_items)
