"""Async device prefetcher: overlap host batch prep + H2D transfer with
the previous step's compute.

Role parity: the reference's async C++ dataloader (``hetu/graph/data/
dataloader.h:18`` batched async feeder) and its dedicated H2D stream
(stream plan index 3, ``core/stream.h``). TPU-native form: a background
thread runs the (numpy-producing) host iterator and eagerly issues
``plan.shard_batch`` — jax device transfers are async, so by the time the
training loop asks for batch N+1 its transfer has already been riding
alongside step N's compute. A bounded queue applies back-pressure so at
most ``buffer_size`` batches of HBM are pinned.

Overlap accounting: every fetch records whether the batch was already
staged (``prefetch_ready_total`` — true H2D/compute overlap) or the
consumer had to block (``data_stall_seconds`` + a ``stall`` span);
``stats()`` exposes the same numbers programmatically. Hot switching
mid-stream is supported via :meth:`DevicePrefetcher.set_place`: the
Trainer re-points placement at the new plan's ``shard_batch`` and any
batch staged under the old plan is re-placed (from its retained host
form) on fetch — never dropped, never double-permuted.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterable, Iterator, List, Optional

from hetu_tpu import telemetry

_SENTINEL = object()

# consumer waits shorter than this are queue handoff noise, not stalls
_STALL_SPAN_THRESHOLD_S = 1e-3


def _producer_loop(pf: "_ProducerState", it: Iterator[Any],
                   max_items: Optional[int]):
    """Module-level so the thread holds NO reference to the prefetcher —
    an abandoned DevicePrefetcher stays collectable and its ``__del__``
    can stop this loop (a bound-method target would pin ``self`` and leak
    the thread plus every staged device batch). ``pf`` is the shared
    producer/consumer state only (queue, stop flag, place fn)."""
    q, stop = pf.q, pf.stop

    def put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    try:
        # ``max_items`` caps how far we read — checked BEFORE each
        # ``next`` so a shared iterator loses nothing: an eager pull past
        # the consumer's step budget would silently drop batches from a
        # chained train() call
        n = 0
        while not stop.is_set() and (max_items is None or n < max_items):
            try:
                batch = next(it)
            except StopIteration:
                break
            # read (place, epoch) atomically: a concurrent set_place must
            # never pair the new epoch with the old placement
            with pf.lock:
                place, epoch = pf.place, pf.epoch
            # device_put inside shard_batch is async — this enqueues the
            # H2D copies without blocking on them. The HOST batch rides
            # along so a post-switch consumer can re-place it under the
            # new plan (re-placing the device batch would double-apply
            # layout permutes like zigzag CP).
            if not put((epoch, batch, place(batch))):
                return
            n += 1
    except BaseException as e:   # propagate to the consumer
        pf.err_box.append(e)
    finally:
        put(_SENTINEL)


class _ProducerState:
    """State shared between producer thread and consumer, reference-free
    with respect to the DevicePrefetcher object itself."""

    __slots__ = ("q", "stop", "err_box", "lock", "place", "epoch")

    def __init__(self, q, place):
        self.q = q
        self.stop = threading.Event()
        self.err_box: List[BaseException] = []
        self.lock = threading.Lock()
        self.place = place
        self.epoch = 0


class DevicePrefetcher:
    """Wrap a host batch iterable; yields device-resident batches.

    ``place`` defaults to the plan's ``shard_batch``; pass a custom
    callable for non-dict batches. Usable as a context manager; an
    abandoned instance is garbage-collected (``__del__`` stops the
    producer). Producer exceptions surface at ``__next__``.
    """

    def __init__(self, batches: Iterable[Any], place: Callable[[Any], Any],
                 *, buffer_size: int = 2,
                 max_items: Optional[int] = None):
        if buffer_size < 1:
            raise ValueError("buffer_size must be >= 1")
        self._q: queue.Queue = queue.Queue(maxsize=buffer_size)
        self._state = _ProducerState(self._q, place)
        self._done = False
        # overlap accounting (host-side ints: no lock needed beyond GIL)
        self.consumed = 0
        self.ready_hits = 0       # batch already staged when asked for
        self.restaged = 0         # re-placed after a mid-run set_place
        self.stall_seconds = 0.0
        self._thread = threading.Thread(
            target=_producer_loop,
            args=(self._state, iter(batches), max_items),
            daemon=True)
        self._thread.start()

    # -- hot-switch integration ---------------------------------------------
    def set_place(self, place: Callable[[Any], Any]) -> None:
        """Swap the placement function mid-stream (Trainer hot switch):
        batches produced from now on use ``place``; batches already in
        the queue are re-placed from their host form when fetched."""
        with self._state.lock:
            self._state.place = place
            self._state.epoch += 1

    def stats(self) -> dict:
        """Overlap counters: ``ready_hits``/``consumed`` is the fraction
        of fetches that never blocked — direct evidence the H2D path ran
        under the previous step's compute."""
        return {"consumed": self.consumed, "ready_hits": self.ready_hits,
                "restaged": self.restaged,
                "stall_seconds": round(self.stall_seconds, 6),
                "queue_depth": self._q.qsize()}

    # -- iteration ----------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            raise StopIteration   # iterator contract: keep raising
        tel = telemetry.enabled()
        try:
            item = self._q.get_nowait()
            wait = 0.0
            ready = True
        except queue.Empty:
            # time the blocking get: the consumer waiting here IS the
            # data stall (the producer fell behind the step loop)
            t0 = time.perf_counter()
            item = self._q.get()
            wait = time.perf_counter() - t0
            ready = False
        if item is _SENTINEL:
            self._done = True
            if self._state.err_box:
                raise self._state.err_box.pop()
            raise StopIteration
        self.consumed += 1
        self.ready_hits += ready
        self.stall_seconds += wait
        if tel:
            reg = telemetry.get_registry()
            reg.counter("prefetch_batches_total",
                        "batches served by the device prefetcher").inc()
            if ready:
                reg.counter("prefetch_ready_total",
                            "fetches that found the batch already "
                            "staged (H2D overlapped compute)").inc()
            reg.counter("data_stall_seconds",
                        "train loop blocked waiting for batches").inc(wait)
            reg.gauge("data_queue_depth",
                      "staged batches after this fetch").set(
                          self._q.qsize())
            if wait > _STALL_SPAN_THRESHOLD_S:
                telemetry.get_tracer().complete(
                    "stall", wait, where="prefetch")
        epoch, host_batch, placed = item
        if epoch != self._state.epoch:
            # staged under a pre-switch plan: re-place the retained host
            # batch under the current one (bounded: <= buffer_size items
            # per switch)
            with self._state.lock:
                place = self._state.place
            placed = place(host_batch)
            self.restaged += 1
            if tel:
                telemetry.get_registry().counter(
                    "prefetch_restaged_total",
                    "staged batches re-placed after a hot switch").inc()
        return placed

    def close(self) -> None:
        self._state.stop.set()    # producer aborts within its put timeout
        self._done = True
        # join BEFORE draining: a producer blocked in put() could
        # otherwise succeed after the drain and leave one staged device
        # batch pinned in the queue until GC. Short timeout: the join
        # only needs to cover a put() already in flight (0.1s poll); a
        # producer stuck in next(it) can't enqueue after _stop anyway,
        # and __del__ → close() must not stall GC.
        if self._thread is not threading.current_thread():
            self._thread.join(timeout=0.3)
        # release any staged device batches immediately
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def prefetch_to_device(batches: Iterable[Any], plan, *,
                       buffer_size: int = 2,
                       max_items: Optional[int] = None) -> DevicePrefetcher:
    """Prefetch ``batches`` through ``plan.shard_batch`` (TrainPlan)."""
    return DevicePrefetcher(batches, plan.shard_batch,
                            buffer_size=buffer_size, max_items=max_items)
