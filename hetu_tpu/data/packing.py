"""Sequence packing with segment ids and reset positions.

Parity target: ``python/hetu/data/bucket.py`` — ``Bucket.pack_data`` (:86)
packs variable-length sequences into fixed rows with ``cu_seqlens``;
``generate_cp_pack_data`` (:193) makes rows CP-splittable. The TPU-native
formulation replaces cu_seqlens with per-token ``segment_ids`` (what the
flash kernels consume) and per-token ``positions`` (reset at each segment
start, what rotary/learned embeddings consume).

Loss alignment: ``labels[i] = tokens[i+1]`` *within* a segment; the last
token of each segment and all padding get ``ignore_index`` so packed loss
equals the sum of per-sequence losses.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Optional, Sequence

import numpy as np


@dataclasses.dataclass
class PackedBatch:
    """Arrays shaped (rows, seq_len); feed directly as a model batch."""

    input_ids: np.ndarray
    labels: np.ndarray
    positions: np.ndarray
    segment_ids: np.ndarray

    def as_batch(self) -> dict:
        return {"input_ids": self.input_ids, "labels": self.labels,
                "positions": self.positions,
                "segment_ids": self.segment_ids}


def pack_sequences(seqs: Sequence[np.ndarray], seq_len: int, *,
                   pad_id: int = 0, ignore_index: int = -100,
                   cp: int = 1) -> PackedBatch:
    """Greedy first-fit packing of token sequences into rows of
    ``seq_len``.

    ``cp``: context-parallel degree — asserts ``seq_len % cp == 0`` so rows
    split evenly into contiguous ring chunks (the reference additionally
    supports SYM splits for load balance; contiguous is what
    ``parallel.ring_attention`` consumes).

    Sequences longer than ``seq_len`` are truncated. Each packed segment
    gets a distinct id; padding uses a trailing id with all-ignored labels.
    """
    if seq_len % cp != 0:
        raise ValueError(f"seq_len {seq_len} not divisible by cp {cp}")
    rows: list[list[np.ndarray]] = []
    space: list[int] = []
    for seq in seqs:
        seq = np.asarray(seq)[:seq_len]
        placed = False
        for i, free in enumerate(space):
            if len(seq) <= free:
                rows[i].append(seq)
                space[i] -= len(seq)
                placed = True
                break
        if not placed:
            rows.append([seq])
            space.append(seq_len - len(seq))

    n = len(rows)
    input_ids = np.full((n, seq_len), pad_id, np.int32)
    labels = np.full((n, seq_len), ignore_index, np.int32)
    positions = np.zeros((n, seq_len), np.int32)
    segment_ids = np.zeros((n, seq_len), np.int32)
    for r, segs in enumerate(rows):
        off = 0
        for s_id, seq in enumerate(segs):
            L = len(seq)
            input_ids[r, off:off + L] = seq
            labels[r, off:off + L - 1] = seq[1:]
            positions[r, off:off + L] = np.arange(L)
            segment_ids[r, off:off + L] = s_id
            off += L
        # padding tail: its own segment id, positions 0, labels ignored
        segment_ids[r, off:] = len(segs)
    return PackedBatch(input_ids, labels, positions, segment_ids)


def pad_batch(seqs: Sequence[np.ndarray], seq_len: int, *,
              pad_id: int = 0, ignore_index: int = -100) -> PackedBatch:
    """One sequence per row (the reference's pad mode, ``bucket.py:8``)."""
    n = len(seqs)
    input_ids = np.full((n, seq_len), pad_id, np.int32)
    labels = np.full((n, seq_len), ignore_index, np.int32)
    positions = np.zeros((n, seq_len), np.int32)
    segment_ids = np.ones((n, seq_len), np.int32)  # 1 = padding
    for r, seq in enumerate(seqs):
        seq = np.asarray(seq)[:seq_len]
        L = len(seq)
        if L == 0:
            continue
        input_ids[r, :L] = seq
        labels[r, :L - 1] = seq[1:]
        positions[r, :L] = np.arange(L)
        segment_ids[r, :L] = 0
    return PackedBatch(input_ids, labels, positions, segment_ids)
