"""Sequence packing with segment ids and reset positions.

Parity target: ``python/hetu/data/bucket.py`` — ``Bucket.pack_data`` (:86)
packs variable-length sequences into fixed rows with ``cu_seqlens``;
``generate_cp_pack_data`` (:193) makes rows CP-splittable. The TPU-native
formulation replaces cu_seqlens with per-token ``segment_ids`` (what the
flash kernels consume) and per-token ``positions`` (reset at each segment
start, what rotary/learned embeddings consume).

Loss alignment: ``labels[i] = tokens[i+1]`` *within* a segment; the last
token of each segment and all padding get ``ignore_index`` so packed loss
equals the sum of per-sequence losses.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Optional, Sequence

import numpy as np


@dataclasses.dataclass
class PackedBatch:
    """Arrays shaped (rows, seq_len); feed directly as a model batch."""

    input_ids: np.ndarray
    labels: np.ndarray
    positions: np.ndarray
    segment_ids: np.ndarray

    def as_batch(self) -> dict:
        return {"input_ids": self.input_ids, "labels": self.labels,
                "positions": self.positions,
                "segment_ids": self.segment_ids}


def pack_sequences(seqs: Sequence[np.ndarray], seq_len: int, *,
                   pad_id: int = 0, ignore_index: int = -100,
                   cp: int = 1, cp_layout: str = "contiguous") -> PackedBatch:
    """Greedy first-fit packing of token sequences into rows of
    ``seq_len``.

    ``cp``: context-parallel degree; ``cp_layout``: "contiguous" needs
    ``seq_len % cp == 0``, "zigzag" (the load-balanced SYM split — see
    :func:`zigzag_indices`) needs ``seq_len % (2*cp) == 0``. The permutation
    itself is applied by ``TrainPlan.shard_batch``, not here — packed rows
    stay in natural order.

    Sequences longer than ``seq_len`` are truncated. Each packed segment
    gets a distinct id; padding uses a trailing id with all-ignored labels.
    """
    div = 2 * cp if (cp_layout == "zigzag" and cp > 1) else cp
    if seq_len % div != 0:
        raise ValueError(
            f"seq_len {seq_len} not divisible by {div} "
            f"(cp={cp}, layout={cp_layout})")
    rows: list[list[np.ndarray]] = []
    space: list[int] = []
    for seq in seqs:
        seq = np.asarray(seq)[:seq_len]
        placed = False
        for i, free in enumerate(space):
            if len(seq) <= free:
                rows[i].append(seq)
                space[i] -= len(seq)
                placed = True
                break
        if not placed:
            rows.append([seq])
            space.append(seq_len - len(seq))

    n = len(rows)
    input_ids = np.full((n, seq_len), pad_id, np.int32)
    labels = np.full((n, seq_len), ignore_index, np.int32)
    positions = np.zeros((n, seq_len), np.int32)
    segment_ids = np.zeros((n, seq_len), np.int32)
    for r, segs in enumerate(rows):
        off = 0
        for s_id, seq in enumerate(segs):
            L = len(seq)
            input_ids[r, off:off + L] = seq
            labels[r, off:off + L - 1] = seq[1:]
            positions[r, off:off + L] = np.arange(L)
            segment_ids[r, off:off + L] = s_id
            off += L
        # padding tail: its own segment id, positions 0, labels ignored
        segment_ids[r, off:] = len(segs)
    return PackedBatch(input_ids, labels, positions, segment_ids)


def zigzag_indices(seq_len: int, cp: int) -> np.ndarray:
    """Zigzag (CP-symmetric) permutation of global sequence positions.

    The global sequence is cut into ``2*cp`` chunks; ring rank ``i`` owns
    chunks ``(i, 2*cp-1-i)``, so under causal masking every rank touches
    the same number of KV positions per ring hop (the reference's SYM
    split, ``hetu/graph/ops/ParallelAttention.h:21-25`` fed by
    ``data/bucket.py:193`` ``generate_cp_pack_data``; contiguous chunks
    leave the causal ring ~2x unbalanced).

    Returns ``idx`` with ``permuted[j] = original[idx[j]]``; contiguous
    sharding of the permuted array over cp then yields the zigzag layout.
    """
    if seq_len % (2 * cp) != 0:
        raise ValueError(f"seq_len {seq_len} not divisible by 2*cp={2 * cp}")
    c = seq_len // (2 * cp)
    chunks = np.arange(seq_len).reshape(2 * cp, c)
    order = [x for i in range(cp) for x in (i, 2 * cp - 1 - i)]
    return chunks[order].reshape(-1)


def zigzag_permute(x, cp: int, axis: int = -1):
    """Reorder ``x`` along ``axis`` into the zigzag CP layout.

    Works on numpy and jax arrays (both expose ``.take``); identity when
    ``cp == 1``.
    """
    if cp == 1:
        return x
    return x.take(zigzag_indices(x.shape[axis], cp), axis=axis)


def zigzag_restore(x, cp: int, axis: int = -1):
    """Inverse of :func:`zigzag_permute`."""
    if cp == 1:
        return x
    idx = zigzag_indices(x.shape[axis], cp)
    inv = np.empty_like(idx)
    inv[idx] = np.arange(len(idx))
    return x.take(inv, axis=axis)


def pad_batch(seqs: Sequence[np.ndarray], seq_len: int, *,
              pad_id: int = 0, ignore_index: int = -100) -> PackedBatch:
    """One sequence per row (the reference's pad mode, ``bucket.py:8``)."""
    n = len(seqs)
    input_ids = np.full((n, seq_len), pad_id, np.int32)
    labels = np.full((n, seq_len), ignore_index, np.int32)
    positions = np.zeros((n, seq_len), np.int32)
    segment_ids = np.ones((n, seq_len), np.int32)  # 1 = padding
    for r, seq in enumerate(seqs):
        seq = np.asarray(seq)[:seq_len]
        L = len(seq)
        if L == 0:
            continue
        input_ids[r, :L] = seq
        labels[r, :L - 1] = seq[1:]
        positions[r, :L] = np.arange(L)
        segment_ids[r, :L] = 0
    return PackedBatch(input_ids, labels, positions, segment_ids)
