"""Llama family (RMSNorm, RoPE, GQA, SwiGLU).

Parity target: ``python/hetu/models/llama/llama_model.py`` —
``LlamaAttention`` :88 (ParallelAttention op), MLP :292 (SwiGLU), blocks
:342, ``LlamaModel`` :385, ``LlamaLMHeadModel`` :446. The reference threads
ds-parallel unions + per-block recompute configs; here the same knobs arrive
via logical axes, ActivationSharding, and the ``remat`` argument.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from hetu_tpu.nn.layers import RMSNorm
from hetu_tpu.nn.module import Module, normal_init
from hetu_tpu.nn.parallel import (
    ColumnParallelLinear, ParallelAttention, ParallelMLP, StackedBlocks,
    VocabParallelEmbedding,
)
from hetu_tpu.ops.dropout import dropout
from hetu_tpu.ops.losses import vocab_parallel_lm_loss
from hetu_tpu.parallel.sharding import act_constrain


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: Optional[int] = None   # None → MHA
    head_dim: Optional[int] = None
    max_positions: int = 4096
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-5
    init_std: float = 0.02
    tie_embeddings: bool = False
    # residual dropout (0.0 = Llama-standard; nonzero is the common SFT
    # regularizer). Keys threaded by the train step; eval never drops.
    resid_pdrop: float = 0.0
    # dropout on attention probs (reference flash p_dropout); carried
    # by both attention paths — in-kernel counter-RNG masks on Pallas
    attn_pdrop: float = 0.0
    # MoE (0 experts = dense; experts are SwiGLU like the dense MLP)
    num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_coef: float = 0.01
    moe_gate: str = "topk"   # "topk" | "ktop1" | "sam" | "balance"
    moe_num_groups: int = 0  # SAM expert groups (0 = gate default)

    @classmethod
    def llama_7b(cls):
        return cls()

    @classmethod
    def llama_13b(cls):
        return cls(hidden_size=5120, intermediate_size=13824,
                   num_layers=40, num_heads=40)

    @classmethod
    def tiny(cls):
        """Test-size config with GQA exercised."""
        return cls(vocab_size=256, hidden_size=64, intermediate_size=128,
                   num_layers=2, num_heads=4, num_kv_heads=2,
                   max_positions=128)


class LlamaBlock(Module):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.input_norm = RMSNorm(cfg.hidden_size, eps=cfg.rms_norm_eps)
        self.attn = ParallelAttention(
            cfg.hidden_size, cfg.num_heads,
            num_kv_heads=cfg.num_kv_heads or cfg.num_heads,
            head_dim=cfg.head_dim, bias=False, causal=True, use_rope=True,
            rope_theta=cfg.rope_theta, max_positions=cfg.max_positions,
            init=normal_init(cfg.init_std))
        self.post_attn_norm = RMSNorm(cfg.hidden_size, eps=cfg.rms_norm_eps)
        if cfg.num_experts > 0:
            from hetu_tpu.nn.moe import MoEMLP
            gkw = {"num_groups": cfg.moe_num_groups} \
                if cfg.moe_gate == "sam" and cfg.moe_num_groups else None
            self.mlp = MoEMLP(cfg.hidden_size, cfg.intermediate_size,
                              cfg.num_experts, k=cfg.moe_top_k,
                              capacity_factor=cfg.moe_capacity_factor,
                              gated=True, gate_type=cfg.moe_gate,
                              gate_kwargs=gkw)
            self.returns_aux = True
        else:
            self.mlp = ParallelMLP(cfg.hidden_size, cfg.intermediate_size,
                                   bias=False, gated=True)
        self.resid_pdrop = cfg.resid_pdrop
        self.attn_pdrop = cfg.attn_pdrop

    def __call__(self, params, x, *, positions=None, segment_ids=None,
                 attn_impl="auto", kv_cache=None, slot_mask=None,
                 block_tables=None, row_mask=None, attn_kernel="reference",
                 pack=None, w8a8=None, w8a8_wq=None, lora=None,
                 dropout_key=None, return_kv=False):
        if kv_cache is not None:
            a, new_cache = self.attn(params["attn"],
                                     self.input_norm(
                                         params["input_norm"], x),
                                     positions=positions,
                                     kv_cache=kv_cache,
                                     slot_mask=slot_mask,
                                     block_tables=block_tables,
                                     row_mask=row_mask,
                                     attn_kernel=attn_kernel,
                                     pack=pack, lora=lora)
            x = x + a
            mlp_in = self.post_attn_norm(params["post_attn_norm"], x)
            if self.returns_aux:
                # MoE decode: per-row top-k through gathered local-
                # expert einsums (MoEMLP.decode); aux is train-only.
                # W8A8 rides the same knobs as the dense FFN lane
                # (int8 expert gathers + einsums).
                h = self.mlp.decode(params["mlp"], mlp_in,
                                    w8a8=w8a8, wq=w8a8_wq)
            else:
                h = self.mlp(params["mlp"], mlp_in, w8a8=w8a8,
                             w8a8_wq=w8a8_wq, lora=lora)
            return x + h, new_cache
        ka = k1 = k2 = None
        if dropout_key is not None and self.attn_pdrop > 0:
            ka, k1, k2 = jax.random.split(dropout_key, 3)
        elif dropout_key is not None and self.resid_pdrop > 0:
            # 2-way split kept for attn_pdrop=0: resid-only configs must
            # reproduce their pre-attn-dropout mask streams across resume
            k1, k2 = jax.random.split(dropout_key)
        a = self.attn(params["attn"],
                      self.input_norm(params["input_norm"], x),
                      positions=positions, segment_ids=segment_ids,
                      attn_impl=attn_impl,
                      dropout_rate=self.attn_pdrop, dropout_key=ka,
                      return_kv=return_kv)
        kv = None
        if return_kv:
            a, kv = a
        x = x + dropout(a, self.resid_pdrop, k1)
        h = self.mlp(params["mlp"],
                     self.post_attn_norm(params["post_attn_norm"], x))
        if self.returns_aux:
            h, aux = h
            out = (act_constrain(
                x + dropout(h, self.resid_pdrop, k2), "tokens"), aux)
        else:
            out = act_constrain(x + dropout(h, self.resid_pdrop, k2),
                                "tokens")
        return (out, kv) if return_kv else out


class LlamaLMHeadModel(Module):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        self.wte = VocabParallelEmbedding(cfg.vocab_size, cfg.hidden_size,
                                          init=normal_init(cfg.init_std))
        self.blocks = StackedBlocks(lambda: LlamaBlock(cfg), cfg.num_layers)
        self.final_norm = RMSNorm(cfg.hidden_size, eps=cfg.rms_norm_eps)
        if not cfg.tie_embeddings:
            self.lm_head = ColumnParallelLinear(
                cfg.hidden_size, cfg.vocab_size, bias=False,
                init=normal_init(cfg.init_std), axis="vocab",
                out_kind="logits")

    def _head_weight(self, params):
        """(V, E) head weight — tied wte or transposed lm_head kernel."""
        if self.cfg.tie_embeddings:
            return params["wte"]["weight"]
        return params["lm_head"]["weight"].T

    def embed(self, params, input_ids, *, positions=None):
        del positions  # rotary positions are applied inside the blocks
        h = self.wte(params["wte"], input_ids)
        return act_constrain(h, "tokens")

    def head_loss(self, params, h, labels, *, ignore_index: int = -100):
        """Final norm + (vocab-parallel) LM loss on *pre-norm* backbone
        output."""
        h = self.final_norm(params["final_norm"], h)
        return vocab_parallel_lm_loss(h, self._head_weight(params), labels,
                                      ignore_index=ignore_index)

    def backbone(self, params, input_ids, *, positions=None,
                 segment_ids=None, attn_impl="auto", remat="none",
                 remat_mask=None, unroll=False, dropout_key=None):
        """embed + blocks, WITHOUT the final norm (head_loss applies it).
        Returns ``(h, aux)`` — aux is 0 for dense models."""
        h = self.embed(params, input_ids)
        out = self.blocks(params["blocks"], h, remat=remat,
                          remat_mask=remat_mask, unroll=unroll,
                          positions=positions, segment_ids=segment_ids,
                          attn_impl=attn_impl, dropout_key=dropout_key)
        if self.blocks.returns_aux:
            return out
        return out, jnp.zeros([], jnp.float32)

    def hidden_norm(self, params, h):
        return self.final_norm(params["final_norm"], h)

    def hidden_states(self, params, input_ids, **kwargs):
        h, _ = self.backbone(params, input_ids, **kwargs)
        return self.hidden_norm(params, h)

    def __call__(self, params, input_ids, **kwargs):
        h = self.hidden_states(params, input_ids, **kwargs)
        w = self._head_weight(params)
        logits = jnp.einsum("bse,ve->bsv", h.astype(jnp.float32),
                            w.astype(jnp.float32))
        return act_constrain(logits, "logits")

    def loss(self, params, input_ids, labels, *, ignore_index: int = -100,
             **kwargs):
        h, aux = self.backbone(params, input_ids, **kwargs)
        lm = self.head_loss(params, h, labels, ignore_index=ignore_index)
        return lm + self.cfg.moe_aux_coef * aux
