"""BERT-family bidirectional encoder with MLM head.

Parity target: the reference's BERT model exercised by
``tests/hetu_bert.py`` (v1 model zoo breadth). TP-ready like GPT/Llama:
every layer declares logical axes, the MLM loss runs vocab-parallel under
an active tp ActivationSharding, and the model follows the same
embed/blocks/head protocol so all strategy machinery (DP/TP/PP and the
pipeline executor) applies unchanged — the only structural differences
from GPT are bidirectional attention and token-type embeddings.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from hetu_tpu.nn.layers import Embedding, LayerNorm
from hetu_tpu.nn.module import Module, normal_init
from hetu_tpu.nn.parallel import (
    ParallelAttention, ParallelMLP, StackedBlocks, VocabParallelEmbedding,
)
from hetu_tpu.ops.dropout import dropout
from hetu_tpu.ops.losses import vocab_parallel_lm_loss
from hetu_tpu.parallel.sharding import act_constrain


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    max_positions: int = 512
    type_vocab_size: int = 2
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_ratio: int = 4
    layer_norm_eps: float = 1e-12
    init_std: float = 0.02
    hidden_pdrop: float = 0.0   # BERT-standard is 0.1; keys come from
                                # the train step, eval never drops

    @classmethod
    def base(cls):
        return cls()

    @classmethod
    def tiny(cls):
        return cls(vocab_size=256, max_positions=128, hidden_size=64,
                   num_layers=2, num_heads=4)


class BertBlock(Module):
    """Post-LN encoder block (original BERT ordering)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.attn = ParallelAttention(
            cfg.hidden_size, cfg.num_heads, bias=True, causal=False,
            use_rope=False, init=normal_init(cfg.init_std))
        self.ln_attn = LayerNorm(cfg.hidden_size, eps=cfg.layer_norm_eps)
        self.mlp = ParallelMLP(cfg.hidden_size,
                               cfg.mlp_ratio * cfg.hidden_size,
                               bias=True, gated=False)
        self.ln_mlp = LayerNorm(cfg.hidden_size, eps=cfg.layer_norm_eps)
        self.hidden_pdrop = cfg.hidden_pdrop

    def __call__(self, params, x, *, positions=None, segment_ids=None,
                 attn_impl="auto", dropout_key=None):
        k1 = k2 = None
        if dropout_key is not None and self.hidden_pdrop > 0:
            k1, k2 = jax.random.split(dropout_key)
        a = self.attn(params["attn"], x, segment_ids=segment_ids,
                      attn_impl=attn_impl)
        x = self.ln_attn(params["ln_attn"],
                         x + dropout(a, self.hidden_pdrop, k1))
        h = self.mlp(params["mlp"], x)
        return act_constrain(
            self.ln_mlp(params["ln_mlp"],
                        x + dropout(h, self.hidden_pdrop, k2)), "tokens")


class BertModel(Module):
    """Encoder backbone + tied-embedding MLM head.

    ``segment_ids`` plays double duty as in packed LM training: attention
    is restricted to equal ids (which for BERT also serves the A/B
    sentence mask when type ids mirror segments).
    """

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        self.wte = VocabParallelEmbedding(cfg.vocab_size, cfg.hidden_size,
                                          init=normal_init(cfg.init_std))
        self.wpe = Embedding(cfg.max_positions, cfg.hidden_size,
                             init=normal_init(cfg.init_std))
        self.wtype = Embedding(cfg.type_vocab_size, cfg.hidden_size,
                               init=normal_init(cfg.init_std))
        self.ln_embed = LayerNorm(cfg.hidden_size, eps=cfg.layer_norm_eps)
        self.blocks = StackedBlocks(lambda: BertBlock(cfg), cfg.num_layers)
        self.ln_f = LayerNorm(cfg.hidden_size, eps=cfg.layer_norm_eps)

    @property
    def embed_dropout_rate(self) -> float:
        return self.cfg.hidden_pdrop

    def embed(self, params, input_ids, *, positions=None,
              token_type_ids=None):
        s = input_ids.shape[-1]
        if positions is None:
            positions = jnp.arange(s)[None, :]
        h = self.wte(params["wte"], input_ids) \
            + self.wpe(params["wpe"], positions)
        if token_type_ids is not None:
            h = h + self.wtype(params["wtype"], token_type_ids)
        return act_constrain(self.ln_embed(params["ln_embed"], h),
                             "tokens")

    def head_loss(self, params, h, labels, *, ignore_index: int = -100):
        h = self.ln_f(params["ln_f"], h)
        return vocab_parallel_lm_loss(h, params["wte"]["weight"], labels,
                                      ignore_index=ignore_index)

    def backbone(self, params, input_ids, *, positions=None,
                 segment_ids=None, token_type_ids=None,
                 attn_impl="auto", remat="none", remat_mask=None,
                 unroll=False, dropout_key=None):
        k_embd = k_blocks = None
        if dropout_key is not None:
            k_embd, k_blocks = jax.random.split(dropout_key)
        h = self.embed(params, input_ids, positions=positions,
                       token_type_ids=token_type_ids)
        h = dropout(h, self.cfg.hidden_pdrop, k_embd)
        h = self.blocks(params["blocks"], h, remat=remat,
                        remat_mask=remat_mask, unroll=unroll,
                        segment_ids=segment_ids, attn_impl=attn_impl,
                        dropout_key=k_blocks)
        return h, jnp.zeros([], jnp.float32)

    def hidden_states(self, params, input_ids, **kw):
        h, _ = self.backbone(params, input_ids, **kw)
        return self.ln_f(params["ln_f"], h)

    def __call__(self, params, input_ids, **kw):
        h = self.hidden_states(params, input_ids, **kw)
        w = params["wte"]["weight"]
        logits = jnp.einsum("bse,ve->bsv", h.astype(jnp.float32),
                            w.astype(jnp.float32))
        return act_constrain(logits, "logits")

    def loss(self, params, input_ids, labels, *, ignore_index: int = -100,
             **kw):
        """Masked-LM loss: ``labels`` = original ids at masked positions,
        ``ignore_index`` elsewhere."""
        h, _ = self.backbone(params, input_ids, **kw)
        return self.head_loss(params, h, labels,
                              ignore_index=ignore_index)


def mlm_mask(rng, input_ids, *, mask_token_id: int, vocab_size: int,
             mask_prob: float = 0.15, ignore_index: int = -100):
    """Standard 80/10/10 BERT masking. Returns (masked_ids, labels)."""
    import numpy as np
    ids = np.asarray(input_ids)
    r = rng.random(ids.shape)
    selected = r < mask_prob
    labels = np.where(selected, ids, ignore_index)
    out = ids.copy()
    sub = rng.random(ids.shape)
    out[selected & (sub < 0.8)] = mask_token_id
    rand = (sub >= 0.8) & (sub < 0.9) & selected
    out[rand] = rng.integers(0, vocab_size, size=int(rand.sum()))
    return out, labels
