"""Autoregressive generation with KV caches.

The reference's inference path appends KV via a dynamic-concat op
(``hetu/graph/ops`` dynamic concat; ``NDArrayMeta`` deprecated
dynamic_shape was for padded inference). TPU-native: fixed-capacity KV
buffers + ``dynamic_update_slice`` (static shapes for jit), prefill in one
pass, then a ``lax.scan`` over decode steps with greedy / temperature /
top-k / nucleus (top-p) sampling.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


class PromptTooLongError(ValueError):
    """A prompt (plus its decode budget) exceeds a hard length limit.

    Structured so callers (the serving scheduler's admission gate,
    :func:`generate`) can report WHICH limit was hit and what would
    lift it, instead of a bare refusal: ``prompt_len`` + ``max_tokens``
    against ``limit`` (the per-slot / cache budget) and — where a
    serving CP-prefill lane exists — ``cp_limit`` (its larger budget).
    """

    def __init__(self, *, prompt_len: int, max_tokens: int, limit: int,
                 cp_limit: Optional[int] = None, source: str = "decode",
                 hint: Optional[str] = None):
        self.prompt_len = int(prompt_len)
        self.max_tokens = int(max_tokens)
        self.limit = int(limit)
        self.cp_limit = int(cp_limit) if cp_limit is not None else None
        self.source = source
        worst = self.prompt_len + self.max_tokens
        msg = (f"prompt of {self.prompt_len} tokens + {self.max_tokens} "
               f"decode tokens = {worst} exceeds the {self.limit}-token "
               f"{source} budget")
        if self.cp_limit is not None:
            msg += (f" and the {self.cp_limit}-token CP-prefill lane "
                    f"budget")
        if hint:
            msg += f" ({hint})"
        super().__init__(msg)


def _head_weight(model, params):
    if hasattr(model, "_head_weight"):
        return model._head_weight(params)
    return params["wte"]["weight"]


def init_kv_caches(model, batch: int, max_len: int, dtype=jnp.float32):
    """(k, v) buffers stacked over layers: (L, b, max_len, hkv, d).

    ``dtype=jnp.int8`` builds the QUANTIZED cache — (k int8, k scales,
    v int8, v scales) with per-(position, head) fp32 scales — the
    reference's inference-side weight/state compression applied to the
    decode bottleneck (the per-step cache read is pure HBM bandwidth;
    int8 halves it vs bf16 and quarters it vs fp32)."""
    attn = model.blocks.block.attn
    L = model.blocks.num_layers
    shape = (L, batch, max_len, attn.num_kv_heads, attn.head_dim)
    if dtype == jnp.int8:
        sshape = shape[:-1] + (1,)
        return (jnp.zeros(shape, jnp.int8), jnp.zeros(sshape, jnp.float32),
                jnp.zeros(shape, jnp.int8), jnp.zeros(sshape, jnp.float32))
    return (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))


def decode(model, params, input_ids, positions, caches, *,
           slot_mask=None, block_tables=None, row_mask=None,
           attn_kernel: str = "reference", w8a8_mask=None,
           w8a8_wq=None, lora=None):
    """Run a chunk through the model in decode mode.

    ``positions`` (b, s) absolute positions. Without ``slot_mask`` they
    must be identical across the batch (batched decode, one shared write
    index). With ``slot_mask`` (b,) bool every row decodes at ITS OWN
    ``positions[r, 0]`` — the serving engine's slot-pooled path — and
    masked-off rows leave their KV rows untouched. ``block_tables``
    (b, W) switches the caches to the block-paged arena layout
    (``(L, n_blocks, block_size, hkv, d)`` leaves; see
    ``ParallelAttention._decode``). ``row_mask`` (b, s) bool gates KV
    writes per CELL within a row (paged mode only) — the speculative
    verify lane's guard against draft rows beyond a slot's allocated
    blocks. ``attn_kernel`` ("reference" | "paged") picks the paged
    arena's attention read path (Pallas kernel vs XLA gather — see
    ``ops.paged_pallas``); ``w8a8_mask`` ((layers,) bool) flips decode
    FFNs to the W8A8 int8 lane per layer, and ``w8a8_wq`` (a stacked
    ``prequantize`` tree) feeds that lane pre-quantized int8 weights
    so the per-step weight quantize disappears. ``lora`` (the
    multi-tenant adapter arena — ``{"ids": (b, s) pages, "pages":
    stacked (L, P, ...) A/B tree}``) adds the per-token batched
    multi-adapter BGMV deltas (``nn.parallel.lora_apply``); None is
    the historical base-only lane. Returns (logits
    (b, s, V), new caches)."""
    h = model.embed(params, input_ids, positions=positions)
    h, caches = model.blocks.decode(params["blocks"], h, caches,
                                    positions=positions,
                                    slot_mask=slot_mask,
                                    block_tables=block_tables,
                                    row_mask=row_mask,
                                    attn_kernel=attn_kernel,
                                    w8a8_mask=w8a8_mask,
                                    w8a8_wq=w8a8_wq, lora=lora)
    h = model.hidden_norm(params, h)
    w = _head_weight(model, params)
    logits = jnp.einsum("bse,ve->bsv", h.astype(jnp.float32),
                        w.astype(jnp.float32))
    return logits, caches


def _sample(logits, *, temperature: float, top_k: int, top_p: float, rng):
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    if top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if 0.0 < top_p < 1.0:
        # nucleus: keep the smallest prefix of the sorted distribution
        # whose mass exceeds top_p (the top token always survives)
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep = cum - probs < top_p           # mass *before* this token
        cutoff = jnp.min(jnp.where(keep, sorted_logits, jnp.inf), axis=-1,
                         keepdims=True)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1)


def generate(model, params, input_ids, *, max_new_tokens: int,
             max_len: Optional[int] = None, temperature: float = 0.0,
             top_k: int = 0, top_p: float = 0.0,
             rng: Optional[jax.Array] = None,
             eos_id: Optional[int] = None, pad_id: Optional[int] = None,
             prompt_lens=None, cache_dtype=jnp.float32):
    """Generate ``max_new_tokens`` continuations for a (b, s) prompt.

    Returns (b, s + max_new_tokens) token ids; positions after an EOS
    are filled with ``pad_id`` when given, else with ``eos_id`` (the
    historical behavior — callers that need to tell a real EOS from
    fill must pass a distinct ``pad_id``). jit-able end to end.

    ``prompt_lens`` (b,) enables RAGGED prompts: row r's real prompt is
    ``input_ids[r, :prompt_lens[r]]`` (right-padded to s). Prefill then
    samples at each row's LAST REAL position instead of column s-1 (a
    padded batch otherwise samples at a pad position), and decode
    writes row r's tokens at positions ``prompt_lens[r] + t`` with a
    per-row causal mask, so stale pad KV rows are never attended.
    Generated tokens still occupy the trailing ``max_new_tokens``
    columns of the output for every row. When omitted, every prompt is
    assumed to span the full s columns (the historical batched path,
    bit-for-bit unchanged).
    """
    b, s = input_ids.shape
    total = max_len or (s + max_new_tokens)
    # fail with a structured error instead of the cryptic downstream
    # gather/embed failure: either the caller's own cache budget
    # (max_len) or the model's positional capacity bounds the request
    if s + max_new_tokens > total:
        raise PromptTooLongError(
            prompt_len=s, max_tokens=max_new_tokens, limit=total,
            source="generate KV-cache (max_len)",
            hint="raise max_len or trim the prompt")
    max_positions = getattr(getattr(model, "cfg", None),
                            "max_positions", None)
    if max_positions is not None and total > max_positions:
        raise PromptTooLongError(
            prompt_len=s, max_tokens=max_new_tokens,
            limit=int(max_positions),
            source="model max_positions",
            hint="the model cannot address positions past its trained "
                 "context window")
    caches = init_kv_caches(model, b, total, cache_dtype)
    rng = rng if rng is not None else jax.random.key(0)
    ragged = prompt_lens is not None
    fill_id = pad_id if pad_id is not None else eos_id

    # prefill the prompt in one pass
    prefill_pos = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
    logits, caches = decode(model, params, input_ids, prefill_pos, caches)
    rng, sub = jax.random.split(rng)
    if ragged:
        plens = jnp.asarray(prompt_lens, jnp.int32)
        # pad-aware gather: sample at each row's last REAL position
        last_logits = jnp.take_along_axis(
            logits, (plens - 1)[:, None, None], axis=1)[:, 0]
        pos0 = plens                       # next write index per row
    else:
        last_logits = logits[:, -1]
        pos0 = None
    tok = _sample(last_logits, temperature=temperature, top_k=top_k,
                  top_p=top_p, rng=sub)
    done = jnp.zeros((b,), bool) if eos_id is None else (tok == eos_id)

    def step(carry, i):
        caches, tok, done, rng = carry
        if ragged:
            pos = (pos0 + i)[:, None]
            logits, caches = decode(model, params, tok[:, None], pos,
                                    caches,
                                    slot_mask=jnp.ones((b,), bool))
        else:
            pos = jnp.broadcast_to((s + i)[None, None], (b, 1))
            logits, caches = decode(model, params, tok[:, None], pos,
                                    caches)
        rng, sub = jax.random.split(rng)
        nxt = _sample(logits[:, -1], temperature=temperature,
                      top_k=top_k, top_p=top_p, rng=sub)
        if eos_id is not None:
            raw = nxt
            nxt = jnp.where(done, fill_id, raw)
            done = done | (raw == eos_id)
        return (caches, nxt, done, rng), tok

    (_, last, _, _), toks = jax.lax.scan(
        step, (caches, tok, done, rng), jnp.arange(max_new_tokens - 1))
    out = jnp.concatenate(
        [input_ids, jnp.moveaxis(toks, 0, 1), last[:, None]], axis=1)
    return out
