"""Small vision models: MLP and CNN classifiers.

Parity target: the reference's model smoke tests
(``tests/test_cifar10.py`` — CNN/MLP trained on CIFAR-10 against a torch
oracle; BASELINE config 1). These are the single-device sanity models;
they reuse the same Module system so dp/fsdp strategies apply if wanted.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from hetu_tpu.nn.layers import Conv2D, Linear, MLP, max_pool2d
from hetu_tpu.nn.module import Module
from hetu_tpu.ops.losses import cross_entropy_mean


@dataclasses.dataclass(frozen=True)
class CNNConfig:
    in_channels: int = 3
    num_classes: int = 10
    channels: tuple = (32, 64)
    hidden: int = 256
    image_size: int = 32


class SimpleCNN(Module):
    """conv-pool x N → MLP head (the reference's CIFAR CNN shape)."""

    def __init__(self, cfg: CNNConfig = CNNConfig()):
        super().__init__()
        self.cfg = cfg
        c_in = cfg.in_channels
        for i, c in enumerate(cfg.channels):
            setattr(self, f"conv{i}", Conv2D(c_in, c, 3))
            c_in = c
        side = cfg.image_size // (2 ** len(cfg.channels))
        self.fc = Linear(c_in * side * side, cfg.hidden)
        self.head = Linear(cfg.hidden, cfg.num_classes)

    def __call__(self, params, x):
        """x (B, H, W, C) → logits (B, num_classes)."""
        for i in range(len(self.cfg.channels)):
            conv = getattr(self, f"conv{i}")
            x = jnp.maximum(conv(params[f"conv{i}"], x), 0.0)
            x = max_pool2d(x)
        x = x.reshape(x.shape[0], -1)
        h = jnp.maximum(self.fc(params["fc"], x), 0.0)
        return self.head(params["head"], h)

    def loss(self, params, x, labels):
        return cross_entropy_mean(self(params, x), labels)


@dataclasses.dataclass(frozen=True)
class RNNConfig:
    in_dim: int = 28          # features per scan step (an MNIST row)
    hidden: int = 128
    num_classes: int = 10
    seq_len: int = 28


class SimpleRNN(Module):
    """Elman-style row RNN (the reference's ``tests/test_rnn.py`` model):
    ``h_t = relu(W2·[W1·x_t ; h_{t-1}])``, classify from the final
    hidden state. TPU-native form: the time loop is a ``lax.scan`` (one
    compiled step, no Python unroll)."""

    def __init__(self, cfg: RNNConfig = RNNConfig()):
        super().__init__()
        self.cfg = cfg
        self.linear1 = Linear(cfg.in_dim, cfg.hidden)
        self.linear2 = Linear(cfg.hidden * 2, cfg.hidden)
        self.head = Linear(cfg.hidden, cfg.num_classes)

    def __call__(self, params, x):
        """x (B, seq_len, in_dim) → logits (B, num_classes)."""
        if x.shape[1] != self.cfg.seq_len:
            raise ValueError(f"expected seq_len {self.cfg.seq_len}, "
                             f"got input with {x.shape[1]} steps")

        def cell(h, x_t):
            z = self.linear1(params["linear1"], x_t)
            h = jnp.maximum(self.linear2(
                params["linear2"], jnp.concatenate([z, h], axis=-1)), 0.0)
            return h, None

        # carry dtype must equal the cell's OUTPUT dtype (the policy
        # compute dtype under autocast) — scan requires identical carry
        # avals in and out
        h0 = jnp.zeros((x.shape[0], self.cfg.hidden),
                       self.compute_dtype())
        h, _ = jax.lax.scan(cell, h0, jnp.swapaxes(x, 0, 1))
        return self.head(params["head"], h)

    def loss(self, params, x, labels):
        return cross_entropy_mean(self(params, x), labels)


class MLPClassifier(Module):
    def __init__(self, in_features: int, hidden: int, num_classes: int):
        super().__init__()
        self.body = MLP(in_features, hidden)
        self.head = Linear(in_features, num_classes)

    def __call__(self, params, x):
        return self.head(params["head"], self.body(params["body"], x))

    def loss(self, params, x, labels):
        return cross_entropy_mean(self(params, x), labels)
