"""Small vision models: MLP and CNN classifiers.

Parity target: the reference's model smoke tests
(``tests/test_cifar10.py`` — CNN/MLP trained on CIFAR-10 against a torch
oracle; BASELINE config 1). These are the single-device sanity models;
they reuse the same Module system so dp/fsdp strategies apply if wanted.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from hetu_tpu.nn.layers import Conv2D, Linear, MLP, max_pool2d
from hetu_tpu.nn.module import Module
from hetu_tpu.ops.losses import cross_entropy_mean


@dataclasses.dataclass(frozen=True)
class CNNConfig:
    in_channels: int = 3
    num_classes: int = 10
    channels: tuple = (32, 64)
    hidden: int = 256
    image_size: int = 32


class SimpleCNN(Module):
    """conv-pool x N → MLP head (the reference's CIFAR CNN shape)."""

    def __init__(self, cfg: CNNConfig = CNNConfig()):
        super().__init__()
        self.cfg = cfg
        c_in = cfg.in_channels
        for i, c in enumerate(cfg.channels):
            setattr(self, f"conv{i}", Conv2D(c_in, c, 3))
            c_in = c
        side = cfg.image_size // (2 ** len(cfg.channels))
        self.fc = Linear(c_in * side * side, cfg.hidden)
        self.head = Linear(cfg.hidden, cfg.num_classes)

    def __call__(self, params, x):
        """x (B, H, W, C) → logits (B, num_classes)."""
        for i in range(len(self.cfg.channels)):
            conv = getattr(self, f"conv{i}")
            x = jnp.maximum(conv(params[f"conv{i}"], x), 0.0)
            x = max_pool2d(x)
        x = x.reshape(x.shape[0], -1)
        h = jnp.maximum(self.fc(params["fc"], x), 0.0)
        return self.head(params["head"], h)

    def loss(self, params, x, labels):
        return cross_entropy_mean(self(params, x), labels)


class MLPClassifier(Module):
    def __init__(self, in_features: int, hidden: int, num_classes: int):
        super().__init__()
        self.body = MLP(in_features, hidden)
        self.head = Linear(in_features, num_classes)

    def __call__(self, params, x):
        return self.head(params["head"], self.body(params["body"], x))

    def loss(self, params, x, labels):
        return cross_entropy_mean(self(params, x), labels)
