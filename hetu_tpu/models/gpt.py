"""GPT-2 family.

Parity target: the reference's GPT model (``python/hetu/models/gpt/``,
driven by ``tests/ci_test/train_hetu_gpt_ds_parallel.py``): learned position
embeddings, pre-LayerNorm blocks, GELU MLP, tied wte/lm_head. TP-ready out of
the box — every layer declares logical axes and the LM loss runs
vocab-parallel under ``shard_map`` when a tp>1 ActivationSharding context is
active.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from hetu_tpu.nn.layers import Embedding, LayerNorm
from hetu_tpu.nn.module import Module, normal_init
from hetu_tpu.nn.parallel import (
    ParallelAttention, ParallelMLP, StackedBlocks, VocabParallelEmbedding,
)
from hetu_tpu.ops.dropout import dropout
from hetu_tpu.ops.losses import vocab_parallel_lm_loss
from hetu_tpu.parallel.sharding import act_constrain


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50257
    max_positions: int = 1024
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_ratio: int = 4
    layer_norm_eps: float = 1e-5
    init_std: float = 0.02
    # dropout (reference: ``graph/ops/Dropout.*`` wired into its GPT
    # model; 0.0 default keeps pretrain benches deterministic — GPT-2's
    # original recipe uses 0.1). Applied via explicit PRNG keys threaded
    # by the train step; eval paths never drop.
    embd_pdrop: float = 0.0
    resid_pdrop: float = 0.0
    # dropout on attention probabilities (reference flash wrapper's
    # p_dropout, ``hetu/impl/kernel/FlashAttention.cu:1-50``); carried
    # by both attention paths — in-kernel counter-RNG masks on Pallas
    # (``ops/flash_pallas._dropout_keep``), jax.random on XLA
    attn_pdrop: float = 0.0
    # MoE (0 experts = dense; parity: HetuMoE GPT, BASELINE config 4)
    num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_coef: float = 0.01
    # gate variant (reference gate zoo ``hetu/v1/python/hetu/layers/``):
    # "topk" | "ktop1" | "sam" | "balance"
    moe_gate: str = "topk"
    # SAM gate: expert groups (should equal the ep degree so group-local
    # routing maps to device-local dispatch); 0 = gate default
    moe_num_groups: int = 0

    @classmethod
    def small(cls):
        return cls()

    @classmethod
    def moe_8e(cls):
        """GPT-MoE 8-expert (BASELINE config 4)."""
        return cls(num_experts=8)

    @classmethod
    def tiny(cls):
        """Test-size config."""
        return cls(vocab_size=256, max_positions=128, hidden_size=64,
                   num_layers=2, num_heads=4)

    @classmethod
    def tiny_moe(cls, num_experts=4, **kw):
        return cls(vocab_size=256, max_positions=128, hidden_size=64,
                   num_layers=2, num_heads=4, num_experts=num_experts, **kw)


class GPTBlock(Module):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.ln_1 = LayerNorm(cfg.hidden_size, eps=cfg.layer_norm_eps)
        self.attn = ParallelAttention(
            cfg.hidden_size, cfg.num_heads, bias=True, causal=True,
            use_rope=False, init=normal_init(cfg.init_std))
        self.ln_2 = LayerNorm(cfg.hidden_size, eps=cfg.layer_norm_eps)
        self.resid_pdrop = cfg.resid_pdrop
        self.attn_pdrop = cfg.attn_pdrop
        if cfg.num_experts > 0:
            from hetu_tpu.nn.moe import MoEMLP
            gkw = {"num_groups": cfg.moe_num_groups} \
                if cfg.moe_gate == "sam" and cfg.moe_num_groups else None
            self.mlp = MoEMLP(cfg.hidden_size,
                              cfg.mlp_ratio * cfg.hidden_size,
                              cfg.num_experts, k=cfg.moe_top_k,
                              capacity_factor=cfg.moe_capacity_factor,
                              gate_type=cfg.moe_gate, gate_kwargs=gkw)
            self.returns_aux = True
        else:
            self.mlp = ParallelMLP(cfg.hidden_size,
                                   cfg.mlp_ratio * cfg.hidden_size,
                                   bias=True, gated=False)

    def __call__(self, params, x, *, positions=None, segment_ids=None,
                 attn_impl="auto", kv_cache=None, slot_mask=None,
                 block_tables=None, row_mask=None, attn_kernel="reference",
                 pack=None, w8a8=None, w8a8_wq=None, lora=None,
                 dropout_key=None, return_kv=False):
        if kv_cache is not None:
            a, new_cache = self.attn(params["attn"],
                                     self.ln_1(params["ln_1"], x),
                                     positions=positions,
                                     kv_cache=kv_cache,
                                     slot_mask=slot_mask,
                                     block_tables=block_tables,
                                     row_mask=row_mask,
                                     attn_kernel=attn_kernel,
                                     pack=pack, lora=lora)
            x = x + a
            mlp_in = self.ln_2(params["ln_2"], x)
            if self.returns_aux:
                # MoE decode: per-row top-k through the gathered
                # local-expert einsums (MoEMLP.decode — O(rows·k)
                # expert FFNs instead of the dense oracle's O(rows·E));
                # aux is train-only. One-shot generate and the serving
                # engine's fused step both land here, so their tokens
                # match by construction. W8A8 rides the same knobs as
                # the dense FFN lane (int8 expert gathers + einsums).
                h = self.mlp.decode(params["mlp"], mlp_in,
                                    w8a8=w8a8, wq=w8a8_wq)
            else:
                h = self.mlp(params["mlp"], mlp_in, w8a8=w8a8,
                             w8a8_wq=w8a8_wq, lora=lora)
            return x + h, new_cache
        # positions only matter for decode (GPT's learned position
        # embedding is applied in embed(), not per block)
        ka = k1 = k2 = None
        if dropout_key is not None and self.attn_pdrop > 0:
            ka, k1, k2 = jax.random.split(dropout_key, 3)
        elif dropout_key is not None and self.resid_pdrop > 0:
            # 2-way split kept for attn_pdrop=0: resid-only configs must
            # reproduce their pre-attn-dropout mask streams across resume
            k1, k2 = jax.random.split(dropout_key)
        a = self.attn(params["attn"], self.ln_1(params["ln_1"], x),
                      positions=positions,
                      segment_ids=segment_ids, attn_impl=attn_impl,
                      dropout_rate=self.attn_pdrop, dropout_key=ka,
                      return_kv=return_kv)
        kv = None
        if return_kv:
            a, kv = a
        x = x + dropout(a, self.resid_pdrop, k1)
        h = self.mlp(params["mlp"], self.ln_2(params["ln_2"], x))
        if self.returns_aux:
            h, aux = h
            out = (act_constrain(
                x + dropout(h, self.resid_pdrop, k2), "tokens"), aux)
        else:
            out = act_constrain(x + dropout(h, self.resid_pdrop, k2),
                                "tokens")
        return (out, kv) if return_kv else out


class GPTLMHeadModel(Module):
    """GPT-2 with tied-embedding LM head."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.wte = VocabParallelEmbedding(cfg.vocab_size, cfg.hidden_size,
                                          init=normal_init(cfg.init_std))
        self.wpe = Embedding(cfg.max_positions, cfg.hidden_size,
                             init=normal_init(cfg.init_std))
        self.blocks = StackedBlocks(lambda: GPTBlock(cfg), cfg.num_layers)
        self.ln_f = LayerNorm(cfg.hidden_size, eps=cfg.layer_norm_eps)

    @property
    def embed_dropout_rate(self) -> float:
        """Rate the backbone applies to the embedding output — consumed
        by executors that schedule embed themselves (pipeline)."""
        return self.cfg.embd_pdrop

    def embed(self, params, input_ids, *, positions=None):
        s = input_ids.shape[-1]
        if positions is None:
            positions = jnp.arange(s)[None, :]
        h = self.wte(params["wte"], input_ids) \
            + self.wpe(params["wpe"], positions)
        return act_constrain(h, "tokens")

    def head_loss(self, params, h, labels, *, ignore_index: int = -100):
        """Final norm + (vocab-parallel) LM loss on *pre-norm* backbone
        output."""
        h = self.ln_f(params["ln_f"], h)
        return vocab_parallel_lm_loss(h, params["wte"]["weight"], labels,
                                      ignore_index=ignore_index)

    def backbone(self, params, input_ids, *, positions=None,
                 segment_ids=None, attn_impl="auto", remat="none",
                 remat_mask=None, unroll=False, dropout_key=None):
        """embed + blocks, WITHOUT the final norm (head_loss applies it).
        Returns ``(h, aux)`` — aux is 0 for dense models, the accumulated
        MoE load-balance loss otherwise. ``dropout_key=None`` (the eval
        default) disables dropout regardless of config rates."""
        k_embd = k_blocks = None
        if dropout_key is not None:
            k_embd, k_blocks = jax.random.split(dropout_key)
        h = self.embed(params, input_ids, positions=positions)
        h = dropout(h, self.cfg.embd_pdrop, k_embd)
        out = self.blocks(params["blocks"], h, remat=remat,
                          remat_mask=remat_mask, unroll=unroll,
                          segment_ids=segment_ids, attn_impl=attn_impl,
                          dropout_key=k_blocks)
        if self.blocks.returns_aux:
            return out
        return out, jnp.zeros([], jnp.float32)

    def hidden_norm(self, params, h):
        return self.ln_f(params["ln_f"], h)

    def hidden_states(self, params, input_ids, **kwargs):
        h, _ = self.backbone(params, input_ids, **kwargs)
        return self.hidden_norm(params, h)

    def __call__(self, params, input_ids, **kwargs):
        """Full logits (inference / entry path)."""
        h = self.hidden_states(params, input_ids, **kwargs)
        w = params["wte"]["weight"]
        logits = jnp.einsum("bse,ve->bsv", h.astype(jnp.float32),
                            w.astype(jnp.float32))
        return act_constrain(logits, "logits")

    def loss(self, params, input_ids, labels, *, ignore_index: int = -100,
             **kwargs):
        """Mean LM loss (+ MoE aux); the head runs vocab-parallel when tp
        is active."""
        h, aux = self.backbone(params, input_ids, **kwargs)
        lm = self.head_loss(params, h, labels, ignore_index=ignore_index)
        return lm + self.cfg.moe_aux_coef * aux
