"""Model zoo: GPT-2 and Llama families.

Parity targets: ``python/hetu/models/gpt`` and
``python/hetu/models/llama/llama_model.py`` (LlamaModel :385,
LlamaLMHeadModel :446).
"""

from hetu_tpu.models.gpt import GPTConfig, GPTLMHeadModel
from hetu_tpu.models.llama import LlamaConfig, LlamaLMHeadModel
from hetu_tpu.models.bert import BertConfig, BertModel
from hetu_tpu.models.vision import (
    CNNConfig, MLPClassifier, RNNConfig, SimpleCNN, SimpleRNN,
)
from hetu_tpu.models.generation import generate, decode, init_kv_caches

__all__ = ["GPTConfig", "GPTLMHeadModel", "LlamaConfig", "BertConfig", "BertModel", "CNNConfig", "SimpleCNN", "MLPClassifier", "RNNConfig", "SimpleRNN", "LlamaLMHeadModel",
           "generate", "decode", "init_kv_caches"]
