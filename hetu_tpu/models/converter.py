"""HuggingFace checkpoint converters.

Parity target: ``python/hetu/models/utils/converter/convert_llama_hf_to_ht.py``
(+ the GPT analogue): map HF state dicts onto our param trees so users can
start from public checkpoints. Input is a ``{name: array}`` state dict
(e.g. ``{k: v.numpy() for k, v in torch_model.state_dict().items()}`` or a
loaded safetensors file) — no torch dependency here.

Layout notes:
- HF GPT-2 uses Conv1D weights already shaped (in, out) with a fused
  (E, 3E) c_attn — split into q/k/v.
- HF Llama uses torch Linear weights (out, in) — transposed on the way in.
- Per-layer tensors stack onto the leading ``layers`` dim of our
  StackedBlocks params.
"""

from __future__ import annotations

from typing import Mapping

import numpy as np

from hetu_tpu.models.gpt import GPTConfig, GPTLMHeadModel
from hetu_tpu.models.llama import LlamaConfig, LlamaLMHeadModel


def _stack(arrs):
    return np.stack([np.asarray(a) for a in arrs])


def convert_gpt2_from_hf(sd: Mapping[str, np.ndarray],
                         cfg: GPTConfig) -> dict:
    """HF ``GPT2LMHeadModel`` state dict → our GPT param tree."""
    g = {k[len("transformer."):] if k.startswith("transformer.") else k: v
         for k, v in sd.items()}
    L, E = cfg.num_layers, cfg.hidden_size

    def layer(i, name):
        return np.asarray(g[f"h.{i}.{name}"])

    qs, ks, vs, qb, kb, vb = [], [], [], [], [], []
    for i in range(L):
        w = layer(i, "attn.c_attn.weight")       # (E, 3E), Conv1D layout
        b = layer(i, "attn.c_attn.bias")
        qs.append(w[:, :E]); ks.append(w[:, E:2 * E]); vs.append(w[:, 2 * E:])
        qb.append(b[:E]); kb.append(b[E:2 * E]); vb.append(b[2 * E:])

    blocks = {
        "ln_1": {"scale": _stack([layer(i, "ln_1.weight")
                                  for i in range(L)]),
                 "bias": _stack([layer(i, "ln_1.bias")
                                 for i in range(L)])},
        "ln_2": {"scale": _stack([layer(i, "ln_2.weight")
                                  for i in range(L)]),
                 "bias": _stack([layer(i, "ln_2.bias")
                                 for i in range(L)])},
        "attn": {
            "q_proj": {"weight": _stack(qs), "bias": _stack(qb)},
            "k_proj": {"weight": _stack(ks), "bias": _stack(kb)},
            "v_proj": {"weight": _stack(vs), "bias": _stack(vb)},
            "out_proj": {
                "weight": _stack([layer(i, "attn.c_proj.weight")
                                  for i in range(L)]),
                "bias": _stack([layer(i, "attn.c_proj.bias")
                                for i in range(L)])},
        },
        "mlp": {
            "fc_in": {"weight": _stack([layer(i, "mlp.c_fc.weight")
                                        for i in range(L)]),
                      "bias": _stack([layer(i, "mlp.c_fc.bias")
                                      for i in range(L)])},
            "fc_out": {"weight": _stack([layer(i, "mlp.c_proj.weight")
                                         for i in range(L)]),
                       "bias": _stack([layer(i, "mlp.c_proj.bias")
                                       for i in range(L)])},
        },
    }
    return {
        "wte": {"weight": np.asarray(g["wte.weight"])},
        "wpe": {"weight": np.asarray(g["wpe.weight"])},
        "blocks": blocks,
        "ln_f": {"scale": np.asarray(g["ln_f.weight"]),
                 "bias": np.asarray(g["ln_f.bias"])},
    }


def convert_llama_from_hf(sd: Mapping[str, np.ndarray],
                          cfg: LlamaConfig) -> dict:
    """HF ``LlamaForCausalLM`` state dict → our Llama param tree."""
    g = {k[len("model."):] if k.startswith("model.") else k: v
         for k, v in sd.items()}
    L = cfg.num_layers

    def lin(i, name):  # torch Linear: (out, in) → (in, out)
        return np.asarray(g[f"layers.{i}.{name}.weight"]).T

    blocks = {
        "input_norm": {"scale": _stack(
            [g[f"layers.{i}.input_layernorm.weight"] for i in range(L)])},
        "post_attn_norm": {"scale": _stack(
            [g[f"layers.{i}.post_attention_layernorm.weight"]
             for i in range(L)])},
        "attn": {
            "q_proj": {"weight": _stack(
                [lin(i, "self_attn.q_proj") for i in range(L)])},
            "k_proj": {"weight": _stack(
                [lin(i, "self_attn.k_proj") for i in range(L)])},
            "v_proj": {"weight": _stack(
                [lin(i, "self_attn.v_proj") for i in range(L)])},
            "out_proj": {"weight": _stack(
                [lin(i, "self_attn.o_proj") for i in range(L)])},
        },
        "mlp": {
            "gate_proj": {"weight": _stack(
                [lin(i, "mlp.gate_proj") for i in range(L)])},
            "up_proj": {"weight": _stack(
                [lin(i, "mlp.up_proj") for i in range(L)])},
            "fc_out": {"weight": _stack(
                [lin(i, "mlp.down_proj") for i in range(L)])},
        },
    }
    out = {
        "wte": {"weight": np.asarray(g["embed_tokens.weight"])},
        "blocks": blocks,
        "final_norm": {"scale": np.asarray(g["norm.weight"])},
    }
    if not cfg.tie_embeddings:
        out["lm_head"] = {"weight": np.asarray(sd["lm_head.weight"]).T}
    return out
