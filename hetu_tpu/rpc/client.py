"""Coordinator client (line protocol over TCP).

Reference: ``hetu/impl/communication/rpc_client.cc`` (Connect/GetRank/
KV/Barrier/HeartBeat) + the Python KV-store client
(``rpc/kv_store/client.py``).
"""

from __future__ import annotations

import json
import os
import socket
import urllib.parse
from typing import Any, Optional


class CoordinatorClient:
    def __init__(self, port: int, host: str = "127.0.0.1",
                 timeout: float = 30.0,
                 token: Optional[str] = None):
        self._sock = socket.create_connection((host, port),
                                              timeout=timeout)
        self._buf = b""
        # auth-enabled coordinators require AUTH first on every
        # connection; workers inherit the pool's token via env
        token = token if token is not None \
            else os.environ.get("HETU_COORD_TOKEN")
        if token:
            resp = self._cmd(f"AUTH {token}")
            if resp != "OK":
                raise ConnectionError(f"coordinator auth failed: {resp}")

    def _cmd(self, line: str) -> str:
        self._sock.sendall(line.encode() + b"\n")
        while b"\n" not in self._buf:
            chunk = self._sock.recv(4096)
            if not chunk:
                raise ConnectionError("coordinator closed connection")
            self._buf += chunk
        resp, self._buf = self._buf.split(b"\n", 1)
        return resp.decode()

    # -- rank / membership --------------------------------------------------
    def rank(self, name: str) -> int:
        resp = self._cmd(f"RANK {name}")
        return int(resp.split()[1])

    def heartbeat(self, name: str):
        assert self._cmd(f"BEAT {name}") == "OK"

    def status(self, timeout_ms: int = 5000) -> tuple[list[str], list[str]]:
        resp = self._cmd(f"STATUS {timeout_ms}")
        # "ALIVE a,b DEAD c"
        parts = resp.split()
        alive = parts[1].split(",") if len(parts) > 1 and parts[1] else []
        dead_idx = parts.index("DEAD")
        dead = parts[dead_idx + 1].split(",") \
            if len(parts) > dead_idx + 1 and parts[dead_idx + 1] else []
        return [a for a in alive if a], [d for d in dead if d]

    # -- KV (typed, like the reference's double/int/string/json) ------------
    def put(self, key: str, value: Any):
        enc = urllib.parse.quote(json.dumps(value), safe="")
        assert self._cmd(f"SET {key} {enc}") == "OK"

    def get(self, key: str, default: Any = None) -> Any:
        resp = self._cmd(f"GET {key}")
        if resp == "NONE":
            return default
        return json.loads(urllib.parse.unquote(resp.split(" ", 1)[1]))

    # -- synchronization ----------------------------------------------------
    def barrier(self, name: str, n: int, who: str):
        """Blocks until ``n`` distinct participants arrive."""
        assert self._cmd(f"BARRIER {name} {n} {who}") == "OK"

    # -- serving plane (hetu_tpu/serving — coordinator with an engine) ------
    def _serving_payload(self, prompt, **sampling) -> str:
        obj = {"prompt": [int(t) for t in prompt], **sampling}
        return urllib.parse.quote(
            json.dumps(obj, separators=(",", ":")), safe="")

    def serving_submit(self, prompt, **sampling) -> int:
        """Queue a generation request; returns its id (FCFS)."""
        resp = self._cmd(f"SUBMIT {self._serving_payload(prompt, **sampling)}")
        if not resp.startswith("ID "):
            raise RuntimeError(f"serving submit failed: {resp}")
        return int(resp.split()[1])

    def serving_result(self, req_id: int,
                       timeout_ms: int = 0) -> Optional[dict]:
        """Poll a queued request: dict result, or None while pending."""
        resp = self._cmd(f"RESULT {req_id} {timeout_ms}")
        if resp == "PEND":
            return None
        if not resp.startswith("VAL "):
            raise RuntimeError(f"serving result failed: {resp}")
        return json.loads(urllib.parse.unquote(resp.split(" ", 1)[1]))

    def serving_generate(self, prompt, **sampling) -> dict:
        """Blocking generate over the line protocol (engine loop must
        be running server-side, e.g. ``ServingServer.start()``)."""
        resp = self._cmd(
            f"GENERATE {self._serving_payload(prompt, **sampling)}")
        if not resp.startswith("VAL "):
            raise RuntimeError(f"serving generate failed: {resp}")
        return json.loads(urllib.parse.unquote(resp.split(" ", 1)[1]))

    # -- live observability (HEALTHZ / METRICS verbs) -----------------------
    def healthz(self) -> dict:
        """Live health document: overall status, watchdog trips, SLO
        alerting state, serving queue/occupancy (telemetry.health_status
        evaluated on the coordinator process)."""
        resp = self._cmd("HEALTHZ")
        if not resp.startswith("VAL "):
            raise RuntimeError(f"healthz failed: {resp}")
        return json.loads(urllib.parse.unquote(resp.split(" ", 1)[1]))

    def metrics_text(self) -> str:
        """Prometheus text exposition of the coordinator process's
        metric registry (scrape-through for a sidecar exporter)."""
        resp = self._cmd("METRICS")
        if not resp.startswith("VAL "):
            raise RuntimeError(f"metrics failed: {resp}")
        return urllib.parse.unquote(resp.split(" ", 1)[1])

    def ping(self) -> bool:
        return self._cmd("PING") == "PONG"

    def shutdown(self):
        self._cmd("SHUTDOWN")

    def close(self):
        self._sock.close()
