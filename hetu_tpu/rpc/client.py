"""Coordinator client (line protocol over TCP).

Reference: ``hetu/impl/communication/rpc_client.cc`` (Connect/GetRank/
KV/Barrier/HeartBeat) + the Python KV-store client
(``rpc/kv_store/client.py``).
"""

from __future__ import annotations

import json
import os
import random
import socket
import time
import urllib.parse
import uuid
from typing import Any, Optional


def _rpc_registry():
    """The process-global metric registry, lazily imported: this module
    must stay importable without the rest of the framework, and the
    disabled-registry fast path keeps the per-verb cost near zero."""
    from hetu_tpu import telemetry
    return telemetry.get_registry()


def _rpc_observe(verb: str, dur_ms: float, tx: int, rx: int) -> None:
    """Client-end wire instrumentation (ISSUE 16): per-verb latency +
    payload bytes. ``dir`` uses tx/rx on the client (the server uses
    in/out), so a test process hosting both ends keeps the series
    distinct."""
    reg = _rpc_registry()
    reg.histogram(
        "rpc_client_verb_ms",
        "client-side wall ms per line-protocol verb (send + reply, "
        "including retries and backoff)").observe(dur_ms, verb=verb)
    c = reg.counter(
        "rpc_payload_bytes_total",
        "line-protocol bytes by verb and direction (client: tx/rx, "
        "server: in/out)")
    c.inc(tx, verb=verb, dir="tx")
    c.inc(rx, verb=verb, dir="rx")


class CoordinatorClient:
    """Line-protocol client.

    The serving-plane verbs carry ``retries`` + jittered exponential
    backoff (reconnect between attempts) instead of blocking forever on
    a dead replica socket: the socket ``timeout`` bounds every recv and
    a connection failure reconnects and retries. SUBMIT/GENERATE carry
    an IDEMPOTENCY KEY the server dedups on, so even a response timeout
    retries safely (a duplicate delivery joins the original request) —
    the PR 8 at-most-once carve-out survives only for verbs whose
    effect has no key (DRAIN/EVICT/SWAPWEIGHTS: one delivery attempt).
    Training-plane verbs (RANK/KV/BARRIER) keep their original
    semantics — BARRIER is *supposed* to block.
    """

    def __init__(self, port: int, host: str = "127.0.0.1",
                 timeout: float = 30.0,
                 token: Optional[str] = None,
                 retries: int = 2, backoff_s: float = 0.05,
                 backoff_max_s: float = 2.0):
        self._host, self._port, self._timeout = host, port, timeout
        self._retries = int(retries)
        self._backoff_s = float(backoff_s)
        self._backoff_max_s = float(backoff_max_s)
        # auth-enabled coordinators require AUTH first on every
        # connection; workers inherit the pool's token via env
        self._token = token if token is not None \
            else os.environ.get("HETU_COORD_TOKEN")
        self._sock: Optional[socket.socket] = None
        self._buf = b""
        self._connect()

    def _connect(self) -> None:
        self._sock = socket.create_connection(
            (self._host, self._port), timeout=self._timeout)
        self._buf = b""
        if self._token:
            resp = self._cmd(f"AUTH {self._token}")
            if resp != "OK":
                raise ConnectionError(f"coordinator auth failed: {resp}")

    def _reconnect(self) -> None:
        try:
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass
        self._connect()

    def _cmd(self, line: str) -> str:
        self._sock.sendall(line.encode() + b"\n")
        while b"\n" not in self._buf:
            chunk = self._sock.recv(4096)
            if not chunk:
                raise ConnectionError("coordinator closed connection")
            self._buf += chunk
        resp, self._buf = self._buf.split(b"\n", 1)
        return resp.decode()

    def _drop_sock(self) -> None:
        """Close and forget the connection. Mandatory on any failed
        command whose response may still arrive: a late response left
        in the socket would be read as the NEXT command's reply and
        desync every call after it — the next verb reconnects clean."""
        try:
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass
        self._sock = None
        self._buf = b""

    def _cmd_retry(self, line: str, *, idempotent: bool = True) -> str:
        """``_cmd`` with bounded retries + jittered exponential backoff.

        At-most-once for non-idempotent verbs (SUBMIT/GENERATE): once
        the command has been handed to a socket, ANY failure — timeout,
        reset, close — may mean it was already delivered and processed,
        so only failures during connection establishment (nothing sent
        yet) are retried. Idempotent verbs retry through a fresh socket
        regardless. Every raise path drops the connection so a late
        response can never poison the next command."""
        verb = line.split(" ", 1)[0]
        t0 = time.perf_counter()
        attempt = 0
        while True:
            sent = False
            try:
                if self._sock is None:       # prior reconnect failed
                    self._connect()
                sent = True        # past here the line may be delivered
                resp = self._cmd(line)
                _rpc_observe(verb,
                             (time.perf_counter() - t0) * 1e3,
                             tx=len(line) + 1, rx=len(resp) + 1)
                return resp
            except (TimeoutError, ConnectionError, OSError):
                attempt += 1
                if attempt > self._retries \
                        or (sent and not idempotent):
                    self._drop_sock()
                    raise
                _rpc_registry().counter(
                    "rpc_retries_total",
                    "line-protocol retry attempts by verb (transport "
                    "failures that reconnected and retried)").inc(
                    verb=verb)
                delay = min(self._backoff_max_s,
                            self._backoff_s * (2 ** (attempt - 1)))
                time.sleep(delay * (0.5 + random.random()))  # jitter
                try:
                    self._reconnect()
                except OSError:
                    # burn this attempt; the next loop turn re-tries
                    # the connect itself (bounded by the same budget)
                    self._sock = None

    # -- rank / membership --------------------------------------------------
    def rank(self, name: str) -> int:
        resp = self._cmd(f"RANK {name}")
        return int(resp.split()[1])

    def heartbeat(self, name: str):
        assert self._cmd(f"BEAT {name}") == "OK"

    def status(self, timeout_ms: int = 5000) -> tuple[list[str], list[str]]:
        resp = self._cmd(f"STATUS {timeout_ms}")
        # "ALIVE a,b DEAD c"
        parts = resp.split()
        alive = parts[1].split(",") if len(parts) > 1 and parts[1] else []
        dead_idx = parts.index("DEAD")
        dead = parts[dead_idx + 1].split(",") \
            if len(parts) > dead_idx + 1 and parts[dead_idx + 1] else []
        return [a for a in alive if a], [d for d in dead if d]

    # -- KV (typed, like the reference's double/int/string/json) ------------
    def put(self, key: str, value: Any):
        enc = urllib.parse.quote(json.dumps(value), safe="")
        assert self._cmd(f"SET {key} {enc}") == "OK"

    def get(self, key: str, default: Any = None) -> Any:
        resp = self._cmd(f"GET {key}")
        if resp == "NONE":
            return default
        return json.loads(urllib.parse.unquote(resp.split(" ", 1)[1]))

    # -- synchronization ----------------------------------------------------
    def barrier(self, name: str, n: int, who: str):
        """Blocks until ``n`` distinct participants arrive."""
        assert self._cmd(f"BARRIER {name} {n} {who}") == "OK"

    # -- serving plane (hetu_tpu/serving — coordinator with an engine) ------
    def _serving_payload(self, prompt, **sampling) -> str:
        obj = {"prompt": [int(t) for t in prompt],
               **{k: v for k, v in sampling.items() if v is not None}}
        return urllib.parse.quote(
            json.dumps(obj, separators=(",", ":")), safe="")

    def serving_submit(self, prompt, *, idem_key: Optional[str] = None,
                       **sampling) -> int:
        """Queue a generation request; returns its id (FCFS).

        Every submit carries an IDEMPOTENCY KEY (auto-generated unless
        ``idem_key`` names one): the server dedups by key, so a
        response timeout is now safely retried — a duplicate delivery
        returns the ORIGINAL request's id instead of queueing a second
        generation. This closes PR 8's at-most-once carve-out."""
        return int(self.serving_submit_info(
            prompt, idem_key=idem_key, **sampling)["id"])

    def serving_submit_info(self, prompt, *,
                            idem_key: Optional[str] = None,
                            resume: Optional[dict] = None,
                            traceparent: Optional[str] = None,
                            **sampling) -> dict:
        """:meth:`serving_submit` returning the full handshake:
        ``{"id", "trace_id", "resumed"}``. ``resume`` attaches a
        wire-format KV spill (``serving.fleet.spill_to_wire``) — the
        fleet proxy's resumable requeue; ``resumed`` reports whether
        the engine accepted it (layout + weight version compatible).
        ``traceparent`` propagates the caller's trace context so the
        remote request joins the fleet trace (ISSUE 16)."""
        payload = dict(sampling)
        payload["idem"] = idem_key or uuid.uuid4().hex
        if resume is not None:
            payload["resume"] = resume
        if traceparent:
            payload["traceparent"] = traceparent
        resp = self._cmd_retry(
            f"SUBMIT {self._serving_payload(prompt, **payload)}")
        if not resp.startswith("ID "):
            raise RuntimeError(f"serving submit failed: {resp}")
        parts = resp.split()
        return {"id": int(parts[1]),
                "trace_id": parts[2] if len(parts) > 2 else "",
                "resumed": len(parts) > 3 and parts[3] == "R"}

    def serving_result(self, req_id: int,
                       timeout_ms: int = 0) -> Optional[dict]:
        """Poll a queued request: dict result, or None while pending.
        Safe to retry (and retried) across timeouts — polling twice is
        harmless."""
        resp = self._cmd_retry(f"RESULT {req_id} {timeout_ms}")
        if resp == "PEND":
            return None
        if not resp.startswith("VAL "):
            raise RuntimeError(f"serving result failed: {resp}")
        return json.loads(urllib.parse.unquote(resp.split(" ", 1)[1]))

    def serving_generate(self, prompt, *,
                         idem_key: Optional[str] = None,
                         traceparent: Optional[str] = None,
                         **sampling) -> dict:
        """Blocking generate over the line protocol (engine loop must
        be running server-side, e.g. ``ServingServer.start()``).
        Idempotency-keyed like :meth:`serving_submit`: a retried
        delivery joins the original request instead of generating
        twice."""
        payload = dict(sampling)
        payload["idem"] = idem_key or uuid.uuid4().hex
        if traceparent:
            payload["traceparent"] = traceparent
        resp = self._cmd_retry(
            f"GENERATE {self._serving_payload(prompt, **payload)}")
        if not resp.startswith("VAL "):
            raise RuntimeError(f"serving generate failed: {resp}")
        return json.loads(urllib.parse.unquote(resp.split(" ", 1)[1]))

    # -- streaming (ISSUE 19) -----------------------------------------------
    def _stream_channel(self):
        """This client's persistent multiplexed channel (lazy,
        recreated after a loss)."""
        ch = getattr(self, "_stream", None)
        if ch is not None and ch.alive:
            return ch
        from hetu_tpu.rpc.stream import StreamChannel
        ch = StreamChannel(self._port, host=self._host,
                           token=self._token or "",
                           connect_timeout=self._timeout)
        self._stream = ch
        return ch

    @staticmethod
    def _count_stream_fallback(reason: str) -> None:
        try:
            from hetu_tpu.serving.streaming import count_fallback
            count_fallback(reason)
        except Exception:                             # noqa: BLE001
            pass

    def generate_stream(self, prompt, *,
                        idem_key: Optional[str] = None,
                        traceparent: Optional[str] = None,
                        event_timeout_s: float = 60.0,
                        max_reconnects: int = 3,
                        **sampling):
        """Streaming generate: yields event dicts ``{"tokens":
        [newly committed ids], "first": bool, "done": bool}`` as the
        engine commits them; the final event adds ``"result"`` — the
        full result with the trailing timing payload, byte-identical
        to what :meth:`serving_generate` returns for the same request.

        Rides the persistent multiplexed channel end to end (router →
        engine → here). Self-healing: a dead socket reconnects and
        resubscribes at the token offset already received (the
        idempotency key re-joins the original request even when the
        loss predates the ack), and after ``max_reconnects`` losses —
        or a server-side drop — the tail degrades to RESULT polling,
        loudly counted. Every path yields each token exactly once."""
        payload = dict(sampling)
        payload["idem"] = idem_key or uuid.uuid4().hex
        if traceparent:
            payload["traceparent"] = traceparent
        received: list[int] = []
        req_id: Optional[int] = None
        reconnects = 0
        while reconnects <= max_reconnects:
            import queue as _queue
            q: "_queue.Queue" = _queue.Queue()
            try:
                ch = self._stream_channel()
                if req_id is None:
                    ack = ch.stream_submit(
                        self._serving_payload(prompt, **payload),
                        sink=q.put, offset=len(received))
                    req_id = int(ack["id"])
                else:
                    ch.subscribe(req_id, offset=len(received),
                                 sink=q.put)
            except RuntimeError:
                raise                  # admission rejection: terminal
            except Exception:                         # noqa: BLE001
                reconnects += 1
                continue
            degrade = False
            while not degrade:
                try:
                    fr = q.get(timeout=event_timeout_s)
                except _queue.Empty:
                    degrade = True     # silent stream: stop trusting it
                    break
                kind = fr.get("k")
                if kind == "ev":
                    off = int(fr.get("off", 0))
                    toks = [int(t) for t in fr.get("toks", [])]
                    skip = len(received) - off
                    if skip < 0:       # lost frame — never guess
                        degrade = True
                        break
                    if skip:
                        toks = toks[skip:]
                    received.extend(toks)
                    out = {"tokens": toks,
                           "first": bool(fr.get("first")),
                           "done": bool(fr.get("done"))}
                    if fr.get("done"):
                        out["result"] = fr.get("result")
                        yield out
                        return
                    if fr.get("end"):
                        degrade = True     # evicted/cancelled: poll
                        break              # the router for the retry
                    if toks:
                        yield out
                elif kind == "lost":
                    reconnects += 1
                    break              # reconnect + resubscribe-at-
                #                        offset on a fresh channel
                else:                  # drop / err: server said stop
                    degrade = True
                    break
            if degrade:
                break
        # -- loud fallback: the RESULT poll lane finishes the request --
        self._count_stream_fallback("client_poll")
        if req_id is None:
            # the loss predates the ack — the idempotency key makes
            # this re-delivery join the original request if it landed
            doc = self.serving_generate(prompt,
                                        idem_key=payload["idem"],
                                        traceparent=traceparent,
                                        **sampling)
        else:
            doc = None
            while doc is None:
                doc = self.serving_result(req_id, timeout_ms=500)
        tail = [int(t) for t in doc.get("tokens", [])][len(received):]
        received.extend(tail)
        yield {"tokens": tail, "first": False, "done": True,
               "result": doc}

    # -- fleet engine verbs (serving.fleet.RemoteEngineProxy) ---------------
    def _val_verb(self, line: str, *, idempotent: bool = True) -> dict:
        resp = self._cmd_retry(line, idempotent=idempotent)
        if not resp.startswith("VAL "):
            raise RuntimeError(f"{line.split()[0]} failed: {resp}")
        return json.loads(urllib.parse.unquote(resp.split(" ", 1)[1]))

    def serving_estatus(self) -> dict:
        """Light engine-status poll (load / queue depth / occupancy /
        weight version / has_work) — the remote replica handle's
        heartbeat-cum-load signal."""
        return self._val_verb("ESTATUS")

    def serving_cancel_queued(self, ids) -> dict:
        """Pull queued (not yet admitted) requests off the remote
        engine — the router's drain leg. Returns
        ``{"cancelled": [{"id", "spill"}]}`` with wire-format spills
        for requests that carried KV."""
        enc = urllib.parse.quote(json.dumps(
            {"ids": [int(i) for i in ids]},
            separators=(",", ":")), safe="")
        return self._val_verb(f"CANCELQ {enc}", idempotent=False)

    def serving_evict(self, req_id: int,
                      lock_timeout_s: Optional[float] = None,
                      traceparent: Optional[str] = None) -> dict:
        """Force one request out of the remote engine, salvaging its
        resident KV: ``{"status", "spill": wire | None}``.
        ``traceparent`` stamps the salvaged spill with the fleet trace
        context when the remote request predates it."""
        obj = {"id": int(req_id), "lock_timeout_s": lock_timeout_s}
        if traceparent:
            obj["traceparent"] = traceparent
        enc = urllib.parse.quote(json.dumps(
            obj, separators=(",", ":")), safe="")
        return self._val_verb(f"EVICT {enc}", idempotent=False)

    def serving_prefill(self, prompt, *,
                        traceparent: Optional[str] = None,
                        **sampling) -> dict:
        """Prefill-tier verb: admission + prefill on the remote engine,
        blocking until the KV is ready. Returns ``{"done": True,
        "result": ...}`` for requests that finished within their first
        token, else ``{"done": False, "id", "tokens", "spill": wire}``
        — the KV-block payload a decode replica resumes from."""
        if traceparent:
            sampling["traceparent"] = traceparent
        resp = self._cmd_retry(
            f"PREFILL {self._serving_payload(prompt, **sampling)}",
            idempotent=False)
        if not resp.startswith("VAL "):
            raise RuntimeError(f"serving prefill failed: {resp}")
        return json.loads(urllib.parse.unquote(resp.split(" ", 1)[1]))

    def serving_swap_weights(self, path: str, version: int,
                             traceparent: Optional[str] = None) -> dict:
        """Remote leg of a dist-checkpoint weight push: the engine
        process loads ``path`` onto its own topology and swaps. NOT
        retried on timeout — the load may already be in flight.
        ``traceparent`` lets the push's trace context travel with the
        swap so remote flight events correlate with it."""
        obj = {"path": path, "version": int(version)}
        if traceparent:
            obj["traceparent"] = traceparent
        enc = urllib.parse.quote(json.dumps(
            obj, separators=(",", ":")), safe="")
        return self._val_verb(f"SWAPWEIGHTS {enc}", idempotent=False)

    def serving_stop_engine(self) -> None:
        resp = self._cmd_retry("STOPENGINE", idempotent=False)
        if resp != "OK":
            raise RuntimeError(f"stop engine failed: {resp}")

    # -- fleet-global KV verbs (ISSUE 18) -----------------------------------
    def serving_kv_export(self, tokens) -> dict:
        """Gather the remote replica's cached whole-block prefix of
        ``tokens``: ``{"spill": wire | None}``. Read-only (the prefix
        cache keeps its refs) — safe to retry."""
        enc = urllib.parse.quote(json.dumps(
            {"tokens": [int(t) for t in tokens]},
            separators=(",", ":")), safe="")
        return self._val_verb(f"KVEXPORT {enc}")

    def serving_kv_import(self, spill_wire: dict) -> dict:
        """Map a peer-exported prefix into the remote replica's prefix
        cache: ``{"ok": bool}`` (False = refused — stale version or
        layout mismatch — the caller prefills instead). Idempotent by
        construction: re-importing an already-cached prefix is a
        no-op."""
        enc = urllib.parse.quote(json.dumps(
            {"spill": spill_wire}, separators=(",", ":")), safe="")
        return self._val_verb(f"KVIMPORT {enc}")

    def serving_kv_put(self, doc: dict) -> None:
        """Deliver one decode-KV replication shipment to the remote
        buddy's replica store. Idempotent: shipments overwrite by
        (trace_id, block index)."""
        enc = urllib.parse.quote(json.dumps(
            doc, separators=(",", ":")), safe="")
        resp = self._cmd_retry(f"KVREPL {enc}")
        if resp != "OK":
            raise RuntimeError(f"kv put failed: {resp}")

    def serving_kv_fetch(self, trace_id: str) -> dict:
        """Assemble the buddy-held replica set for ``trace_id``:
        ``{"spill": wire | None}`` — the recovery path's resume
        payload."""
        enc = urllib.parse.quote(json.dumps(
            {"trace_id": str(trace_id)},
            separators=(",", ":")), safe="")
        return self._val_verb(f"KVFETCH {enc}")

    def serving_kv_buddy(self, host: Optional[str], port: int = 0, *,
                         token: Optional[str] = None, origin: str = "",
                         cadence_s: float = 0.02) -> None:
        """Point the remote engine's replication stream at a buddy
        replica (``host=None`` disables replication)."""
        obj = {"host": host, "port": int(port), "origin": origin,
               "cadence_s": float(cadence_s)}
        if token:
            obj["token"] = token
        enc = urllib.parse.quote(json.dumps(
            obj, separators=(",", ":")), safe="")
        resp = self._cmd_retry(f"KVBUDDY {enc}")
        if resp != "OK":
            raise RuntimeError(f"kv buddy failed: {resp}")

    # -- fleet verbs (coordinator with a serving.router.Router) -------------
    def fleet_status(self) -> dict:
        """Fleet-wide aggregation: per-replica state/load/version,
        pending + requeue counters (``Router.fleet_status``)."""
        resp = self._cmd_retry("FLEET")
        if not resp.startswith("VAL "):
            raise RuntimeError(f"fleet status failed: {resp}")
        return json.loads(urllib.parse.unquote(resp.split(" ", 1)[1]))

    def fleet_drain(self, name: str) -> dict:
        """Drain one replica (requests re-dispatch to peers); returns
        ``{"requeued": n}``. NOT retried on timeout: drain blocks
        server-side until the replica runs dry."""
        resp = self._cmd_retry(f"DRAIN {name}", idempotent=False)
        if not resp.startswith("VAL "):
            raise RuntimeError(f"fleet drain failed: {resp}")
        return json.loads(urllib.parse.unquote(resp.split(" ", 1)[1]))

    def fleet_resume(self, name: str) -> None:
        resp = self._cmd_retry(f"RESUME {name}", idempotent=False)
        if resp != "OK":
            raise RuntimeError(f"fleet resume failed: {resp}")

    def fleet_metrics_text(self) -> str:
        """Federated Prometheus page from a Router front door: every
        replica's series labeled ``replica="<name>"`` plus
        pre-aggregated ``replica="_fleet"`` totals (ISSUE 16)."""
        resp = self._cmd_retry("FLEETMETRICS")
        if not resp.startswith("VAL "):
            raise RuntimeError(f"fleet metrics failed: {resp}")
        return urllib.parse.unquote(resp.split(" ", 1)[1])

    def dump_obs(self) -> dict:
        """The serving process's observability bundle (chrome trace +
        flight ring + fleet identity) via the DUMPOBS verb — the wire
        collection path of ``tools/fleet_trace.py``."""
        resp = self._cmd_retry("DUMPOBS")
        if not resp.startswith("VAL "):
            raise RuntimeError(f"dump obs failed: {resp}")
        return json.loads(urllib.parse.unquote(resp.split(" ", 1)[1]))

    # -- live observability (HEALTHZ / METRICS verbs) -----------------------
    def healthz(self) -> dict:
        """Live health document: overall status, watchdog trips, SLO
        alerting state, serving queue/occupancy (telemetry.health_status
        evaluated on the coordinator process)."""
        resp = self._cmd_retry("HEALTHZ")
        if not resp.startswith("VAL "):
            raise RuntimeError(f"healthz failed: {resp}")
        return json.loads(urllib.parse.unquote(resp.split(" ", 1)[1]))

    def metrics_text(self) -> str:
        """Prometheus text exposition of the coordinator process's
        metric registry (scrape-through for a sidecar exporter)."""
        resp = self._cmd_retry("METRICS")
        if not resp.startswith("VAL "):
            raise RuntimeError(f"metrics failed: {resp}")
        return urllib.parse.unquote(resp.split(" ", 1)[1])

    def ping(self) -> bool:
        return self._cmd("PING") == "PONG"

    def shutdown(self):
        self._cmd("SHUTDOWN")

    def close(self):
        ch = getattr(self, "_stream", None)
        if ch is not None:
            try:
                ch.close()
            except Exception:                         # noqa: BLE001
                pass
            self._stream = None
        if self._sock is not None:
            self._sock.close()
