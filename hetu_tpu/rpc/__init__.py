"""Cluster control plane: coordinator service + client.

Parity target: ``python/hetu/rpc`` — gRPC DeviceController servers
(polling/async/elastic), KV store, barriers, heartbeat monitoring.
"""

from hetu_tpu.rpc.coordinator import Coordinator
from hetu_tpu.rpc.client import CoordinatorClient

from hetu_tpu.rpc.launcher import (
    DistContext, ElasticWorkerPool, bootstrap_distributed,
)

__all__ = ["Coordinator", "CoordinatorClient",
           "DistContext", "ElasticWorkerPool", "bootstrap_distributed"]
