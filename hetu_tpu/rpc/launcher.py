"""Multi-process launch + elastic restart plane.

TPU-native counterpart of the reference's launcher stack:
``rpc/pssh_start.py:17`` (SSH fan-out, per-process env + log files) and
``rpc/heturpc_elastic_server.py:497-559`` (death detection → restart the
worker pool, resume from checkpoint). Here the fan-out is local
``subprocess`` workers (the SSH hop is an env-provided command prefix away)
and the cross-process device runtime is ``jax.distributed`` — the
Coordinator supplies rank assignment, the KV used to exchange the JAX
coordinator address, heartbeats, and barriers; JAX's own distributed
service then owns collective bootstrap (the role NCCL-id exchange plays in
the reference).

Elastic model (same as the reference's): individual processes cannot be
re-admitted into a running JAX job, so on any worker death the pool kills
the generation and relaunches all workers; workers resume from the latest
(sharded) checkpoint. Generations are namespaced in worker names and KV
keys. Single-controller flows do better: when the controller process
survives the failure, ``engine.elastic.elastic_resume`` reshards its LIVE
train state onto the recovery plan in memory (cross_topology_switch) and
no checkpoint is read — disk is only the dead-controller fallback.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Optional, Sequence

from hetu_tpu.rpc.client import CoordinatorClient
from hetu_tpu.rpc.coordinator import Coordinator
from hetu_tpu.utils.logging import get_logger


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclasses.dataclass
class DistContext:
    """A worker's view of the cluster after bootstrap."""

    rank: int
    num_processes: int
    generation: int
    client: CoordinatorClient
    heartbeat: Optional[object]   # HeartbeatSender (imported lazily —
                                  # engine.elastic imports rpc.client)

    def shutdown(self):
        if self.heartbeat is not None:
            self.heartbeat.stop()
        self.client.close()


def bootstrap_distributed(*, coord_port: Optional[int] = None,
                          num_processes: Optional[int] = None,
                          rank: Optional[int] = None,
                          name: Optional[str] = None,
                          heartbeat: bool = True,
                          timeout_s: float = 60.0) -> DistContext:
    """Connect to the Coordinator, resolve rank, and bring up
    ``jax.distributed`` across the worker set.

    Reference flow: ``distributed_init`` → Connect/GetRank → NCCL-id via
    coordinator (SURVEY §3.1). Here: rank from the Coordinator (or the
    launcher's HETU_RANK), JAX service address via the coordinator KV
    (rank 0 publishes, everyone else polls), then
    ``jax.distributed.initialize``.
    """
    port = coord_port if coord_port is not None \
        else int(os.environ["HETU_COORD_PORT"])
    coord_host = os.environ.get("HETU_COORD_HOST", "127.0.0.1")
    n = num_processes if num_processes is not None \
        else int(os.environ.get("HETU_NUM_PROCS", "1"))
    gen = int(os.environ.get("HETU_GENERATION", "0"))
    name = name or os.environ.get("HETU_WORKER_NAME",
                                  f"worker-{os.getpid()}")
    client = CoordinatorClient(port, host=coord_host)
    if rank is None:
        env_rank = os.environ.get("HETU_RANK")
        rank = int(env_rank) if env_rank is not None else client.rank(name)

    if n > 1:
        key = f"jax_coordinator/g{gen}"
        if rank == 0:
            # cross-host workers must publish a routable address, not
            # loopback; HETU_ADVERTISE_HOST overrides, else hostname when
            # the coordinator itself is non-local
            if coord_host in ("127.0.0.1", "localhost"):
                my_host = "127.0.0.1"
            else:
                import socket as _socket
                my_host = _socket.gethostname()
            my_host = os.environ.get("HETU_ADVERTISE_HOST", my_host)
            addr = f"{my_host}:{_free_port()}"
            client.put(key, addr)
        else:
            deadline = time.monotonic() + timeout_s
            addr = client.get(key)
            while addr is None:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"rank {rank}: no {key} published within "
                        f"{timeout_s}s")
                time.sleep(0.05)
                addr = client.get(key)
        import jax
        from hetu_tpu.core.compat import enable_cpu_collectives
        from hetu_tpu.telemetry.flight import flight_record
        enable_cpu_collectives()   # old-jax CPU default is "none"
        # collective bootstraps are the classic distributed-hang site:
        # bracket the blocking initialize in the black box so a wedged
        # rendezvous is attributable post-mortem
        flight_record("collective_bootstrap", phase="start", rank=rank,
                      num_processes=n, addr=addr)
        jax.distributed.initialize(addr, num_processes=n, process_id=rank)
        flight_record("collective_bootstrap", phase="done", rank=rank,
                      num_processes=n)

    if heartbeat:
        from hetu_tpu.engine.elastic import HeartbeatSender
        hb = HeartbeatSender(port, name).start()
    else:
        hb = None
    return DistContext(rank, n, gen, client, hb)


class ElasticWorkerPool:
    """Spawn N worker processes; on any death, restart the generation.

    Parity: the elastic server's restart-with-PSSH-pool loop
    (``heturpc_elastic_server.py:497-559``) with ``max_restart_times``
    semantics from the host yaml (``pssh_start.py:27-36``).
    """

    #: default worker platform: the CPU-simulation flow (one virtual
    #: device per process). Pass ``platform_env={}`` (or your own) to run
    #: workers on real TPU hosts with the inherited environment.
    CPU_SIM_ENV = {
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "JAX_PLATFORMS": "cpu",
    }

    def __init__(self, script: str, num_workers: int, *,
                 args: Sequence[str] = (),
                 max_restarts: int = 1,
                 log_dir: Optional[str] = None,
                 env: Optional[dict] = None,
                 platform_env: Optional[dict] = None,
                 ssh_hosts: Optional[Sequence[str]] = None,
                 ssh_cmd: Sequence[str] = ("ssh", "-tt"),
                 coordinator_host: Optional[str] = None,
                 poll_s: float = 0.2):
        self.script = script
        self.num_workers = num_workers
        self.args = list(args)
        # multi-host fan-out à la pssh_start.py: worker i runs on
        # ssh_hosts[i % len] with its env serialized into the remote
        # command (the coordinator address must then be reachable —
        # bind-all is the operator's call, as in the reference)
        self.ssh_hosts = list(ssh_hosts) if ssh_hosts else None
        # transport argv prefix: the hop command that receives
        # ``host remote-shell-string...`` — ("ssh", "-tt") in production
        # (reference: parallel-ssh, ``pssh_start.py:17``); tests and
        # exotic fabrics substitute a shim with the same contract (the
        # remote words are shell-quoted, so the hop must run them
        # through a shell like sshd does)
        self.ssh_cmd = list(ssh_cmd)
        # routable address of THIS machine for remote workers' coordinator
        # connections (required with ssh_hosts)
        self.coordinator_host = coordinator_host
        if self.ssh_hosts and not coordinator_host:
            raise ValueError(
                "ssh_hosts needs coordinator_host (a routable address of "
                "the launcher machine — remote workers must reach the "
                "coordinator and it binds 127.0.0.1 otherwise)")
        self.max_restarts = max_restarts
        self.log_dir = log_dir
        self.extra_env = dict(env or {})
        self.platform_env = dict(self.CPU_SIM_ENV if platform_env is None
                                 else platform_env)
        self.poll_s = poll_s
        self.coordinator: Optional[Coordinator] = None
        self.procs: list[subprocess.Popen] = []
        self.generation = 0
        self._logs: list = []

    # -- lifecycle -----------------------------------------------------------
    def __enter__(self):
        # multi-host fleets need a reachable coordinator; every pool
        # gets a fresh bearer token (shipped to workers via
        # HETU_COORD_TOKEN) — mandatory when binding beyond loopback
        import secrets
        self._token = secrets.token_hex(16)
        self.coordinator = Coordinator(
            bind="0.0.0.0" if self.ssh_hosts else "127.0.0.1",
            token=self._token)
        return self

    def __exit__(self, *exc):
        self._kill_all()
        if self.coordinator is not None:
            self.coordinator.shutdown()
        return False

    def _worker_env(self, rank: int) -> dict:
        env = dict(os.environ)
        env.update(self.platform_env)
        env.update(self.extra_env)
        # launcher-owned keys always win — they define the worker identity
        env.update({
            "HETU_COORD_PORT": str(self.coordinator.port),
            "HETU_COORD_TOKEN": self._token,
            "HETU_NUM_PROCS": str(self.num_workers),
            "HETU_RANK": str(rank),
            "HETU_GENERATION": str(self.generation),
            "HETU_WORKER_NAME": f"g{self.generation}-w{rank}",
        })
        return env

    def _spawn_all(self):
        self.procs = []
        for r in range(self.num_workers):
            if self.log_dir:
                os.makedirs(self.log_dir, exist_ok=True)
                path = os.path.join(self.log_dir,
                                    f"g{self.generation}-w{r}.log")
                # 0600: worker logs can carry secrets (e.g. a pty-echoed
                # auth token line on ssh fleets)
                log = os.fdopen(os.open(
                    path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600),
                    "w")
            else:
                log = subprocess.DEVNULL
            self._logs.append(log)
            env = self._worker_env(r)
            cmd = [sys.executable, self.script, *self.args]
            stdin = None
            if self.ssh_hosts:
                import shlex
                host = self.ssh_hosts[r % len(self.ssh_hosts)]
                env["HETU_COORD_HOST"] = self.coordinator_host
                hetu_env = [shlex.quote(f"{k}={v}")
                            for k, v in env.items()
                            if k.startswith(("HETU_", "JAX_", "XLA_",
                                             "PYTHONPATH"))
                            and k != "HETU_COORD_TOKEN"]
                # -tt (in the default ssh_cmd): killing the local ssh
                # client drops the remote tty, so the remote worker gets
                # SIGHUP on generation teardown. The auth token travels
                # over the ssh STDIN pipe, never on the remote command
                # line — /proc/<pid>/cmdline is world-readable on every
                # worker host. The remote bootstrap is wrapped in an
                # explicit `sh -c` so csh/fish login shells work, and
                # turns pty echo off (best-effort) before reading the
                # token; the launcher-local log file is 0600 regardless,
                # so even a raced echo never lands world-readable.
                payload = (
                    "stty -echo 2>/dev/null; read -r HETU_COORD_TOKEN; "
                    "export HETU_COORD_TOKEN; exec env "
                    + " ".join(hetu_env) + " python3 "
                    + shlex.quote(self.script) + " "
                    + " ".join(map(shlex.quote, self.args))).rstrip()
                cmd = [*self.ssh_cmd, host, "sh", "-c",
                       shlex.quote(payload)]
                stdin = subprocess.PIPE
            p = subprocess.Popen(cmd, env=env, stdout=log, stderr=log,
                                 stdin=stdin)
            if stdin is not None:
                try:
                    p.stdin.write((self._token + "\n").encode())
                    p.stdin.flush()
                except OSError:
                    # ssh died instantly (unreachable host): leave the
                    # dead proc for the generation-restart loop, exactly
                    # like any other worker death
                    pass
            self.procs.append(p)
        get_logger().info(
            f"pool: generation {self.generation} spawned "
            f"{self.num_workers} workers")

    def _kill_all(self):
        for p in self.procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.monotonic() + 5
        for p in self.procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
        for log in self._logs:
            if log is not subprocess.DEVNULL and not log.closed:
                log.close()
        self._logs = []

    def kill_worker(self, rank: int, sig=signal.SIGKILL):
        """Fault injection for chaos tests."""
        self.procs[rank].send_signal(sig)

    # -- supervision ---------------------------------------------------------
    def run(self, timeout_s: float = 300.0) -> dict:
        """Launch and supervise until the generation exits cleanly (all rc
        0) or restarts are exhausted. Returns a summary dict."""
        if self.coordinator is None:
            raise RuntimeError("use ElasticWorkerPool as a context manager")
        self._spawn_all()
        deadline = time.monotonic() + timeout_s
        restarts = 0
        while True:
            if time.monotonic() > deadline:
                self._kill_all()
                raise TimeoutError("worker pool timed out")
            codes = [p.poll() for p in self.procs]
            if all(c == 0 for c in codes):
                return {"generations": self.generation + 1,
                        "restarts": restarts, "exit_codes": codes}
            if any(c is not None and c != 0 for c in codes):
                dead = [i for i, c in enumerate(codes)
                        if c is not None and c != 0]
                get_logger().warning(
                    f"pool: generation {self.generation} lost workers "
                    f"{dead} (codes {[codes[i] for i in dead]})")
                self._kill_all()
                if restarts >= self.max_restarts:
                    return {"generations": self.generation + 1,
                            "restarts": restarts, "exit_codes": codes,
                            "failed": True}
                restarts += 1
                self.generation += 1
                self._spawn_all()
            time.sleep(self.poll_s)


@dataclasses.dataclass
class FleetHandle:
    """A launched serving fleet: the router, its replica names, and the
    optional coordinator front door. ``stop()`` tears down front door →
    router → every replica loop (reverse launch order). A REMOTE fleet
    also carries its engine processes (``procs``) — ``stop()`` SIGTERMs
    them after the router lets go, and :meth:`kill_replica_process` is
    the chaos hook (real SIGKILL; the router's heartbeat staleness
    detects it)."""

    router: object                   # serving.router.Router
    replicas: list
    coordinator: Optional[object] = None   # PyCoordinatorServer | None
    port: Optional[int] = None
    procs: dict = dataclasses.field(default_factory=dict)
    #                                ^ name → subprocess.Popen (remote)
    engine_ports: dict = dataclasses.field(default_factory=dict)
    _logs: list = dataclasses.field(default_factory=list)

    def kill_replica_process(self, name: str, sig=signal.SIGKILL):
        """Chaos hook: SIGKILL one remote engine process. Death is
        detected by the router through heartbeat staleness — nothing
        here tells it."""
        self.procs[name].send_signal(sig)

    def stop(self):
        if self.coordinator is not None:
            self.coordinator.stop()
        self.router.stop()
        for p in self.procs.values():
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.monotonic() + 10
        for p in self.procs.values():
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
        for log in self._logs:
            if log is not subprocess.DEVNULL and not log.closed:
                log.close()
        self._logs = []

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


def launch_serving_fleet(build_engine=None, n_replicas: int = 2, *,
                         names: Optional[Sequence[str]] = None,
                         roles: Optional[dict] = None,
                         port: Optional[int] = None,
                         bind: str = "127.0.0.1", token: str = "",
                         remote: bool = False,
                         engine_spec: Optional[str] = None,
                         env: Optional[dict] = None,
                         platform_env: Optional[dict] = None,
                         log_dir: Optional[str] = None,
                         spawn_timeout_s: float = 120.0,
                         beat_timeout_s: Optional[float] = None,
                         proxy_kw: Optional[dict] = None,
                         **router_kw) -> FleetHandle:
    """Bring up a serving fleet: N replicas, one load-aware Router over
    them, and — when ``port`` is given — a coordinator speaking the
    full verb set (SUBMIT/RESULT/GENERATE routed fleet-wide,
    FLEET/DRAIN/RESUME, HEALTHZ/METRICS) as the fleet's front door.

    **In-process** (default): each replica is ``build_engine(i)`` — a
    fresh ServingEngine whose background loop registration starts.
    Threads share one process's devices: the single-host shape used by
    ``workloads/rollout_loop.py``, ``bench.py --router`` and the
    router tests.

    **Multi-process** (``remote=True`` — ISSUE 15): one engine PROCESS
    per replica. ``engine_spec`` names a ``module:function`` the child
    resolves and calls with its replica index (closures cannot cross
    the process boundary); each child serves its engine on a private
    line-protocol port (``serving.fleet.replica_main``), the launcher
    waits for it to answer PING, and registers a
    ``RemoteEngineProxy``-backed handle — death detection is heartbeat
    staleness, KV spills and weight pushes travel the wire
    (``docs/SERVING.md`` "Disaggregated fleet"). ``platform_env``
    defaults to the CPU-simulation flow
    (``ElasticWorkerPool.CPU_SIM_ENV``); pass ``{}`` to inherit (real
    TPU hosts). ``roles`` maps replica name → ``prefill|decode|both``
    for P/D disaggregation (both modes).

    ``proxy_kw`` forwards extra keyword arguments to every
    ``RemoteEngineProxy`` (e.g. ``{"use_stream": False}`` to force the
    legacy RESULT-polling transport — the bench's polling baseline).

    Lazy imports keep the launcher importable without jax.
    """
    from hetu_tpu.serving.router import Router

    names = list(names) if names is not None \
        else [f"r{i}" for i in range(n_replicas)]
    if len(names) != n_replicas:
        raise ValueError(f"{len(names)} names for {n_replicas} replicas")
    roles = dict(roles or {})
    if beat_timeout_s is not None:
        router_kw["beat_timeout_s"] = beat_timeout_s
    router = Router(**router_kw)
    handle = FleetHandle(router=router, replicas=names)

    if remote:
        if engine_spec is None:
            raise ValueError(
                "remote=True needs engine_spec='module:function' — a "
                "builder the engine process can import (closures "
                "cannot cross the process boundary)")
        penv = dict(ElasticWorkerPool.CPU_SIM_ENV
                    if platform_env is None else platform_env)
        for i, name in enumerate(names):
            eport = _free_port()
            env_i = dict(os.environ)
            env_i.update(penv)
            env_i.update(env or {})
            env_i.update({
                "HETU_ENGINE_SPEC": engine_spec,
                "HETU_REPLICA_INDEX": str(i),
                "HETU_REPLICA_NAME": name,
                # observability identity: flight-recorder dumps and
                # DUMPOBS bundles are stamped with the replica's P/D
                # role so obs_report/fleet_trace can group them
                "HETU_REPLICA_ROLE": str(roles.get(name, "both")),
                "HETU_ENGINE_PORT": str(eport),
                # the engine ports must enforce the same token as the
                # front door — an unauthenticated replica port would
                # accept STOPENGINE/SWAPWEIGHTS from anyone local
                "HETU_ENGINE_TOKEN": token,
            })
            if log_dir:
                os.makedirs(log_dir, exist_ok=True)
                log = open(os.path.join(log_dir, f"{name}.log"), "w")
                handle._logs.append(log)
            else:
                log = subprocess.DEVNULL
            p = subprocess.Popen(
                [sys.executable, "-m", "hetu_tpu.serving.fleet"],
                env=env_i, stdout=log, stderr=log)
            handle.procs[name] = p
            handle.engine_ports[name] = eport
        # wait for every engine to answer, then register its proxy —
        # registration starts the status poller (= the heartbeat). A
        # replica that fails to come up must not leak its siblings:
        # tear the whole half-launched fleet down before re-raising.
        from hetu_tpu.rpc.client import CoordinatorClient
        from hetu_tpu.serving.fleet import RemoteEngineProxy
        deadline = time.monotonic() + spawn_timeout_s
        try:
            for name in names:
                eport = handle.engine_ports[name]
                while True:
                    proc = handle.procs[name]
                    if proc.poll() is not None:
                        raise RuntimeError(
                            f"fleet replica {name} exited "
                            f"rc={proc.poll()} before serving "
                            f"(check log_dir logs)")
                    try:
                        cli = CoordinatorClient(eport, timeout=2.0,
                                                retries=0)
                        ok = cli.ping()
                        cli.close()
                        if ok:
                            break
                    except OSError:
                        pass
                    if time.monotonic() > deadline:
                        raise TimeoutError(
                            f"fleet replica {name} not serving on "
                            f":{eport} within {spawn_timeout_s}s")
                    time.sleep(0.1)
                router.register(
                    name, RemoteEngineProxy(eport, token=token or None,
                                            **(proxy_kw or {})),
                    role=roles.get(name, "both"))
        except BaseException:
            handle.stop()             # SIGTERM spawned procs, close
            raise                     # logs, stop router + pollers
    else:
        for i, name in enumerate(names):
            router.register(name, build_engine(i),
                            role=roles.get(name, "both"))

    coordinator = None
    if port is not None:
        from hetu_tpu.rpc.py_server import PyCoordinatorServer
        coordinator = PyCoordinatorServer(port, bind=bind, token=token,
                                          serving=router)
        coordinator.start()
        coordinator.wait_ready()
    handle.coordinator = coordinator
    handle.port = port
    get_logger().info(
        f"serving fleet up: {n_replicas} "
        f"{'process' if remote else 'in-process'} replicas "
        f"({', '.join(names)})"
        + (f", coordinator :{port}" if port is not None else ""))
    return handle
