"""Coordinator service launcher.

Builds and spawns the native server (``hetu_tpu/csrc/coordinator.cpp`` —
the C++ re-implementation of the reference's gRPC DeviceController), with
a pure-Python fallback speaking the same line protocol when no toolchain
is available. Reference servers: ``rpc/heturpc_polling_server.py:17``,
``heturpc_elastic_server.py:39-559``.
"""

from __future__ import annotations

import os
import socket
import subprocess
import threading
import time
from typing import Optional

_CSRC = os.path.join(os.path.dirname(os.path.dirname(__file__)),
                     "csrc", "coordinator.cpp")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class Coordinator:
    """Owns a running coordinator server (native or Python fallback)."""

    def __init__(self, port: Optional[int] = None, *,
                 prefer_native: bool = True,
                 bind: str = "127.0.0.1",
                 token: Optional[str] = None):
        self.port = port or _free_port()
        self.bind = bind
        # shared-secret auth (optional): every client connection must
        # AUTH <token> first; the launcher generates one per pool and
        # ships it to workers as HETU_COORD_TOKEN
        self.token = token or ""
        self._proc: Optional[subprocess.Popen] = None
        self._py_server = None
        if prefer_native and self._start_native():
            self.native = True
        else:
            self._start_python()
            self.native = False

    # -- native server ------------------------------------------------------
    def _start_native(self) -> bool:
        try:
            from hetu_tpu.utils.native import build_native
            exe = build_native(_CSRC, "coordinator", shared=False)
            if exe is None:
                return False
            # token via env, not argv — /proc/<pid>/cmdline is world-
            # readable on the coordinator host
            env = dict(os.environ)
            if self.token:
                env["HETU_COORD_TOKEN"] = self.token
            else:
                env.pop("HETU_COORD_TOKEN", None)
            self._proc = subprocess.Popen(
                [exe, str(self.port), self.bind], env=env,
                stdout=subprocess.PIPE, text=True)
            line = self._proc.stdout.readline()
            return line.startswith("COORDINATOR READY")
        except Exception:
            if self._proc is not None:
                self._proc.kill()
                self._proc = None
            return False

    # -- python fallback ----------------------------------------------------
    def _start_python(self):
        from hetu_tpu.rpc.py_server import PyCoordinatorServer
        self._py_server = PyCoordinatorServer(self.port, bind=self.bind,
                                              token=self.token)
        self._py_server.start()
        self._py_server.wait_ready()

    def shutdown(self):
        try:
            from hetu_tpu.rpc.client import CoordinatorClient
            CoordinatorClient(self.port,
                              token=self.token or None).shutdown()
        except Exception:
            pass
        if self._proc is not None:
            try:
                self._proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._proc.kill()
            self._proc = None
        if self._py_server is not None:
            self._py_server.stop()
            self._py_server = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False
