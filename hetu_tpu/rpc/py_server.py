"""Pure-Python coordinator fallback (same line protocol as the native
server in ``hetu_tpu/csrc/coordinator.cpp``) — used where no C++
toolchain exists. Reference analogue: ``rpc/heturpc_polling_server.py``.
"""

from __future__ import annotations

import json
import socket
import socketserver
import threading
import time
from typing import Optional


#: serving verbs forwarded to hetu_tpu/serving/server.py — duplicated
#: here (instead of imported) so the bare coordinator stays importable
#: without jax; tests/test_fleet.py asserts this mirrors
#: ``serving.server.SERVING_COMMANDS``.
_SERVING_VERBS = ("SUBMIT", "RESULT", "GENERATE",
                  "FLEET", "DRAIN", "RESUME",
                  "ESTATUS", "CANCELQ", "EVICT", "PREFILL",
                  "SWAPWEIGHTS", "STOPENGINE",
                  "DUMPOBS", "FLEETMETRICS",
                  "KVEXPORT", "KVIMPORT", "KVREPL", "KVFETCH",
                  "KVBUDDY")


def _rpc_server_observe(verb: str, dur_ms: float,
                        n_in: int, n_out: int) -> None:
    """Server-end wire instrumentation (ISSUE 16): per-verb handling
    latency + payload bytes. ``dir`` uses in/out here (the client uses
    tx/rx) so both ends can share one registry in a single-process
    test without colliding."""
    from hetu_tpu import telemetry
    reg = telemetry.get_registry()
    reg.histogram(
        "rpc_server_verb_ms",
        "server-side handling ms per line-protocol verb (parse to "
        "reply write)").observe(dur_ms, verb=verb)
    c = reg.counter(
        "rpc_payload_bytes_total",
        "line-protocol bytes by verb and direction (client: tx/rx, "
        "server: in/out)")
    c.inc(n_in, verb=verb, dir="in")
    c.inc(n_out, verb=verb, dir="out")


class _State:
    def __init__(self):
        self.lock = threading.Lock()
        self.ranks: dict[str, int] = {}
        self.kv: dict[str, str] = {}
        self.beats: dict[str, float] = {}
        self.barriers: dict[str, dict] = {}


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        st: _State = self.server.state  # type: ignore[attr-defined]
        token: str = self.server.token  # type: ignore[attr-defined]
        authed = False
        first = True
        while True:
            line = self.rfile.readline()
            if not line:
                return
            if first:
                first = False
                # protocol sniff (ISSUE 19): a client whose first line
                # is the stream hello flips this connection into the
                # length-framed multiplexed mode; everything else stays
                # on the unchanged line protocol.
                if line.split(b" ", 1)[0].rstrip() == b"HSTRM1":
                    return self._stream_session(line)
            parts = line.decode().strip().split()
            if not parts:
                continue
            cmd, args = parts[0], parts[1:]
            # wire instrumentation window: _send() closes it (BARRIER
            # deliberately includes its wait — that IS its wire cost)
            self._verb, self._t0, self._rx_bytes = \
                cmd, time.perf_counter(), len(line)
            # auth gate (same contract as coordinator.cpp): PING stays
            # open for liveness probes, everything else needs the token
            if token and cmd != "PING" and not authed:
                import hmac
                if cmd == "AUTH" and args \
                        and hmac.compare_digest(args[0], token):
                    authed = True
                    self._send("OK")
                    continue
                self._send("ERR bad token" if cmd == "AUTH"
                           else "ERR auth required")
                return                       # close the connection
            if cmd == "AUTH":
                self._send("OK")             # no-token / already authed
            elif cmd == "RANK":
                with st.lock:
                    r = st.ranks.setdefault(args[0], len(st.ranks))
                self._send(f"RANK {r}")
            elif cmd == "SET":
                with st.lock:
                    st.kv[args[0]] = args[1]
                self._send("OK")
            elif cmd == "GET":
                with st.lock:
                    v = st.kv.get(args[0])
                self._send("NONE" if v is None else f"VAL {v}")
            elif cmd == "BEAT":
                with st.lock:
                    st.beats[args[0]] = time.monotonic()
                # a fleet front door forwards replica beats into the
                # attached Router's staleness tracking (remote engine
                # processes beat their own name; unknown names are
                # training workers — ignored by the router)
                serving = getattr(self.server, "serving", None)
                if serving is not None and hasattr(serving, "heartbeat"):
                    try:
                        serving.heartbeat(args[0])
                    except Exception:       # noqa: BLE001
                        pass
                self._send("OK")
            elif cmd == "STATUS":
                timeout = int(args[0]) / 1e3
                now = time.monotonic()
                with st.lock:
                    alive = [n for n, t in st.beats.items()
                             if now - t <= timeout]
                    dead = [n for n, t in st.beats.items()
                            if now - t > timeout]
                self._send(f"ALIVE {','.join(alive)} DEAD "
                           f"{','.join(dead)}")
            elif cmd == "BARRIER":
                name, target, who = args[0], int(args[1]), args[2]
                with st.lock:
                    b = st.barriers.setdefault(
                        name, {"event": threading.Event(), "who": set()})
                    b["who"].add(who)
                    if len(b["who"]) >= target:
                        b["event"].set()
                        st.barriers.pop(name, None)
                        ev = b["event"]
                    else:
                        ev = b["event"]
                ev.wait()
                self._send("OK")
            elif cmd in _SERVING_VERBS:
                # serving-plane verbs (hetu_tpu/serving/server.py) —
                # lazy import keeps the bare coordinator jax-free.
                # ``serving`` may be one ServingEngine or a fleet
                # Router (FLEET/DRAIN/RESUME are router-only; the
                # ESTATUS.. engine-process verbs drive one replica).
                from hetu_tpu.serving.server import handle_serving_command
                resp = handle_serving_command(
                    getattr(self.server, "serving", None), cmd, args)
                self._send(resp or "ERR unknown command")
            elif cmd == "HEALTHZ":
                # live health document: SLO state, watchdog trips,
                # serving queue/occupancy (telemetry/slo.health_status)
                import urllib.parse

                from hetu_tpu.telemetry.slo import health_status
                serving = getattr(self.server, "serving", None)
                doc = health_status(
                    serving=serving,
                    slo=getattr(serving, "slo", None))
                if hasattr(serving, "fleet_healthz"):
                    # Router front door: embed the federated rollup
                    # that names the degraded replica (ISSUE 16)
                    try:
                        doc["fleet"] = serving.fleet_healthz()
                    except Exception:       # noqa: BLE001
                        pass
                self._send("VAL " + urllib.parse.quote(
                    json.dumps(doc, separators=(",", ":")), safe=""))
            elif cmd == "METRICS":
                # Prometheus text exposition of the process-global
                # registry (URL-quoted onto the one-line protocol)
                import urllib.parse

                from hetu_tpu import telemetry
                self._send("VAL " + urllib.parse.quote(
                    telemetry.get_registry().to_prometheus(), safe=""))
            elif cmd == "PING":
                self._send("PONG")
            elif cmd == "SHUTDOWN":
                self._send("OK")
                threading.Thread(
                    target=self.server.shutdown, daemon=True).start()
                return
            else:
                self._send("ERR unknown command")

    # -- streaming mode (ISSUE 19) -------------------------------------------
    def _stream_session(self, hello: bytes) -> None:
        """One persistent multiplexed connection: frames in, frames
        out (``rpc/stream.py`` documents the kinds). The read loop
        stays single-threaded; one-shot verbs run on short-lived
        threads (a slow GENERATE must not block the channel) and every
        subscription gets its own drainer thread pulling events off
        the engine's bounded queue — all socket writes serialize on
        one lock, so frames never tear."""
        from hetu_tpu.rpc.stream import read_frame, write_frame
        wlock = threading.Lock()
        parts = hello.decode(errors="replace").split()
        token: str = self.server.token  # type: ignore[attr-defined]
        if token:
            import hmac
            if len(parts) < 2 or not hmac.compare_digest(parts[1],
                                                         token):
                try:
                    write_frame(self.wfile, wlock,
                                {"k": "err", "sid": 0,
                                 "msg": "auth required"},
                                direction="out")
                except (OSError, ValueError):
                    pass
                return
        write_frame(self.wfile, wlock, {"k": "hello", "sid": 0, "v": 1},
                    direction="out")
        try:
            from hetu_tpu.rpc.stream import _count_connect
            _count_connect("server")
        except Exception:                             # noqa: BLE001
            pass
        subs: dict[int, object] = {}
        closed = threading.Event()
        try:
            while True:
                fr = read_frame(self.rfile, direction="in")
                if fr is None:
                    return
                kind = fr.get("k")
                sid = int(fr.get("sid", 0))
                if kind == "req":
                    threading.Thread(
                        target=self._stream_req, args=(fr, wlock),
                        daemon=True).start()
                elif kind == "sub":
                    self._stream_sub(fr, wlock, subs, closed)
                elif kind == "stream":
                    self._stream_submit(fr, wlock, subs, closed)
                elif kind == "unsub":
                    sub = subs.pop(sid, None)
                    if sub is not None:
                        sub.close()
                elif kind == "ping":
                    write_frame(self.wfile, wlock,
                                {"k": "pong", "sid": sid},
                                direction="out")
        except (OSError, ValueError):
            return                      # client gone / corrupt stream
        finally:
            closed.set()
            for sub in subs.values():
                try:
                    sub.close()
                except Exception:                     # noqa: BLE001
                    pass

    def _stream_req(self, fr: dict, wlock: threading.Lock) -> None:
        """One multiplexed one-shot verb: same dispatch as the line
        loop for the serving family (+ PING), answered by a ``res``
        frame carrying the exact response line."""
        from hetu_tpu.rpc.stream import write_frame
        line = str(fr.get("line", ""))
        parts = line.strip().split()
        t0 = time.perf_counter()
        if not parts:
            resp = "ERR empty"
        elif parts[0] == "PING":
            resp = "PONG"
        elif parts[0] in _SERVING_VERBS:
            from hetu_tpu.serving.server import handle_serving_command
            try:
                resp = handle_serving_command(
                    getattr(self.server, "serving", None),
                    parts[0], parts[1:]) or "ERR unknown command"
            except Exception as e:                    # noqa: BLE001
                resp = f"ERR {type(e).__name__}: {e}"
        else:
            resp = "ERR verb not multiplexable"
        try:
            write_frame(self.wfile, wlock,
                        {"k": "res", "sid": fr.get("sid", 0),
                         "line": resp}, direction="out")
            if parts:
                _rpc_server_observe(
                    parts[0], (time.perf_counter() - t0) * 1e3,
                    n_in=len(line), n_out=len(resp))
        except (OSError, ValueError):
            pass                        # connection died mid-reply

    def _start_sub(self, req, fr: dict, wlock: threading.Lock,
                   subs: dict, closed: threading.Event) -> None:
        """Attach one subscription (shared by ``sub`` and ``stream``):
        the serving object replays from the requested token offset,
        then a drainer thread forwards events as they land."""
        from hetu_tpu.rpc.stream import write_frame
        serving = getattr(self.server, "serving", None)
        sid = int(fr.get("sid", 0))
        off = max(0, int(fr.get("off", 0)))
        if serving is None or not hasattr(serving, "stream_subscribe"):
            write_frame(self.wfile, wlock,
                        {"k": "drop", "sid": sid,
                         "reason": "unsupported"}, direction="out")
            return
        try:
            sub = serving.stream_subscribe(req, offset=off)
        except Exception as e:                        # noqa: BLE001
            write_frame(self.wfile, wlock,
                        {"k": "err", "sid": sid,
                         "msg": f"{type(e).__name__}: {e}"},
                        direction="out")
            return
        try:
            from hetu_tpu.serving.streaming import count_subscribe
            count_subscribe("resume" if off > 0 else "new")
        except Exception:                             # noqa: BLE001
            pass
        subs[sid] = sub
        threading.Thread(
            target=self._stream_drain, args=(sid, sub, wlock, closed),
            daemon=True,
            name=f"stream-drain-{getattr(req, 'id', '?')}").start()

    def _stream_sub(self, fr: dict, wlock: threading.Lock,
                    subs: dict, closed: threading.Event) -> None:
        from hetu_tpu.rpc.stream import write_frame
        serving = getattr(self.server, "serving", None)
        sid = int(fr.get("sid", 0))
        req = None
        if serving is not None:
            req = getattr(serving, "_requests_by_id", {}).get(
                int(fr.get("id", -1)))
        if req is None:
            write_frame(self.wfile, wlock,
                        {"k": "drop", "sid": sid,
                         "reason": "unknown_request"}, direction="out")
            return
        self._start_sub(req, fr, wlock, subs, closed)

    def _stream_submit(self, fr: dict, wlock: threading.Lock,
                       subs: dict, closed: threading.Event) -> None:
        """``stream`` = SUBMIT (idempotency-keyed payload) + subscribe
        in one frame, acked with the request/trace ids before the
        first event."""
        from hetu_tpu.rpc.stream import write_frame
        serving = getattr(self.server, "serving", None)
        sid = int(fr.get("sid", 0))
        if serving is None:
            write_frame(self.wfile, wlock,
                        {"k": "err", "sid": sid,
                         "msg": "serving disabled"}, direction="out")
            return
        from hetu_tpu.serving.server import handle_stream_submit
        req, err = handle_stream_submit(serving,
                                        str(fr.get("payload", "")))
        if err is not None:
            write_frame(self.wfile, wlock,
                        {"k": "err", "sid": sid, "msg": err},
                        direction="out")
            return
        write_frame(self.wfile, wlock,
                    {"k": "ack", "sid": sid, "id": int(req.id),
                     "trace": req.trace_id}, direction="out")
        self._start_sub(req, fr, wlock, subs, closed)

    def _stream_drain(self, sid: int, sub, wlock: threading.Lock,
                      closed: threading.Event) -> None:
        """Per-subscription drainer: pulls events OFF the step lock's
        bounded queue and writes frames. A queue overflow (slow
        consumer) sends one ``drop`` frame and stops — the client
        falls back to RESULT polling."""
        from hetu_tpu.rpc.stream import write_frame
        try:
            while not closed.is_set():
                ev = sub.get(timeout=0.25)
                if ev is None:
                    if sub.dropped:
                        write_frame(self.wfile, wlock,
                                    {"k": "drop", "sid": sid,
                                     "reason": "slow"},
                                    direction="out")
                        return
                    if sub.closed:
                        return
                    continue
                write_frame(self.wfile, wlock,
                            {"k": "ev", "sid": sid, **ev},
                            direction="out")
                if ev.get("done") or ev.get("end"):
                    return
        except (OSError, ValueError):
            sub.close()                 # connection gone — stop feeding

    def _send(self, s: str):
        self.wfile.write((s + "\n").encode())
        self.wfile.flush()
        verb = getattr(self, "_verb", None)
        if verb is not None:
            self._verb = None
            try:
                _rpc_server_observe(
                    verb, (time.perf_counter() - self._t0) * 1e3,
                    n_in=self._rx_bytes, n_out=len(s) + 1)
            except Exception:               # noqa: BLE001
                pass    # instrumentation must never break the protocol


class PyCoordinatorServer:
    def __init__(self, port: int, bind: str = "127.0.0.1",
                 token: str = "", serving=None):
        self.bind = bind
        self.port = port
        self.token = token
        self.serving = serving   # optional ServingEngine or fleet
        #                          Router (SUBMIT/.../FLEET verbs)
        self._server: Optional[socketserver.ThreadingTCPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._ready = threading.Event()

    def start(self):
        socketserver.ThreadingTCPServer.allow_reuse_address = True
        self._server = socketserver.ThreadingTCPServer(
            (self.bind, self.port), _Handler)
        self._server.state = _State()  # type: ignore[attr-defined]
        self._server.token = self.token  # type: ignore[attr-defined]
        self._server.serving = self.serving  # type: ignore[attr-defined]
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()
        self._ready.set()

    def wait_ready(self, timeout: float = 10.0):
        self._ready.wait(timeout)

    def stop(self):
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
