"""Persistent multiplexed stream channel over the coordinator port.

The line protocol (``py_server.py`` / ``csrc/coordinator.cpp``) costs
one round trip per RESULT poll — the fleet's dominant dispatch tax
(BENCH_fleet.json). This module adds the push lane: a client opens ONE
long-lived socket per (client, server) pair, sends the hello line
``HSTRM1 [token]\\n`` (sniffable by the server's existing
``readline()``), and both directions switch to length-framed compact
JSON messages tagged with a stream id:

    4-byte big-endian length | {"k": <kind>, "sid": <id>, ...}

Client → server kinds:

- ``req``     — one multiplexed one-shot verb (``line`` = the same
  text a line-protocol client would send); answered by ``res``.
- ``sub``     — subscribe to request ``id`` from token offset ``off``;
  the server replays everything from that offset, so reconnect loses
  nothing and replays nothing.
- ``stream``  — SUBMIT (``payload`` = the URL-quoted SUBMIT payload,
  idempotency key + traceparent included) and subscribe in one frame;
  answered by ``ack`` (request id + trace id) then ``ev`` frames.
- ``unsub``   — drop one subscription.
- ``ping``    — liveness; answered by ``pong``.

Server → client kinds:

- ``hello``   — auth accepted, stream mode live.
- ``res`` / ``ack`` / ``pong`` — responses, matched by ``sid``.
- ``ev``      — one token event: ``off`` (per-request monotonic token
  offset), ``toks`` (newly committed ids), ``first``/``done`` markers,
  ``result`` (trailing timing payload on the final frame), ``end``
  (out-of-band exit: evicted/cancelled — the subscriber falls back).
- ``drop``    — subscription killed server-side (slow consumer,
  unknown request, unsupported) — the client falls back to RESULT
  polls and may resubscribe-at-offset.
- ``err``     — request-level failure.

One-shot verbs keep working unchanged on the same listener: the first
bytes decide the protocol, nothing else changes.
"""

from __future__ import annotations

import itertools
import json
import os
import socket
import threading
from typing import Callable, Optional

MAGIC = "HSTRM1"

#: frame size ceiling — a corrupt length prefix must not allocate GBs
MAX_FRAME = 64 * 1024 * 1024


def _count_frame(kind: str, direction: str) -> None:
    """Wire instrumentation (never breaks the protocol): stream frames
    by kind and direction — client uses tx/rx, server in/out, matching
    ``rpc_payload_bytes_total``'s convention."""
    try:
        from hetu_tpu import telemetry
        telemetry.get_registry().counter(
            "rpc_stream_frames_total",
            "stream-channel frames by kind and direction (client: "
            "tx/rx, server: in/out)").inc(kind=kind, dir=direction)
    except Exception:                                 # noqa: BLE001
        pass


def _count_connect(role: str) -> None:
    try:
        from hetu_tpu import telemetry
        telemetry.get_registry().counter(
            "rpc_stream_connects_total",
            "stream-channel connections established, by role").inc(
            role=role)
    except Exception:                                 # noqa: BLE001
        pass


def write_frame(wfile, lock: threading.Lock, obj: dict, *,
                direction: str) -> None:
    """Serialize one frame onto ``wfile`` (length prefix + compact
    JSON). ``lock`` serializes concurrent writers on one connection —
    a torn frame desyncs everything after it."""
    body = json.dumps(obj, separators=(",", ":")).encode()
    if len(body) > MAX_FRAME:
        raise ValueError(f"frame too large: {len(body)} bytes")
    buf = len(body).to_bytes(4, "big") + body
    with lock:
        wfile.write(buf)
        wfile.flush()
    _count_frame(str(obj.get("k", "?")), direction)


def read_frame(rfile, *, direction: str) -> Optional[dict]:
    """Read one frame from ``rfile``; None on clean EOF. Raises
    ValueError on a corrupt length prefix (caller closes the
    connection — there is no resynchronizing a framed stream)."""
    head = rfile.read(4)
    if not head:
        return None
    if len(head) < 4:
        raise ValueError("truncated frame header")
    n = int.from_bytes(head, "big")
    if not 0 < n <= MAX_FRAME:
        raise ValueError(f"bad frame length: {n}")
    body = rfile.read(n)
    if len(body) < n:
        raise ValueError("truncated frame body")
    fr = json.loads(body)
    if not isinstance(fr, dict):
        raise ValueError("frame is not an object")
    _count_frame(str(fr.get("k", "?")), direction)
    return fr


class StreamChannel:
    """Client end of one persistent multiplexed connection.

    A single background reader thread demultiplexes inbound frames:
    ``res``/``ack``/``pong`` resolve the waiter parked on their stream
    id, ``ev``/``drop``/``err`` frames go to the subscription's sink
    callable (invoked ON the reader thread — sinks must be quick and
    never block, exactly like the engine's token callbacks). When the
    socket dies every sink receives a final ``{"k": "lost"}`` event,
    which is the subscriber's cue to fall back to RESULT polling and
    resubscribe-at-offset on a fresh channel.
    """

    def __init__(self, port: int, host: str = "127.0.0.1", *,
                 token: Optional[str] = None,
                 connect_timeout: float = 10.0):
        self._host, self._port = host, int(port)
        tok = token if token is not None \
            else os.environ.get("HETU_COORD_TOKEN") or ""
        self._sock = socket.create_connection(
            (host, int(port)), timeout=connect_timeout)
        self._rfile = self._sock.makefile("rb")
        self._wfile = self._sock.makefile("wb")
        self._wlock = threading.Lock()
        self._lock = threading.Lock()
        self._sids = itertools.count(1)
        self._sinks: dict[int, Callable[[dict], None]] = {}
        self._waiters: dict[int, tuple[threading.Event, dict]] = {}
        self.alive = False
        hello = f"{MAGIC} {tok}".rstrip() + "\n"
        self._sock.sendall(hello.encode())
        first = read_frame(self._rfile, direction="rx")
        if first is None or first.get("k") != "hello":
            self._close_sock()
            raise ConnectionError(
                f"stream hello rejected: {first!r}")
        self._sock.settimeout(None)    # reader blocks until frames/EOF
        self.alive = True
        _count_connect("client")
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True,
            name=f"stream-chan-{port}")
        self._reader.start()

    # -- plumbing -----------------------------------------------------------
    def _send(self, obj: dict) -> None:
        if not self.alive:
            raise ConnectionError("stream channel is down")
        try:
            write_frame(self._wfile, self._wlock, obj, direction="tx")
        except (OSError, ValueError):
            self._down()
            raise

    def _read_loop(self) -> None:
        try:
            while True:
                fr = read_frame(self._rfile, direction="rx")
                if fr is None:
                    break
                self._dispatch(fr)
        except (OSError, ValueError, json.JSONDecodeError):
            pass
        self._down()

    def _dispatch(self, fr: dict) -> None:
        sid = int(fr.get("sid", 0))
        kind = fr.get("k")
        if kind in ("res", "ack", "pong", "err"):
            with self._lock:
                w = self._waiters.pop(sid, None)
            if w is not None:
                w[1]["fr"] = fr
                w[0].set()
                return
            if kind != "err":
                return                 # late response, waiter gave up
        with self._lock:
            sink = self._sinks.get(sid)
            terminal = kind in ("drop", "err") or (
                kind == "ev" and (fr.get("done") or fr.get("end")))
            if terminal:
                self._sinks.pop(sid, None)
        if sink is not None:
            try:
                sink(fr)
            except Exception:                         # noqa: BLE001
                pass                   # a broken sink must not kill
            #                            the channel for its siblings

    def _down(self) -> None:
        with self._lock:
            if not self.alive and not self._sinks and not self._waiters:
                return
            self.alive = False
            sinks = list(self._sinks.items())
            waiters = list(self._waiters.values())
            self._sinks.clear()
            self._waiters.clear()
        for ev, box in waiters:
            box["fr"] = {"k": "err", "msg": "stream channel lost"}
            ev.set()
        for sid, sink in sinks:
            try:
                sink({"k": "lost", "sid": sid})
            except Exception:                         # noqa: BLE001
                pass
        self._close_sock()

    def _close_sock(self) -> None:
        # shutdown FIRST: it unblocks a reader parked in recv (a bare
        # close of a buffered reader another thread is blocked inside
        # deadlocks on the buffer's internal lock)
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        for closer in (self._wfile, self._sock):
            try:
                closer.close()
            except OSError:
                pass
        # _rfile belongs to the reader thread; anyone else closing it
        # races the blocked read on the buffer lock. The shutdown above
        # EOFs the reader, which drops through here itself on exit.
        reader = getattr(self, "_reader", None)
        if reader is None or reader is threading.current_thread():
            try:
                self._rfile.close()
            except OSError:
                pass

    # -- API ----------------------------------------------------------------
    def request(self, line: str, *, timeout: float = 30.0) -> str:
        """One multiplexed one-shot verb; returns the response line
        (exactly what the line protocol would answer). Concurrent
        requests interleave freely on the shared socket."""
        sid = next(self._sids)
        done, box = threading.Event(), {}
        with self._lock:
            self._waiters[sid] = (done, box)
        try:
            self._send({"k": "req", "sid": sid, "line": line})
        except Exception:
            with self._lock:
                self._waiters.pop(sid, None)
            raise
        if not done.wait(timeout):
            with self._lock:
                self._waiters.pop(sid, None)
            raise TimeoutError(f"stream request timed out: {line!r}")
        fr = box["fr"]
        if fr.get("k") == "err":
            raise ConnectionError(
                f"stream request failed: {fr.get('msg')}")
        return str(fr.get("line", ""))

    def subscribe(self, req_id: int, *, offset: int = 0,
                  sink: Callable[[dict], None]) -> int:
        """Subscribe to token events for ``req_id`` starting at token
        ``offset`` — the server replays everything from there, so a
        reconnecting subscriber passes the count it already holds and
        the stream resumes seamlessly. Returns the stream id."""
        sid = next(self._sids)
        with self._lock:
            self._sinks[sid] = sink
        try:
            self._send({"k": "sub", "sid": sid, "id": int(req_id),
                        "off": int(offset)})
        except Exception:
            with self._lock:
                self._sinks.pop(sid, None)
            raise
        return sid

    def stream_submit(self, payload: str, *,
                      sink: Callable[[dict], None],
                      offset: int = 0,
                      timeout: float = 30.0) -> dict:
        """SUBMIT + subscribe in one frame. ``payload`` is the same
        URL-quoted SUBMIT payload the line protocol carries (the
        idempotency key and traceparent ride inside it, so a retried
        delivery joins the original request). Returns
        ``{"id", "trace", "sid"}`` once the server acks."""
        sid = next(self._sids)
        done, box = threading.Event(), {}
        with self._lock:
            self._sinks[sid] = sink
            self._waiters[sid] = (done, box)
        try:
            self._send({"k": "stream", "sid": sid, "payload": payload,
                        "off": int(offset)})
        except Exception:
            with self._lock:
                self._sinks.pop(sid, None)
                self._waiters.pop(sid, None)
            raise
        if not done.wait(timeout):
            with self._lock:
                self._sinks.pop(sid, None)
                self._waiters.pop(sid, None)
            raise TimeoutError("stream submit timed out")
        fr = box["fr"]
        if fr.get("k") != "ack":
            with self._lock:
                self._sinks.pop(sid, None)
            raise RuntimeError(
                f"stream submit failed: {fr.get('msg', fr)}")
        return {"id": int(fr["id"]), "trace": fr.get("trace", ""),
                "sid": sid}

    def unsubscribe(self, sid: int) -> None:
        with self._lock:
            self._sinks.pop(sid, None)
        try:
            self._send({"k": "unsub", "sid": int(sid)})
        except Exception:                             # noqa: BLE001
            pass                        # channel already down — moot

    def ping(self, timeout: float = 5.0) -> bool:
        sid = next(self._sids)
        done, box = threading.Event(), {}
        with self._lock:
            self._waiters[sid] = (done, box)
        try:
            self._send({"k": "ping", "sid": sid})
        except Exception:                             # noqa: BLE001
            return False
        if not done.wait(timeout):
            with self._lock:
                self._waiters.pop(sid, None)
            return False
        return box["fr"].get("k") == "pong"

    def close(self) -> None:
        self._down()
