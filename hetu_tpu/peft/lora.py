"""LoRA: low-rank adapters over linear layers, multi-task capable.

Parity target: ``python/hetu/peft/lora`` — config, layer wrapper, model
injection, and multi-task ``MultiLoraModel`` (``peft/lora/model.py:6``,
used by the LobRA example). Functional JAX design: injection *mutates the
module tree* (modules are config objects), and a params-migration helper
moves the existing trained weights under ``"base"`` while initializing
adapter A/B factors — so a pretrained checkpoint keeps loading.

Multi-task: adapters carry a leading ``task`` dim; ``task_id`` selects one
at call time (the reference trains several LoRA tasks against one frozen
base).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp

from hetu_tpu.core.tree import flatten_with_paths, unflatten_from_paths
from hetu_tpu.nn.layers import Linear
from hetu_tpu.nn.module import Module, normal_init, zeros_init
from hetu_tpu.nn.parallel import ColumnParallelLinear, RowParallelLinear

_LINEAR_TYPES = (Linear, ColumnParallelLinear, RowParallelLinear)


@dataclasses.dataclass(frozen=True)
class LoraConfig:
    r: int = 8
    alpha: float = 16.0
    num_tasks: int = 1
    # regex matched against dotted module paths (e.g. "attn.q_proj")
    target_patterns: Sequence[str] = (r"\.(q_proj|k_proj|v_proj|"
                                      r"out_proj|fc_in|fc_out|gate_proj|"
                                      r"up_proj)$",)

    @property
    def scaling(self) -> float:
        return self.alpha / self.r


class LoraLinear(Module):
    """Wraps a Linear-like module: ``y = base(x) + scaling · (x A) B``.

    A: (tasks, in, r) init normal; B: (tasks, r, out) init zeros (adapter
    starts as identity). The base params live under ``params["base"]`` and
    are frozen by :func:`lora_trainable_mask`.
    """

    def __init__(self, base: Module, cfg: LoraConfig):
        super().__init__()
        self.base = base
        self.cfg = cfg
        in_f = base.in_features
        out_f = base.out_features
        self.param("lora_A", (cfg.num_tasks, in_f, cfg.r),
                   normal_init(0.02), axes=(None, "embed", None))
        self.param("lora_B", (cfg.num_tasks, cfg.r, out_f),
                   zeros_init(), axes=(None, None, None))

    def abstract_specs(self) -> dict:
        out = dict(self._param_specs)
        out["base"] = self.base.abstract_specs()
        return out

    def children(self):
        return {}  # base handled explicitly (nested under "base")

    def __call__(self, params, x, *, task_id: int | jnp.ndarray = 0):
        y = self.base(params["base"], x)
        a = params["lora_A"][task_id]
        b = params["lora_B"][task_id]
        dt = self.compute_dtype()
        delta = jnp.matmul(jnp.matmul(x.astype(dt), a.astype(dt)),
                           b.astype(dt))
        return y + self.cfg.scaling * delta


def _match(path: str, patterns: Sequence[str]) -> bool:
    return any(re.search(p, path) for p in patterns)


def inject_lora(model: Module, cfg: LoraConfig) -> list[str]:
    """Replace matching Linear-like children with LoraLinear wrappers
    (in place). Returns the dotted paths that were wrapped."""
    wrapped = []
    for path, mod in list(model.named_modules()):
        for name, child in list(vars(mod).items()):
            if name.startswith("_") or not isinstance(child,
                                                      _LINEAR_TYPES):
                continue
            child_path = f"{path}.{name}" if path else name
            if _match(child_path, cfg.target_patterns):
                setattr(mod, name, LoraLinear(child, cfg))
                wrapped.append(child_path)
    return wrapped


def wrap_params_for_lora(model: Module, params: Any, key: jax.Array,
                         dtype=None) -> Any:
    """Migrate an existing (pretrained) param tree into the post-injection
    structure: wrapped leaves move under ``"base"``, adapters initialize
    fresh. Call *after* :func:`inject_lora`."""
    old_flat = flatten_with_paths(params)
    fresh = model.init(key, dtype=dtype)  # correct structure + new A/B
    fresh_flat = flatten_with_paths(fresh)
    out = {}
    for path, leaf in fresh_flat.items():
        if path.endswith("lora_A") or path.endswith("lora_B"):
            out[path] = leaf
            continue
        base_path = path.replace(".base.", ".")
        out[path] = old_flat.get(base_path, leaf)
    return unflatten_from_paths(out)


def lora_trainable_mask(params: Any) -> Any:
    """Pytree of bools: True for adapter params, False for frozen base."""
    flat = flatten_with_paths(params)
    mask = {p: (p.endswith("lora_A") or p.endswith("lora_B"))
            for p in flat}
    return unflatten_from_paths(mask)


def merge_lora(model: Module, params: Any, *, task_id: int = 0) -> Any:
    """Fold adapters into base weights (W += scaling · A B) and return a
    param tree matching the *pre-injection* structure."""
    flat = flatten_with_paths(params)
    out = {}
    for path, leaf in flat.items():
        if path.endswith("lora_A") or path.endswith("lora_B"):
            continue
        if ".base." in f".{path}":
            prefix = path[:path.index("base.")]
            new_path = (prefix + path[path.index("base.") + 5:]) \
                .replace("..", ".")
            if path.endswith("weight"):
                a = jnp.asarray(flat[prefix + "lora_A"])
                b = jnp.asarray(flat[prefix + "lora_B"])
                scale = _first_lora_scaling(model)
                if a.ndim == 4:  # stacked blocks: (layers, tasks, in, r)
                    delta = jnp.einsum("lir,lro->lio", a[:, task_id],
                                       b[:, task_id])
                else:            # (tasks, in, r)
                    delta = a[task_id] @ b[task_id]
                leaf = leaf + (scale * delta).astype(leaf.dtype)
            out[new_path] = leaf
        else:
            out[path] = leaf
    return unflatten_from_paths(out)


def _first_lora_scaling(model: Module) -> float:
    for _, mod in model.named_modules():
        if isinstance(mod, LoraLinear):
            return mod.cfg.scaling
    return 1.0
