"""Parameter-efficient fine-tuning.

Parity target: ``python/hetu/peft`` (LoRA config/layer/model injection,
multi-task ``MultiLoraModel`` — ``peft/lora/model.py:6``).
"""

from hetu_tpu.peft.lora import (
    LoraConfig, LoraLinear, inject_lora, merge_lora, lora_trainable_mask,
    wrap_params_for_lora,
)

__all__ = [
    "LoraConfig", "LoraLinear", "inject_lora", "merge_lora",
    "lora_trainable_mask", "wrap_params_for_lora",
]
