"""Trainer: the user-facing training engine.

Parity target: ``python/hetu/engine/trainer.py:66`` — builds the graph
under autocast (:187-244), runs steps with a strategy id (:279-323), packs
data, checkpoints, and hot-switches strategies (``examples/hotspa``).
TPU-native shape: a Trainer owns (model, optimizer, TrainPlan, TrainState);
``set_strategy`` recompiles the plan and re-shards the live state
(HotSPa switch = ``parallel.switch.switch_strategy``); data arrives as an
iterator of host batches (``hetu_tpu.data.build_data_loader``).
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Iterable, Iterator, Optional

import jax
import jax.numpy as jnp

from hetu_tpu import telemetry
from hetu_tpu.core.dtypes import BF16_COMPUTE, FP32, Policy, autocast
from hetu_tpu.engine.state import TrainState
from hetu_tpu.engine.train_step import (
    CachedStep, StepCache, compile_strategy, get_step_cache, init_state,
    trace_total,
)
from hetu_tpu.optim.base import Transform
from hetu_tpu.parallel.strategy import Strategy
from hetu_tpu.parallel.switch import switch_strategy
from hetu_tpu.telemetry import GoodputAccountant
from hetu_tpu.utils.checkpoint import (
    CheckpointWriter, load_checkpoint, save_checkpoint,
)
from hetu_tpu.utils.logging import MetricsLogger, get_logger


@dataclasses.dataclass
class TrainerConfig:
    """Reference: ``engine/trainer_config.py`` TrainingConfig."""

    total_steps: int = 1000
    log_every: int = 10
    ckpt_dir: Optional[str] = None
    ckpt_every: int = 0          # 0 = only final
    async_ckpt: bool = True
    seed: int = 0
    precision: str = "bf16"      # "bf16" | "fp32"
    attn_impl: str = "auto"
    distributed_ckpt: bool = False   # per-host shard files, no gather
    delta_ckpt: bool = False     # distributed saves after the first one
                                 # rewrite only CHANGED pieces (content
                                 # hashes; unchanged pieces reference
                                 # the previous save's step-stamped
                                 # file) — docs/ELASTICITY.md
    prefetch: int = 2            # device-prefetch depth for train();
                                 # 0 disables (reference: async C++
                                 # dataloader + dedicated H2D stream)
    eval_every: int = 0          # validation cadence for train(); 0 = off
                                 # (needs eval_batches passed to train)
    telemetry: bool = False      # turn the global telemetry switch ON at
                                 # construction (spans + metric registry;
                                 # docs/OBSERVABILITY.md). Off: the
                                 # instrumented call sites cost <1% of
                                 # the step loop (asserted in tests).
    trace_dir: Optional[str] = None
                                 # where train() exports artifacts when
                                 # telemetry is enabled: trace.json
                                 # (Perfetto) + telemetry.jsonl (unified
                                 # span/metric/goodput records)
    peak_flops: Optional[float] = None
                                 # per-chip peak for MFU in the goodput
                                 # report; None = report goodput only
    step_cache: bool = True      # memoize compiled (plan, step, eval)
                                 # per strategy in the shared StepCache
                                 # so A→B→A switching never re-traces;
                                 # False rebuilds on every set_strategy
                                 # (the cache-disabled baseline for
                                 # goodput A/B runs — docs/PERFORMANCE.md)
    compile_cache_dir: Optional[str] = None
                                 # persistent XLA compilation cache dir
                                 # (engine.precompile.enable_persistent_
                                 # compilation_cache): restarts re-trace
                                 # but skip the XLA compile. Also honors
                                 # $HETU_COMPILE_CACHE_DIR when unset.
    comm_overlap: str = "auto"   # "auto": wire XLA's async-collective +
                                 # latency-hiding-scheduler flags on TPU
                                 # (parallel.overlap.enable_xla_overlap)
                                 # — the automatic comm/compute overlap
                                 # fallback when the manual ring
                                 # (Strategy.tp_overlap="ring") is off;
                                 # "off": leave XLA_FLAGS alone. Only
                                 # effective before backend init.
    aggregate_every: int = 0     # cadence (steps) for publishing this
                                 # rank's metric snapshot through
                                 # telemetry.cluster_aggregate during
                                 # train() (multi-host: pass dist= to
                                 # the Trainer; single-process runs
                                 # reduce locally). 0 = off. Aggregates
                                 # land in telemetry.jsonl as
                                 # kind=cluster_aggregate records.
    watchdog: bool = False       # hang watchdog around train(): a
                                 # monitor thread trips when no step
                                 # completes within watchdog_factor x
                                 # the rolling median step interval,
                                 # dumps the flight record + all-thread
                                 # stacks to trace_dir (or cwd) and
                                 # bumps watchdog_trips_total
                                 # (docs/OBSERVABILITY.md "Flight
                                 # recorder & watchdog")
    watchdog_factor: float = 8.0
    watchdog_min_timeout_s: float = 30.0
    slo: bool = False            # SLO/anomaly engine on the log
                                 # cadence: step-time regression, loss
                                 # spike, grad-norm spike against
                                 # rolling baselines; alerts are logged,
                                 # counted (slo_alerts_total) and
                                 # written to telemetry.jsonl as
                                 # kind=slo_alert records
    seq_buckets: Optional[tuple] = None
                                 # seq-len bucket ladder (shape plane,
                                 # docs/PERFORMANCE.md): each host batch
                                 # is snapped to the smallest bucket >=
                                 # its max REAL length
                                 # (data.bucket.ShapeBucketer) and
                                 # routed through a per-(strategy,
                                 # bucket) StepCache entry — a ragged
                                 # epoch compiles at most len(buckets)
                                 # step programs instead of one per
                                 # distinct width, and pad FLOPs drop
                                 # from pad-to-max to pad-to-bucket
                                 # (counters data_padding_tokens_total /
                                 # data_bucket_hits_total). None = off
                                 # (exact historical behavior).

    def policy(self) -> Policy:
        return BF16_COMPUTE if self.precision == "bf16" else FP32


class Trainer:
    def __init__(self, model, opt: Transform, strategy: Strategy,
                 config: Optional[TrainerConfig] = None, devices=None,
                 step_cache: Optional[StepCache] = None, dist=None):
        self.model = model
        self.opt = opt
        self.config = config if config is not None else TrainerConfig()
        self.devices = devices
        # dist: a rpc.launcher.DistContext (or anything with .client /
        # .rank / .num_processes) — enables the cross-rank telemetry
        # aggregation cadence (config.aggregate_every) on multi-host runs
        self._dist = dist
        if self.config.comm_overlap != "off":
            # XLA-side comm/compute overlap: best-effort (only lands
            # before backend init, TPU-only flags), the data-plane
            # fallback when the manual ring is not in force
            from hetu_tpu.parallel.overlap import enable_xla_overlap
            enable_xla_overlap()
        self.state: Optional[TrainState] = None
        self.plan = None
        self._step_fn = None
        self._eval_fn = None
        self._live_prefetcher = None   # re-pointed on mid-run hot switch
        self._ckpt_writer: Optional[CheckpointWriter] = None
        if self.config.compile_cache_dir \
                or "HETU_COMPILE_CACHE_DIR" in os.environ:
            from hetu_tpu.engine.precompile import (
                enable_persistent_compilation_cache)
            enable_persistent_compilation_cache(
                self.config.compile_cache_dir)
        if self.config.telemetry:
            telemetry.enable(True)
        self.tracer = telemetry.get_tracer()
        self.registry = telemetry.get_registry()
        # production-observability side-band (telemetry/flight.py,
        # telemetry/slo.py): the flight recorder is always on; the
        # watchdog and SLO engine are created on demand by train()
        self.flight = telemetry.get_flight_recorder()
        self.slo: Optional[telemetry.SLOEngine] = None
        if self.config.slo:
            self.slo = telemetry.default_training_rules(
                telemetry.SLOEngine(self.registry))
        self.goodput: Optional[GoodputAccountant] = None
        # JSONL export high-water mark; keyed to the tracer epoch so a
        # telemetry.reset() between runs restarts the window instead of
        # silently dropping the next run's spans
        self._spans_exported = 0
        self._spans_epoch = self.tracer.epoch
        metrics_path = None
        if self.config.trace_dir:
            os.makedirs(self.config.trace_dir, exist_ok=True)
            metrics_path = os.path.join(self.config.trace_dir,
                                        "telemetry.jsonl")
        # one unified record per log interval: training metrics + the
        # registry snapshot ride the same JSONL stream
        self.metrics = MetricsLogger(path=metrics_path,
                                     registry=self.registry)
        # step cache: one compiled (plan, step, eval) per strategy, so
        # switching A -> B -> A reuses executables (the reference's
        # ExecGraphPlan pool, define_and_run_graph.h:23-64). Shared with
        # engine.precompile's background AOT worker by default, so
        # planner-announced candidate strategies are already warm when
        # set_strategy asks for them.
        self.cache = step_cache if step_cache is not None \
            else get_step_cache()
        # kept as an alias: tests / callers may inspect the pool size
        self._plan_cache = self.cache
        # shape plane: bucketed steps (config.seq_buckets) — host batches
        # are snapped to the ladder and each bucket gets its own
        # StepCache entry (cleared on strategy change)
        self.bucketer = None
        if self.config.seq_buckets:
            from hetu_tpu.data.bucket import SeqLenBuckets, ShapeBucketer
            self.bucketer = ShapeBucketer(
                SeqLenBuckets(sizes=self.config.seq_buckets))
        self._bucket_entries: dict = {}
        self.set_strategy(strategy)

    # -- strategy / hot switching ------------------------------------------
    def _cache_key(self, strategy, bucket: int = 0):
        return self.cache.key_for(
            self.model, self.opt, strategy,
            attn_impl=self.config.attn_impl, donate=True,
            policy_key=self.config.precision, devices=self.devices,
            bucket=bucket)

    def set_strategy(self, strategy):
        """Compile the plan for ``strategy`` (a :class:`Strategy` or a
        Malleus :class:`~hetu_tpu.parallel.hetero.HeteroStrategy`); if
        training is live, hot-switch the full train state — params AND
        optimizer moments — onto the new layout (HotSPa; hetero via the
        homo<->hetero converters).

        The compiled artifacts come from the :class:`StepCache`: a
        strategy seen before (or pre-compiled by ``precompile()`` /
        ``engine.precompile``) makes the switch pure data movement —
        cache lookup + one ``device_put`` of the live state."""
        from hetu_tpu.parallel.hetero import (
            HeteroState, HeteroStrategy, build_hetero_train_step,
            make_hetero_plan, state_from_hetero, state_to_hetero,
        )
        strategy.validate(len(self.devices or jax.devices()))
        hetero = isinstance(strategy, HeteroStrategy)

        def to_homo_state():
            if isinstance(self.state, HeteroState):
                return state_from_hetero(self.state, self.plan, self.model)
            return self.state

        def build() -> CachedStep:
            t0 = time.perf_counter()
            with telemetry.span("compile", hetero=hetero,
                                strategy=strategy.to_json()), \
                    autocast(self.config.policy()):
                if hetero:
                    plan = make_hetero_plan(self.model, strategy,
                                            self.devices)
                    step_fn = build_hetero_train_step(
                        self.model, self.opt, plan,
                        attn_impl=self.config.attn_impl)
                    entry = CachedStep(plan, step_fn, None,
                                       refs=(self.model, self.opt))
                    entry.compile_seconds = time.perf_counter() - t0
                else:
                    entry = compile_strategy(
                        self.model, self.opt, strategy,
                        devices=self.devices,
                        attn_impl=self.config.attn_impl)
            dt = time.perf_counter() - t0
            self._note("compile", dt)
            self.flight.record("compile", hetero=hetero,
                               seconds=round(dt, 3))
            return entry

        if self.config.step_cache:
            entry = self.cache.get_or_build(self._cache_key(strategy),
                                            build)
        else:
            entry = build()

        if self.state is not None:
            t0 = time.perf_counter()
            if hetero:
                with telemetry.span("switch", hetero=True):
                    self.state = state_to_hetero(to_homo_state(),
                                                 entry.plan)
            else:
                # switch_strategy records the "switch" span itself (with
                # cross-topology + volume attrs); only the ledger lives
                # here
                self.state = switch_strategy(to_homo_state(), entry.plan)
            dt = time.perf_counter() - t0
            self._note("switch", dt)
            self.flight.record("switch", hetero=hetero,
                               seconds=round(dt, 3))
            get_logger().info(
                f"hot-switched to {'hetero ' if hetero else ''}"
                f"{strategy.to_json()} at step "
                f"{int(jax.device_get(self.state.step))}")
        self.plan = entry.plan
        self._step_fn = entry
        self._eval_fn = entry.eval_fn  # None under hetero: switch back
        self._bucket_entries.clear()   # per-(strategy, bucket) entries
        if self._live_prefetcher is not None:
            # a mid-run switch re-points the input pipeline: batches
            # staged under the old plan are re-placed lazily on fetch
            self._live_prefetcher.set_place(self.plan.shard_batch)
        return entry.plan

    def precompile(self, strategies, *, batch_shape=None,
                   batch_keys=("input_ids", "labels"),
                   buckets=None, bucket_rows=None,
                   block: bool = False):
        """Warm the step cache for candidate ``strategies`` (e.g. the
        Galvatron search's top-k) on a background thread — see
        :func:`hetu_tpu.engine.precompile.precompile_strategies`. With a
        ``batch_shape`` each candidate is AOT-compiled for it, making a
        later ``set_strategy`` + first step completely compile-free;
        ``batch_keys`` must match the run's real batch dict (packed
        loaders carry positions + segment_ids). ``buckets`` defaults to
        this Trainer's ``config.seq_buckets`` ladder so a bucketed run's
        AOT coverage automatically spans every (strategy, bucket)
        variant."""
        from hetu_tpu.engine.precompile import precompile_strategies
        if buckets is None and self.config.seq_buckets:
            buckets = self.config.seq_buckets
        handle = precompile_strategies(
            self.model, self.opt, strategies, batch_shape=batch_shape,
            batch_keys=batch_keys, buckets=buckets,
            bucket_rows=bucket_rows,
            devices=self.devices, attn_impl=self.config.attn_impl,
            policy=self.config.policy(),
            policy_key=self.config.precision, cache=self.cache,
            background=not block)
        if block:
            handle.wait()
        return handle

    # -- shape plane (bucketed steps) --------------------------------------
    def _bucket_entry(self, bucket: int) -> CachedStep:
        """CachedStep for (current strategy, ``bucket``) — one entry per
        bucket so each holds exactly one shape in its jit/AOT caches and
        the ragged-epoch compile count is bounded by the ladder size."""
        entry = self._bucket_entries.get(bucket)
        if entry is not None:
            return entry
        strategy = self.strategy
        key = self._cache_key(strategy, bucket=bucket)
        first_build = self.cache.lookup(key) is None

        def build() -> CachedStep:
            t0 = time.perf_counter()
            with telemetry.span("compile", bucket=bucket,
                                strategy=strategy.to_json()), \
                    autocast(self.config.policy()):
                e = compile_strategy(
                    self.model, self.opt, strategy,
                    devices=self.devices,
                    attn_impl=self.config.attn_impl)
            dt = time.perf_counter() - t0
            self._note("compile", dt)
            self.flight.record("compile", bucket=bucket,
                               seconds=round(dt, 3))
            return e

        entry = self.cache.get_or_build(key, build) \
            if self.config.step_cache else build()
        if first_build and telemetry.enabled():
            self.registry.counter(
                "data_bucket_compiles_total",
                "step entries built per seq-len bucket (the re-trace "
                "audit's per-bucket view)").inc(bucket=str(bucket))
        self._bucket_entries[bucket] = entry
        return entry

    def _step_entry_for(self, sbatch: dict) -> CachedStep:
        """Pick the step entry for an (already fitted, already sharded)
        batch: the per-bucket entry when bucketing is on and the batch
        carries a seq dim, else the strategy's base entry. Hetero plans
        keep the base entry (the hetero executor owns its own shapes)."""
        if self.bucketer is None or self._eval_fn is None \
                or "input_ids" not in sbatch:
            return self._step_fn
        return self._bucket_entry(int(sbatch["input_ids"].shape[1]))

    def _note(self, category: str, seconds: float) -> None:
        """Goodput ledger + cumulative counter for an overhead event."""
        if self.goodput is not None:
            self.goodput.record(category, seconds)
        if telemetry.enabled():
            self.registry.counter(
                f"{category}_seconds_total",
                f"cumulative {category} time").inc(seconds)

    def shrink_to(self, devices, strategy: Optional[Strategy] = None):
        """Elastic recovery on the live controller: rebuild plans over
        the SURVIVING ``devices`` and reshard the live state onto them —
        no checkpoint read (``parallel.switch`` cross-topology path; see
        also ``engine.elastic.elastic_resume`` for the non-Trainer form).

        ``strategy``: the recovery strategy (e.g. from
        ``ElasticController.recovery_plan``); defaults to the current one,
        which must fit the surviving device count.
        """
        return self._retarget(devices, strategy, kind="shrink")

    def grow_to(self, devices, strategy: Optional[Strategy] = None):
        """Elastic re-admission: a recovered worker's devices rejoin the
        mesh and the live state hot-switches onto the GROWN plan — the
        same cross-topology switch a shrink uses, in the other direction
        (``engine.elastic.ElasticSupervisor.grow`` drives this from the
        membership side)."""
        return self._retarget(devices, strategy, kind="grow")

    def _retarget(self, devices, strategy, *, kind: str):
        self.devices = list(devices)
        # cached plans pin departed devices — drop the whole pool (the
        # cache may be process-shared: a membership change invalidates
        # every plan compiled for the old topology anyway)
        self.cache.clear()
        self.flight.record(f"elastic_{kind}", n_devices=len(self.devices))
        return self.set_strategy(strategy if strategy is not None
                                 else self.strategy)

    @property
    def strategy(self) -> Strategy:
        return self.plan.strategy

    # -- state lifecycle ---------------------------------------------------
    def initialize(self, key: Optional[jax.Array] = None) -> TrainState:
        from hetu_tpu.parallel.hetero import HeteroPlan, init_hetero_state
        key = key if key is not None else jax.random.key(self.config.seed)
        with autocast(self.config.policy()):
            if isinstance(self.plan, HeteroPlan):
                self.state = init_hetero_state(self.model, self.opt,
                                               self.plan, key)
            else:
                self.state = init_state(self.model, self.opt, self.plan,
                                        key)
        return self.state

    def resume(self, path: str) -> TrainState:
        import os
        from hetu_tpu.parallel.hetero import HeteroPlan, state_to_hetero
        hetero = isinstance(self.plan, HeteroPlan)
        plan = None if hetero else self.plan
        if os.path.exists(os.path.join(path, "index-host00000.json")):
            from hetu_tpu.utils.dist_checkpoint import (
                load_checkpoint_distributed)
            self.state = load_checkpoint_distributed(
                path, self.model, self.opt, plan)
        else:
            self.state = load_checkpoint(path, self.model, self.opt,
                                         plan)
        if hetero:
            self.state = state_to_hetero(self.state, self.plan)
        get_logger().info(
            f"resumed from {path} at step "
            f"{int(jax.device_get(self.state.step))}")
        return self.state

    def save(self, path: Optional[str] = None, *, wait: bool = False):
        path = path or self.config.ckpt_dir
        if path is None:
            raise ValueError("no checkpoint path configured")
        t0 = time.perf_counter()
        with telemetry.span("checkpoint", path=path, wait=wait):
            if self._ckpt_writer is not None:
                self._ckpt_writer.wait()  # one in-flight save at a time
            from hetu_tpu.parallel.hetero import (
                HeteroState, state_from_hetero)
            state = self.state
            if isinstance(state, HeteroState):
                # checkpoints are layout-independent: merge to one
                # TrainState
                state = state_from_hetero(state, self.plan, self.model)
            if self.config.distributed_ckpt:
                import glob
                from hetu_tpu.utils.dist_checkpoint import (
                    save_checkpoint_distributed)
                delta = None
                if self.config.delta_ckpt and glob.glob(
                        os.path.join(path, "index-host*.json")):
                    delta = path   # in-place series: delta vs last save
                self._ckpt_writer = save_checkpoint_distributed(
                    path, state, delta_base=delta,
                    # hash even the series' first, full save — the next
                    # one deltas against it
                    hash_pieces=self.config.delta_ckpt or None,
                    async_save=self.config.async_ckpt and not wait)
            else:
                self._ckpt_writer = save_checkpoint(
                    path, state,
                    async_save=self.config.async_ckpt and not wait)
            if wait:
                self._ckpt_writer.wait()
        # the span/ledger cover what BLOCKED the loop (previous writer
        # drain + device→host gather + sync write); an async write's own
        # latency is tracked by checkpoint_write_seconds on its thread
        dt = time.perf_counter() - t0
        self._note("checkpoint", dt)
        self.flight.record("checkpoint", path=path,
                           blocked_s=round(dt, 3))
        return path

    # -- training ----------------------------------------------------------
    def train_step(self, batch: dict) -> dict:
        if self.state is None:
            self.initialize()
        if self.bucketer is not None and self._eval_fn is not None:
            batch = self.bucketer.fit(batch)
        sbatch = self.plan.shard_batch(batch)
        self.state, metrics = self._step_entry_for(sbatch)(self.state,
                                                           sbatch)
        return metrics

    def train(self, batches: Iterable[dict],
              steps: Optional[int] = None, *,
              eval_batches=None) -> list[dict]:
        """Run up to ``steps`` (default config.total_steps) steps; returns
        the logged metric records.

        The loop keeps the device pipeline full: the step counter is
        tracked host-side (a per-step ``device_get(state.step)`` would
        sync every step and serialize dispatch), the host only blocks on
        metrics at log boundaries, and batches are staged through the
        device prefetcher (``data/prefetch.py``) so H2D transfers overlap
        the previous step's compute.

        ``eval_batches``: a *callable returning an iterable* of held-out
        batches; every ``config.eval_every`` steps it is re-invoked and
        the mean validation loss (dropout off) is logged as
        ``eval_loss``."""
        if self.state is None:
            self.initialize()
        steps = steps if steps is not None else self.config.total_steps
        history = []
        tel = telemetry.enabled()
        # goodput ledger for THIS run: every loop second lands in a
        # category (compute/stall/eval here; compile/switch/checkpoint
        # via set_strategy()/save()); report exported at the end
        acct = GoodputAccountant(peak_flops=self.config.peak_flops)
        self.goodput = acct
        t_last = time.perf_counter()
        tokens_since = 0
        tokens_total = 0
        # MFU pricing under varying widths (bucketed ragged epochs): the
        # attention FLOPs/token depend on seq width, so the accountant's
        # single flops_per_token is kept as the running FLOPS-WEIGHTED
        # mean — tokens_total * flops_per_token stays exact per batch
        fpt_by_width: dict[int, Optional[float]] = {}
        flops_sum = 0.0
        slo_blocked_s = 0.0   # eval/checkpoint time inside the current
                              # log interval — excluded from the SLO
                              # step-time observation
        host_step = int(jax.device_get(self.state.step))
        # hang watchdog for THIS run: fed once per completed step; trips
        # dump the flight record + thread stacks to trace_dir (or cwd)
        watchdog = None
        if self.config.watchdog:
            watchdog = telemetry.HangWatchdog(
                name="train", factor=self.config.watchdog_factor,
                min_timeout_s=self.config.watchdog_min_timeout_s,
                dump_dir=self.config.trace_dir or ".",
                registry=self.registry).start()
        if self.bucketer is not None and self._eval_fn is not None:
            # snap every host batch to its bucket BEFORE placement: the
            # prefetcher stages the fitted (bucket-wide) arrays, so the
            # step entry picked at dispatch time sees exactly one shape
            # per bucket
            fit = self.bucketer.fit
            batches = (fit(b) for b in batches)
        prefetcher = None
        if self.config.prefetch > 0:
            from hetu_tpu.data.prefetch import DevicePrefetcher
            prefetcher = DevicePrefetcher(
                batches, self.plan.shard_batch,
                buffer_size=self.config.prefetch, max_items=steps)
            # registered so a mid-run set_strategy() re-points placement
            # at the new plan (staged batches re-place lazily on fetch)
            self._live_prefetcher = prefetcher
            it: Iterator[dict] = prefetcher
        else:
            it = (self.plan.shard_batch(b) for b in batches)
        failed: Optional[str] = None   # exception name when train() dies
        try:
            for _ in range(steps):
                t_iter = time.perf_counter()
                try:
                    sbatch = next(it)
                except StopIteration:
                    break
                t_fetch = time.perf_counter()
                # waiting on the data path is a stall (the prefetcher
                # additionally emits a "stall" span + counter itself)
                acct.record("stall", t_fetch - t_iter)
                width = int(sbatch["input_ids"].shape[-1]) \
                    if "input_ids" in sbatch else None
                if width is not None and width not in fpt_by_width:
                    fpt_by_width[width] = self._flops_per_token(width)
                n_traces = trace_total()
                self.state, metrics = self._step_entry_for(sbatch)(
                    self.state, sbatch)
                host_step += 1
                acct.add_step()
                # step boundary into the black box; one beat per
                # completed step feeds the watchdog's rolling median
                self.flight.record("step", step=host_step)
                if watchdog is not None:
                    watchdog.beat()
                ntok = int(sbatch["input_ids"].size)
                tokens_since += ntok
                tokens_total += ntok
                acct.add_tokens(ntok)
                fpt = fpt_by_width.get(width) if width is not None \
                    else None
                if fpt:
                    flops_sum += fpt * ntok
                    acct.flops_per_token = flops_sum / tokens_total
                if self.config.log_every and \
                        host_step % self.config.log_every == 0:
                    loss = float(jax.device_get(metrics["loss"]))
                    grad_norm = float(
                        jax.device_get(metrics["grad_norm"]))
                    now = time.perf_counter()
                    rec = self.metrics.log(
                        host_step, loss=loss,
                        grad_norm=grad_norm,
                        tokens_per_sec=round(
                            tokens_since / (now - t_last), 1),
                        tokens_total=tokens_total)
                    history.append(rec)
                    if self.slo is not None:
                        # one observation per log interval, then run
                        # every detector (burn rates + regressions).
                        # Known blocking work (eval, checkpoint drain)
                        # is subtracted — it is accounted overhead, not
                        # a step-time regression
                        self.slo.observe("loss", loss)
                        self.slo.observe("grad_norm", grad_norm)
                        self.slo.observe(
                            "step_time_s",
                            max(now - t_last - slo_blocked_s, 0.0)
                            / self.config.log_every)
                        slo_blocked_s = 0.0
                        for a in self.slo.evaluate():
                            get_logger().warning(f"SLO alert: "
                                                 f"{a.message}")
                            self.metrics.write_record(a.to_record())
                    t_last, tokens_since = now, 0
                    if tel:
                        # sample the mem_*/comm_* registry series into
                        # Perfetto counter tracks on the log cadence
                        self.tracer.record_counters(
                            self.registry.snapshot())
                # step dispatch + the log boundary's blocking fetch: the
                # productive slice of this iteration — UNLESS the step
                # body re-traced, in which case the wall went to
                # trace+XLA-compile (a cold/cache-disabled first step)
                # and belongs in the compile ledger, not compute
                step_s = time.perf_counter() - t_fetch
                if trace_total() > n_traces:
                    acct.record("compile", step_s)
                    if tel:
                        self.tracer.complete("compile", step_s,
                                             where="step_trace")
                else:
                    acct.record("compute", step_s)
                if self.config.eval_every and eval_batches is not None \
                        and host_step % self.config.eval_every == 0:
                    # eval/checkpoint are legitimately long blocking
                    # operations, not hangs: suspend trip checks so a
                    # slow eval pass or writer drain never produces a
                    # false "the run HUNG" flight dump
                    if watchdog is not None:
                        watchdog.pause()
                    t0 = time.perf_counter()
                    with telemetry.span("eval", step=host_step):
                        ev = self.evaluate(eval_batches())
                    ev_s = time.perf_counter() - t0
                    acct.record("eval", ev_s)
                    slo_blocked_s += ev_s
                    history.append(self.metrics.log(host_step,
                                                    eval_loss=ev))
                    if watchdog is not None:
                        watchdog.resume()
                if self.config.aggregate_every and telemetry.enabled() \
                        and host_step % self.config.aggregate_every == 0:
                    self._aggregate_cluster(host_step)
                if self.config.ckpt_every and self.config.ckpt_dir and \
                        host_step % self.config.ckpt_every == 0:
                    if watchdog is not None:
                        watchdog.pause()
                    t0 = time.perf_counter()
                    self.save()   # notes "checkpoint" in the ledger
                    slo_blocked_s += time.perf_counter() - t0
                    if watchdog is not None:
                        watchdog.resume()
            if self.config.ckpt_dir:
                if watchdog is not None:
                    watchdog.pause()
                self.save(wait=True)
        except BaseException as e:
            # explicit capture, NOT sys.exc_info() in the finally: that
            # would also see a CALLER's in-flight handled exception and
            # overwrite the flight postmortem after a successful run
            failed = type(e).__name__
            raise
        finally:
            if watchdog is not None:
                watchdog.stop()
            if prefetcher is not None:
                self._live_prefetcher = None
                prefetcher.close()
            acct.freeze()   # later manual exports must not dilute goodput
            # export in the failure path too: a crashed run is exactly
            # when the operator needs the trace (best-effort — an export
            # problem must not mask the training error)
            if failed is not None:
                try:
                    self.flight.record("train_error", error=failed)
                    self.flight.dump(
                        self.flight.default_path(self.config.trace_dir),
                        reason="train_error", stacks=True)
                except Exception:
                    pass
            if tel:
                try:
                    self.export_telemetry()
                except Exception as e:
                    get_logger().warning(f"telemetry export failed: {e}")
        return history

    def train_dynamic(self, dispatcher, seqs, epochs: int = 1, *,
                      use_bucket_strategies: bool = False) -> list[dict]:
        """Hydraulis flow: train over a DynamicDispatcher's per-bucket
        batches, one cached jitted step per bucket length (jit cache
        keyed on shape).

        ``use_bucket_strategies=True`` is the COMPOSED Hydraulis planner
        (reference ``examples/hydraulis/strategy/new_planning.py``): each
        bucket trains under ITS OWN parallel strategy from
        ``plan_buckets``'s cost-model search (short buckets dp-heavy,
        long buckets cp+remat), hot-switching the live state between
        plans at bucket boundaries. The dispatcher emits largest buckets
        first, so switches happen once per bucket class per epoch, and
        the plan pool makes A→B→A reuse free. False keeps this Trainer's
        single strategy (per-bucket shapes only)."""
        if self.state is None:
            self.initialize()
        history = []
        tel = telemetry.enabled()
        acct = GoodputAccountant(peak_flops=self.config.peak_flops)
        self.goodput = acct   # set_strategy switches/compiles feed it
        host_step = int(jax.device_get(self.state.step))
        # per-bucket FLOP pricing, same running weighted mean as train()
        fpt_by_width: dict[int, Optional[float]] = {}
        flops_sum = 0.0
        tokens_sum = 0
        try:
            for _ in range(epochs):
                for batch, plan in dispatcher.batches(seqs):
                    if use_bucket_strategies \
                            and plan.strategy != self.strategy:
                        self.set_strategy(plan.strategy)
                    t0 = time.perf_counter()
                    width = int(batch["input_ids"].shape[-1])
                    if width not in fpt_by_width:
                        fpt_by_width[width] = self._flops_per_token(
                            width)
                    n_traces = trace_total()
                    metrics = self.train_step(batch)
                    host_step += 1   # host-side: no per-step device sync
                    acct.add_step()
                    ntok = int(batch["input_ids"].size)
                    acct.add_tokens(ntok)
                    tokens_sum += ntok
                    if fpt_by_width.get(width):
                        flops_sum += fpt_by_width[width] * ntok
                        acct.flops_per_token = flops_sum / tokens_sum
                    if self.config.log_every and \
                            host_step % self.config.log_every == 0:
                        extra = {"strategy": plan.strategy.to_json()} \
                            if use_bucket_strategies else {}
                        history.append(self.metrics.log(
                            host_step,
                            loss=float(jax.device_get(metrics["loss"])),
                            bucket=plan.bucket_len, **extra))
                    acct.record(
                        "compile" if trace_total() > n_traces
                        else "compute", time.perf_counter() - t0)
        finally:
            acct.freeze()
            if tel:
                try:
                    self.export_telemetry()
                except Exception as e:
                    get_logger().warning(f"telemetry export failed: {e}")
        return history

    # -- telemetry ---------------------------------------------------------
    def _aggregate_cluster(self, step: int) -> Optional[dict]:
        """One cross-rank aggregation round on the train() cadence
        (``config.aggregate_every``): publish this rank's registry
        snapshot through the coordinator KV, take back the cluster
        min/max/mean reduction, and log it as a ``cluster_aggregate``
        record. Without a ``dist`` context (single process) the snapshot
        reduces locally — same record shape, ranks=1 — so the cadence
        and artifact schema are exercised everywhere. Failures are
        logged, never fatal: telemetry must not kill training."""
        snap = self.registry.snapshot()
        t0 = time.perf_counter()
        try:
            with telemetry.span("cluster_aggregate", step=step):
                if self._dist is not None and \
                        getattr(self._dist, "num_processes", 1) > 1:
                    agg = telemetry.cluster_aggregate(
                        self._dist.client, self._dist.rank,
                        self._dist.num_processes, snap, run="trainer-agg")
                    ranks = self._dist.num_processes
                else:
                    agg = telemetry.aggregate_snapshots([snap])
                    ranks = 1
        except Exception as e:   # noqa: BLE001 — observability side-path
            get_logger().warning(
                f"cluster aggregation failed at step {step}: {e}")
            return None
        finally:
            # the blocking barrier time is overhead the goodput ledger
            # must see (the cadence is the operator's knob against it)
            self._note("telemetry", time.perf_counter() - t0)
        rec = {"kind": "cluster_aggregate", "step": step,
               "ranks": ranks, "metrics": agg}
        self.metrics.write_record(rec)
        return agg

    def _flops_per_token(self, seq_len: int) -> Optional[float]:
        """Model FLOPs/token from the config shapes (cost-model dims);
        None when the model family doesn't expose transformer dims."""
        cfg = getattr(self.model, "cfg", None)
        if cfg is None or not hasattr(cfg, "num_layers") \
                or not hasattr(cfg, "hidden_size"):
            return None
        try:
            from hetu_tpu.tools.galvatron.cost_model import ModelDims
            dims = ModelDims.from_config(cfg, seq_len=seq_len,
                                         global_batch=1)
            return telemetry.model_flops_per_token(dims)
        except Exception:
            return None

    def export_telemetry(self) -> Optional[dict]:
        """Flush telemetry artifacts for the last run to
        ``config.trace_dir``: rewrite ``trace.json`` (all spans so far,
        Perfetto-loadable) and append the new span records plus the
        goodput report to ``telemetry.jsonl``. Returns the goodput
        record (also without a trace_dir, for programmatic use)."""
        rec = None
        if self.goodput is not None:
            rec = self.goodput.report().to_record()
        if not self.config.trace_dir or not telemetry.enabled():
            return rec
        import os
        self.tracer.export_chrome(
            os.path.join(self.config.trace_dir, "trace.json"))
        if self._spans_epoch != self.tracer.epoch:   # reset() since last
            self._spans_exported = 0
            self._spans_epoch = self.tracer.epoch
        events = self.tracer.events()
        for ev in events[self._spans_exported:]:
            self.metrics.write_record(ev.to_record())
        self._spans_exported = len(events)
        if rec is not None:
            self.metrics.write_record(rec)
            # per-strategy OBSERVED step time: the record the Galvatron
            # search's measured re-rank consumes
            # (tools.galvatron.search.rerank_by_measured) — closing the
            # planner loop from the gain side
            comp, steps = rec["components"].get("compute", 0.0), \
                rec.get("steps", 0)
            if comp > 0 and steps:
                try:
                    self.metrics.write_record({
                        "kind": "measured_step",
                        "strategy": self.strategy.to_json(),
                        "step_time_s": comp / steps, "steps": steps})
                except Exception:   # hetero strategies: no to_json parity
                    pass
        # final registry snapshot: the control-plane counters (cache
        # hits, prefetch overlap, switch fast path) as of run end —
        # trace_summary's "control plane" section reads the LAST one
        snap = self.registry.to_record()
        if snap["metrics"]:
            self.metrics.write_record(snap)
        return rec

    def close(self) -> None:
        """Release resources: drain any in-flight checkpoint write and
        close the metrics JSONL stream (idempotent)."""
        if self._ckpt_writer is not None:
            self._ckpt_writer.wait()
            self._ckpt_writer = None
        self.metrics.close()

    def evaluate(self, batches: Iterable[dict]) -> float:
        if self._eval_fn is None:
            raise RuntimeError(
                "evaluate() is not supported under a hetero strategy — "
                "set_strategy(Strategy(...)) back to a homogeneous plan "
                "first (the hot switch preserves the state)")
        total, n = 0.0, 0
        for batch in batches:
            loss = self._eval_fn(self.state.params,
                                 self.plan.shard_batch(batch))
            total += float(jax.device_get(loss))
            n += 1
        return total / max(n, 1)
