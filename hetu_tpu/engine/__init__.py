"""Engine: train state, train-step compiler, trainer.

Parity target: ``python/hetu/engine`` (``Trainer`` `trainer.py:66`,
planners, straggler monitor).
"""

from hetu_tpu.engine.state import TrainState
from hetu_tpu.engine.train_step import (
    TrainPlan, make_plan, init_state, build_train_step, build_eval_step,
    build_grad_accum_steps,
    CachedStep, StepCache, compile_strategy, get_step_cache,
    abstract_batch, abstract_train_state, trace_counts,
    reset_trace_counts,
)
from hetu_tpu.engine.precompile import (
    PrecompileHandle, PrecompileResult,
    enable_persistent_compilation_cache, precompile_strategies,
    precompile_top_k,
)

from hetu_tpu.engine.malleus import plan_hetero

__all__ = [
    "TrainState", "TrainPlan", "make_plan", "init_state",
    "build_train_step", "build_eval_step", "build_grad_accum_steps",
    "CachedStep", "StepCache", "compile_strategy", "get_step_cache",
    "abstract_batch", "abstract_train_state", "trace_counts",
    "reset_trace_counts",
    "PrecompileHandle", "PrecompileResult",
    "enable_persistent_compilation_cache", "precompile_strategies",
    "precompile_top_k",
    "plan_hetero",
]
