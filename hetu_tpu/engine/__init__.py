"""Engine: train state, train-step compiler, trainer.

Parity target: ``python/hetu/engine`` (``Trainer`` `trainer.py:66`,
planners, straggler monitor).
"""

from hetu_tpu.engine.state import TrainState
from hetu_tpu.engine.train_step import (
    TrainPlan, make_plan, init_state, build_train_step, build_eval_step,
)

from hetu_tpu.engine.malleus import plan_hetero

__all__ = [
    "TrainState", "TrainPlan", "make_plan", "init_state",
    "build_train_step", "build_eval_step", "plan_hetero",
]
