"""Engine: train state, train-step compiler, trainer.

Parity target: ``python/hetu/engine`` (``Trainer`` `trainer.py:66`,
planners, straggler monitor).
"""

from hetu_tpu.engine.state import TrainState
from hetu_tpu.engine.train_step import (
    TrainPlan, make_plan, init_state, build_train_step, build_eval_step,
    build_grad_accum_steps,
)

from hetu_tpu.engine.malleus import plan_hetero

__all__ = [
    "TrainState", "TrainPlan", "make_plan", "init_state",
    "build_train_step", "build_eval_step", "build_grad_accum_steps",
    "plan_hetero",
]
