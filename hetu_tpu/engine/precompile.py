"""AOT strategy pre-compilation + persistent compilation cache wiring.

Hot switching (HotSPa) is only "hot" if the destination strategy's step
executable already exists; otherwise the switch pays a full re-trace +
XLA compile on the critical path — exactly the compile/switch slices the
goodput accountant (``telemetry/goodput.py``) itemizes. This module
removes that tax along two axes:

- **Background AOT compilation** — :func:`precompile_strategies` runs
  ``jax.jit(step).lower(abstract_state, abstract_batch).compile()`` for
  candidate strategies on a worker thread while step N of the *current*
  strategy trains, parking the executables in the shared
  :class:`~hetu_tpu.engine.train_step.StepCache`. A later
  ``Trainer.set_strategy`` is then a cache hit, and the first step after
  the switch dispatches the ahead-of-time executable — zero traces, zero
  compiles on the critical path. :func:`precompile_top_k` feeds the
  worker from the Galvatron search's best plans (Alpa/Galvatron-style
  plan reuse).
- **Persistent compilation cache** —
  :func:`enable_persistent_compilation_cache` wires jax's on-disk XLA
  cache so restarts (and the AOT worker itself) start warm: a re-trace
  still happens, but the minutes-long XLA compile becomes a disk read.

Everything is thread-safe: compile state is per-entry via the cache's
single-flight builds, and the dtype policy (``core.dtypes.autocast``) is
thread-local so a background lowering never leaks its policy into the
training thread.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Iterable, Optional, Sequence

import jax

from hetu_tpu.core.dtypes import Policy, autocast
from hetu_tpu.engine.train_step import (
    CachedStep, StepCache, _batch_key, abstract_batch,
    abstract_train_state, compile_strategy, get_step_cache,
)
from hetu_tpu.parallel.strategy import Strategy


@dataclasses.dataclass
class PrecompileResult:
    """Outcome of one strategy's pre-compilation."""

    strategy: Strategy
    ok: bool
    seconds: float
    aot: bool                      # an AOT executable was compiled
    cached: bool = False           # entry already existed (cache hit)
    error: Optional[str] = None
    bucket: int = 0                # seq-len bucket (0 = unbucketed)


class PrecompileHandle:
    """Join handle for a (possibly background) pre-compilation run."""

    def __init__(self):
        self._done = threading.Event()
        self._results: list[PrecompileResult] = []
        self._thread: Optional[threading.Thread] = None

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None
             ) -> list[PrecompileResult]:
        """Block until every candidate finished compiling; returns the
        per-strategy results (partial list if ``timeout`` expires)."""
        self._done.wait(timeout)
        return list(self._results)

    @property
    def results(self) -> list[PrecompileResult]:
        return list(self._results)


def _precompile_one(model, opt, strategy: Strategy, *, devices, attn_impl,
                    donate, policy: Optional[Policy], policy_key,
                    batch_shape, batch_keys,
                    cache: StepCache, bucket: int = 0) -> PrecompileResult:
    from hetu_tpu import telemetry
    t0 = time.perf_counter()
    # EVERY key-bearing field must be forwarded here (the shape-plane
    # lint asserts it): a field the enumeration drops would silently
    # compile into the wrong entry and the runtime would re-trace
    key = cache.key_for(model, opt, strategy, attn_impl=attn_impl,
                        donate=donate, policy_key=policy_key,
                        devices=devices, bucket=bucket)
    with telemetry.span("precompile", strategy=strategy.to_json()) as sp:
        existed = cache.lookup(key) is not None

        def build() -> CachedStep:
            ctx = autocast(policy) if policy is not None else _nullctx()
            with ctx:
                return compile_strategy(model, opt, strategy,
                                        devices=devices,
                                        attn_impl=attn_impl,
                                        donate=donate)

        entry = cache.get_or_build(key, build)
        did_aot = False
        if batch_shape is not None:
            # one source of truth for the AOT dict key: the exact batch
            # the executable is lowered for
            batch_sds = abstract_batch(entry.plan, batch_shape,
                                       keys=batch_keys)
            bkey = _batch_key(batch_sds)
            if bkey not in entry.aot:
                ctx = autocast(policy) if policy is not None else _nullctx()
                with ctx:
                    # dtype left to the autocast policy — must mirror
                    # what Trainer.initialize's init_state produces
                    state_sds = abstract_train_state(model, opt,
                                                     entry.plan)
                    exe = entry.step_fn.lower(state_sds,
                                              batch_sds).compile()
                entry.aot[bkey] = exe
                did_aot = True
        if telemetry.enabled():
            sp.set(cached=existed, aot=did_aot)
            if not existed or did_aot:   # count real work, not no-ops
                telemetry.get_registry().counter(
                    "precompiled_strategies_total",
                    "strategies compiled ahead of time").inc()
    return PrecompileResult(strategy, ok=True,
                            seconds=time.perf_counter() - t0,
                            aot=did_aot, cached=existed, bucket=bucket)


class _nullctx:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def precompile_strategies(model, opt, strategies: Iterable[Strategy], *,
                          batch_shape: Optional[tuple] = None,
                          batch_keys: Sequence[str] = ("input_ids",
                                                       "labels"),
                          buckets: Optional[Sequence[int]] = None,
                          bucket_rows: Optional[dict] = None,
                          devices=None, attn_impl: str = "auto",
                          donate: bool = True,
                          policy: Optional[Policy] = None,
                          policy_key: str = "",
                          cache: Optional[StepCache] = None,
                          background: bool = True) -> PrecompileHandle:
    """Compile every candidate strategy into the step cache.

    ``batch_shape`` — global (batch, seq) the training loop will feed;
    when given, each strategy is ALSO AOT-compiled for that shape
    (``lower().compile()``) so the first post-switch step dispatches a
    ready executable. Without it only the plan + jitted step are built
    (the first step after a switch still traces once).

    ``buckets`` — the seq-len bucket ladder of a shape-plane run
    (``TrainerConfig(seq_buckets=...)``): candidates become the full
    (strategy x bucket) product, each keyed with its bucket in the
    StepCache (``key_for(bucket=)``) and AOT-compiled at
    ``(rows, bucket)`` where ``rows`` comes from ``bucket_rows[bucket]``
    (falling back to ``batch_shape[0]``). Without it the bucket ladder's
    variants would silently miss AOT coverage and the first step at each
    new bucket would trace on the critical path.

    ``batch_keys`` must name EXACTLY the keys the real (post
    ``shard_batch``) batches carry — the AOT executable is selected by
    shape/dtype signature, so a mismatch silently falls back to the
    jitted path. Packed loaders (``build_data_loader(pack=True)``) need
    ``("input_ids", "labels", "positions", "segment_ids")``.

    ``background=True`` returns immediately; compilation proceeds on a
    daemon worker thread (one worker: XLA already parallelizes a single
    compile, and serial candidates keep host memory bounded). Failures
    are per-strategy — one infeasible candidate never aborts the rest.
    """
    cache = cache if cache is not None else get_step_cache()
    strategies = list(strategies)
    handle = PrecompileHandle()
    rows0 = batch_shape[0] if batch_shape is not None else None
    if buckets is not None:
        cands = [(s, int(L)) for s in strategies
                 for L in sorted(set(int(b) for b in buckets))]
    else:
        cands = [(s, 0) for s in strategies]

    def _shape_for(bucket: int) -> Optional[tuple]:
        if bucket == 0:
            return batch_shape
        rows = (bucket_rows or {}).get(bucket, rows0)
        return None if rows is None else (int(rows), bucket)

    def work():
        for s, bkt in cands:
            try:
                res = _precompile_one(
                    model, opt, s, devices=devices, attn_impl=attn_impl,
                    donate=donate, policy=policy, policy_key=policy_key,
                    batch_shape=_shape_for(bkt), batch_keys=batch_keys,
                    cache=cache, bucket=bkt)
            except Exception as e:   # noqa: BLE001 — per-candidate
                res = PrecompileResult(s, ok=False, seconds=0.0,
                                       aot=False, error=str(e)[:500],
                                       bucket=bkt)
            handle._results.append(res)
        handle._done.set()

    if background:
        t = threading.Thread(target=work, daemon=True,
                             name="hetu-precompile")
        handle._thread = t
        t.start()
    else:
        work()
    return handle


def precompile_top_k(model, opt, dims, topo, *, k: int = 3,
                     batch_shape: Optional[tuple] = None,
                     num_devices: Optional[int] = None,
                     measured_path: Optional[str] = None,
                     **kw) -> PrecompileHandle:
    """Drive the AOT worker from the Galvatron search: take the top-``k``
    feasible candidates of :func:`~hetu_tpu.tools.galvatron.search.
    search_uniform` over (``dims``, ``topo``) and pre-compile them, so a
    planner-directed hot switch to ANY of its likely picks is warm.

    ``num_devices`` filters candidates to what the live mesh can host
    (defaults to ``jax.device_count()``). ``measured_path`` (or
    ``$HETU_MEASURED_TELEMETRY``) points at a telemetry JSONL whose
    ``measured_step`` records re-rank the candidates by OBSERVED step
    time before the top-``k`` cut — the precompiled set then reflects
    what actually ran fastest, not just the analytic model."""
    from hetu_tpu.tools.galvatron.search import search_uniform
    n = num_devices if num_devices is not None else jax.device_count()
    cands = [c.strategy
             for c in search_uniform(dims, topo,
                                     measured_path=measured_path)
             if c.strategy.num_devices <= n]
    return precompile_strategies(model, opt, cands[:k],
                                 batch_shape=batch_shape, **kw)


def enable_persistent_compilation_cache(
        path: Optional[str] = None, *,
        min_compile_seconds: float = 1.0) -> Optional[str]:
    """Point jax's persistent (on-disk) compilation cache at ``path`` so
    process restarts start warm: the cache is keyed on the XLA program,
    so an identical strategy re-compiled after a restart is a disk read
    instead of a full XLA compile.

    ``path`` defaults to ``$HETU_COMPILE_CACHE_DIR`` (unset + no arg =
    no-op, returns None — the cache stays opt-in because XLA:CPU
    executable *deserialization* is known-broken under jaxlib 0.4.37
    when many processes share one cache; see docs/PERFORMANCE.md).
    Returns the activated path."""
    path = path or os.environ.get("HETU_COMPILE_CACHE_DIR")
    if not path:
        return None
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    try:
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_seconds))
    except Exception:     # knob renamed across jax versions: best-effort
        pass
    return path
