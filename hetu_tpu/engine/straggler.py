"""Straggler detection + Malleus-style replanning hook.

Parity target: ``python/hetu/engine/straggler.py:20`` (each worker times a
standard matmul workload, publishes slowdown ratios) feeding the Malleus
ILP planner (``engine/strategy.py:53-98``) which emits a new hetero config
for hot switching. TPU formulation: per-device microbench of an
MXU-saturating matmul; ratios scale the cost model's ``mxu_efficiency``
and (in the elastic path) select the device subset to re-plan over with
the Galvatron search.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp


@dataclasses.dataclass
class StragglerReport:
    times_s: dict[int, float]           # device id → measured seconds
    ratios: dict[int, float]            # device id → time / best time

    def stragglers(self, threshold: float = 1.5) -> list[int]:
        return [d for d, r in self.ratios.items() if r > threshold]


class StragglerMonitor:
    """Times a standard matmul workload on each device."""

    def __init__(self, size: int = 2048, iters: int = 8,
                 dtype=jnp.bfloat16):
        self.size = size
        self.iters = iters
        self.dtype = dtype

    def _bench_device(self, device) -> float:
        x = jax.device_put(
            jnp.ones((self.size, self.size), self.dtype), device)

        @jax.jit
        def mm(a):
            for _ in range(4):
                a = a @ a / self.size
            return a

        mm(x).block_until_ready()  # compile
        t0 = time.perf_counter()
        for _ in range(self.iters):
            x = mm(x)
        x.block_until_ready()
        return (time.perf_counter() - t0) / self.iters

    def measure(self, devices: Optional[Sequence] = None
                ) -> StragglerReport:
        from hetu_tpu import telemetry
        with telemetry.span("straggler_measure", size=self.size):
            devices = list(devices) if devices is not None \
                else jax.devices()
            times = {d.id: self._bench_device(d) for d in devices}
        best = min(times.values())
        ratios = {i: t / best for i, t in times.items()}
        if telemetry.enabled():
            # the Malleus planner's input, continuously scrapeable: a
            # ratio gauge per device (1.0 = healthy, >threshold = replan)
            reg = telemetry.get_registry()
            g_ratio = reg.gauge("straggler_ratio",
                                "device slowdown vs the fastest peer")
            g_time = reg.gauge("straggler_bench_seconds",
                               "matmul microbench wall time")
            for d, t in times.items():
                g_time.set(t, device=str(d))
                g_ratio.set(ratios[d], device=str(d))
        return StragglerReport(times, ratios)


def replan_for_stragglers(report: StragglerReport, dims, topo, *,
                          threshold: float = 1.5):
    """Drop straggling devices and search a new strategy over the healthy
    subset (the Malleus flow: ratios → plan → hot switch/elastic restart).
    Returns (healthy_device_ids, best Candidate or None)."""
    from hetu_tpu.tools.galvatron import TPUTopology, search_uniform

    bad = set(report.stragglers(threshold))
    healthy = [d for d in report.ratios if d not in bad]
    # strategies need a power-of-two-ish device count; take the largest
    # divisor-friendly prefix
    n = len(healthy)
    while n > 1 and (n & (n - 1)):
        n -= 1
    healthy = healthy[:n]
    if not healthy:
        return [], None
    new_topo = TPUTopology(
        num_devices=len(healthy), peak_flops=topo.peak_flops,
        ici_bw=topo.ici_bw, dcn_bw=topo.dcn_bw,
        hbm_bytes=topo.hbm_bytes,
        mxu_efficiency=topo.mxu_efficiency /
        max(report.ratios[d] for d in healthy),
        dp_overlap=topo.dp_overlap)
    cands = search_uniform(dims, new_topo)
    return healthy, (cands[0] if cands else None)
