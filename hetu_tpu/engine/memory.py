"""Memory-plane ledger + remat policy engine.

The step-time/HBM tradeoff has three knobs — parallel degrees, remat
policy, per-device batch — and until now only the planner's private
memory formula priced them. This module is the ONE analytic model both
sides consume:

- the **byte ledger** (:func:`estimate_breakdown`): per-device bytes by
  class (params / grads / optimizer / activations) for a (model,
  Strategy) pair, the same arithmetic ``tools.galvatron.cost_model``
  ranks candidates with (selective activation recomputation factors per
  Korthikanti et al.; ZeRO shard divisors per Rajbhandari et al. SC'20);
- the **runtime recorder** (:func:`record_model_memory_plane`): the
  train step seeds a process-global snapshot + ``mem_*`` telemetry
  gauges on its first call, so ``trace_summary`` / ``bench.py`` report
  the memory plane next to the control/data planes — and the Perfetto
  counter tracks render it over time;
- the **remat policy engine** (:func:`derive_remat_mask`): given an HBM
  budget, derive the minimal per-layer recompute mask
  (``Strategy(remat_mask=...)`` → ``StackedBlocks``) instead of the
  all-or-nothing per-block switch.

Byte numbers here are ANALYTIC (model-shape arithmetic, optionally
scaled by the AOT-measured calibration) — the ground-truth companion is
``jax.local_devices()[0].memory_stats()`` where the backend exposes it
(``bench.py`` records both).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Optional, Sequence

from hetu_tpu.parallel.strategy import Strategy

#: activation bytes per (token, hidden) as a multiple of bytes_per_el —
#: the standard transformer residual accounting by remat policy
#: (none = every matmul input + attention residuals live to bwd;
#: selective = flash outputs + checkpointed dots only; full = block
#: boundaries; offload = streamed to host)
REMAT_ACT_FACTORS = {"none": 14.0, "selective": 6.0, "full": 2.0,
                     "offload": 1.0}

#: step-compute multiplier: recompute replays (part of) the forward
#: during backward — fwd is 1/3 of the 6N fwd+bwd total, selective
#: replays only attention+norms
REMAT_COMPUTE_FACTORS = {"none": 1.0, "selective": 1.12,
                         "full": 4.0 / 3.0, "offload": 4.0 / 3.0}


def act_factor(remat: str) -> float:
    return REMAT_ACT_FACTORS.get(remat, 14.0)


def compute_factor(remat: str) -> float:
    return REMAT_COMPUTE_FACTORS.get(remat, 1.0)


@dataclasses.dataclass(frozen=True)
class MemoryBreakdown:
    """Per-device bytes by class for one (model dims, Strategy) pair.

    ``act_bytes_per_microbatch`` is UNSCALED (the per-live-microbatch
    residual footprint); ``act_bytes`` applies schedule liveness
    (``live_microbatches``, the scan-flush pipeline keeps nm+pp-1 alive)
    and the measured ``act_scale`` calibration.
    """

    params_bytes: float
    grads_bytes: float
    opt_bytes: float
    act_bytes_per_microbatch: float
    live_microbatches: int
    act_scale: float
    remat: str
    remat_recompute_flops: float

    @property
    def act_bytes(self) -> float:
        return self.act_bytes_per_microbatch * self.live_microbatches \
            * self.act_scale

    @property
    def peak_bytes(self) -> float:
        return self.params_bytes + self.grads_bytes + self.opt_bytes \
            + self.act_bytes

    def classes(self) -> dict[str, float]:
        return {"params": self.params_bytes, "grads": self.grads_bytes,
                "opt": self.opt_bytes, "act": self.act_bytes}

    def to_record(self) -> dict:
        return {"kind": "memory_plane", "remat": self.remat,
                "peak_bytes": self.peak_bytes,
                "act_bytes_per_microbatch": self.act_bytes_per_microbatch,
                "live_microbatches": self.live_microbatches,
                "remat_recompute_flops": self.remat_recompute_flops,
                **{f"{k}_bytes": v for k, v in self.classes().items()}}


def estimate_breakdown(dims, strategy: Strategy, *,
                       act_scale: float = 1.0) -> MemoryBreakdown:
    """Analytic per-device memory breakdown (the arithmetic
    ``cost_model.estimate`` ranks with, split by class).

    ``dims`` is duck-typed on the ``ModelDims`` fields (num_layers,
    hidden, total_params(), layer_params(), seq_len, global_batch,
    bytes_per_el, ...).
    """
    s = strategy
    # expert params (rule "expert" → "ep") shard over ep on top of
    # tp·pp; dense params do NOT — the historical formula divided the
    # whole model by ep, under-pricing dense weights on MoE strategies
    # exactly when the planner compares ep against tp/fsdp
    expert_fn = getattr(dims, "layer_expert_params", None)
    expert_total = dims.num_layers * expert_fn() if callable(expert_fn) \
        else 0.0
    dense_total = dims.total_params() - expert_total
    p_shard = dense_total / (s.tp * s.pp) \
        + expert_total / (s.tp * s.pp * max(s.ep, 1))
    dp_shard = s.dp if (s.fsdp or s.zero) else 1
    opt_div = s.dp if s.zero else 1
    # weights bf16 + fp32 grads; fsdp shards the grad copy over dp
    # (ZeRO-3 reduce-scattered grads), two fp32 Adam moments under zero
    params_bytes = p_shard * 2.0
    grads_bytes = p_shard * (4.0 / dp_shard if s.fsdp else 4.0)
    opt_bytes = p_shard * 8.0 / opt_div

    b_loc = dims.global_batch / max(s.dp * s.ep, 1)
    seq_loc = dims.seq_len / s.cp
    nm = max(s.num_microbatches, 1)
    layers_per_stage = dims.num_layers / s.pp
    act_mb = b_loc / nm * seq_loc * dims.hidden * act_factor(s.remat) \
        * layers_per_stage * dims.bytes_per_el / s.tp
    if getattr(dims, "num_experts", 0) > 0:
        # MoE dispatch liveness: the fp32 capacity buffers (pre- and
        # post-a2a views, capacity_factor·T_loc·k·d each) are saved
        # residuals of the dispatch einsums — not tp-sharded, scaled by
        # the residual-stream remat ratio like everything else the
        # policy can free
        cf = getattr(dims, "moe_capacity_factor", 1.25)
        k = max(getattr(dims, "moe_top_k", 2), 1)
        moe_buf = 2.0 * cf * (b_loc / nm) * seq_loc * k \
            * dims.hidden * 4.0
        act_mb += moe_buf * layers_per_stage \
            * act_factor(s.remat) / act_factor("none")
    # the scan-flush pipeline keeps every microbatch's residuals live
    # until its backward REGARDLESS of remat (validated against XLA
    # memory_analysis — see cost_model history); plain accumulation
    # keeps one
    live_mb = (nm + s.pp - 1) if s.pp > 1 else 1

    # recompute FLOPs/step/device: the fwd share replayed during bwd
    tokens_loc = b_loc * dims.seq_len
    flops_layer = 6.0 * tokens_loc * dims.layer_params()
    flops_attn = 6.0 * b_loc * dims.seq_len * dims.seq_len \
        * dims.hidden / 2
    base_flops = (flops_layer + flops_attn) * layers_per_stage \
        / (s.tp * s.cp)
    recompute = (compute_factor(s.remat) - 1.0) * base_flops

    return MemoryBreakdown(
        params_bytes=params_bytes, grads_bytes=grads_bytes,
        opt_bytes=opt_bytes, act_bytes_per_microbatch=act_mb,
        live_microbatches=live_mb, act_scale=act_scale, remat=s.remat,
        remat_recompute_flops=recompute)


def layer_act_weights(dims) -> tuple:
    """Per-layer relative activation-byte weights from the ledger's
    per-class split: a layer's residual footprint decomposes into an
    MLP share and an attention share (proxied by each side's width —
    ``ModelDims.attn_param_share``), and the attention share scales
    with the layer's attention intensity (``dims.layer_attn_scale``:
    1.0 = full causal attention, ``window/seq_len`` for sliding-window
    layers). Homogeneous stacks get uniform weights."""
    n = dims.num_layers
    scales = getattr(dims, "layer_attn_scale", None)
    if scales is None:
        return (1.0,) * n
    if len(scales) != n:
        raise ValueError(
            f"layer_attn_scale has {len(scales)} entries for {n} layers")
    attn = dims.attn_param_share() if hasattr(dims, "attn_param_share") \
        else 0.5
    return tuple((1.0 - attn) + attn * float(s) for s in scales)


def derive_remat_mask(dims, strategy: Strategy, *,
                      hbm_budget_bytes: float,
                      act_scale: float = 1.0,
                      weights: Optional[Sequence[float]] = None
                      ) -> Optional[tuple]:
    """Per-layer recompute mask fitting ``hbm_budget_bytes`` with the
    fewest rematted layers.

    Returns ``None`` when the strategy fits WITHOUT recompute (uniform
    ``remat="none"`` is optimal — recompute is never free), else a
    ``Strategy(remat_mask=...)``-shaped tuple selecting the smallest
    set of layers that brings the ledger peak under budget. Raises
    ``ValueError`` when even full recompute does not fit (the planner
    must change parallel degrees instead). The rematted layers use
    ``strategy.remat`` when it names a policy, else "full" (matching
    ``StackedBlocks``' mask semantics).

    Layer selection is GREEDY BY SAVINGS, not a fixed prefix: each
    layer's live-residual bytes are weighted by ``weights`` (default:
    :func:`layer_act_weights` — the ledger's attention/MLP byte split
    times the per-layer attention intensity), so ATTENTION-HEAVY layers
    are rematted first (Korthikanti et al.: attention residuals
    dominate and recompute cheapest). A homogeneous stack has uniform
    weights and degrades to the historical leading-prefix mask (greedy
    ties break on layer index)."""
    import dataclasses as _dc
    none_bd = estimate_breakdown(
        dims, _dc.replace(strategy, remat="none"), act_scale=act_scale)
    if none_bd.peak_bytes <= hbm_budget_bytes:
        return None
    policy = strategy.remat if strategy.remat != "none" else "full"
    remat_bd = estimate_breakdown(
        dims, _dc.replace(strategy, remat=policy), act_scale=act_scale)
    if remat_bd.peak_bytes > hbm_budget_bytes:
        raise ValueError(
            f"over HBM budget even with remat={policy!r} on every "
            f"layer ({remat_bd.peak_bytes / 1e9:.2f}GB > "
            f"{hbm_budget_bytes / 1e9:.2f}GB) — change parallel "
            f"degrees, not remat")
    n = dims.num_layers
    w = tuple(weights) if weights is not None else layer_act_weights(dims)
    if len(w) != n:
        raise ValueError(f"weights has {len(w)} entries for {n} layers")
    wsum = sum(w)
    # per-layer activation contribution (schedule-scaled): the uniform
    # ledger total split by weight for the "none" residuals; the remat
    # floor (saved block boundaries / flash residuals) is uniform
    layer_none = [none_bd.act_bytes * wi / wsum for wi in w]
    layer_remat = remat_bd.act_bytes / n
    fixed = none_bd.params_bytes + none_bd.grads_bytes \
        + none_bd.opt_bytes
    need = fixed + sum(layer_none) - hbm_budget_bytes
    # biggest savings first; stable sort keeps index order on ties, so
    # uniform stacks produce the historical leading prefix
    order = sorted(range(n),
                   key=lambda i: -(layer_none[i] - layer_remat))
    chosen: set[int] = set()
    saved = 0.0
    for i in order:
        if saved >= need and chosen:
            break
        chosen.add(i)
        saved += max(layer_none[i] - layer_remat, 0.0)
    return tuple(i in chosen for i in range(n))


# -- shape plane: per-bucket pricing -----------------------------------------
#
# The bucket planner (data/hydraulis.plan_buckets) and the trainer's
# bucketed dispatch feed DIFFERENT seq-lens through one strategy; these
# helpers price each bucket with the same estimate_breakdown arithmetic
# so the planner's HBM gate and the runtime gauges can never disagree
# about what a long bucket costs.


def bucket_act_bytes(dims_base, strategy: Strategy, bucket_len: int,
                     rows: int, *, act_scale: float = 1.0) -> float:
    """Live activation bytes of one (bucket_len, rows) dispatch under
    ``strategy`` — ``estimate_breakdown`` at the bucket's own seq-len."""
    dims = dataclasses.replace(dims_base, seq_len=int(bucket_len),
                               global_batch=max(int(rows), 1))
    return estimate_breakdown(dims, strategy,
                              act_scale=act_scale).act_bytes


def bucket_peak_bytes(dims_base, strategy: Strategy,
                      plans: dict) -> dict[int, float]:
    """Ledger peak per bucket for a ``plan_buckets`` output
    (``{bucket_len: BucketPlan}``) — each bucket priced under ITS OWN
    strategy and row count. The honest per-bucket view the shape-plane
    bench and trace_summary report."""
    out: dict[int, float] = {}
    for L, plan in plans.items():
        dims = dataclasses.replace(dims_base, seq_len=int(L),
                                   global_batch=max(plan.batch_rows, 1))
        out[int(L)] = estimate_breakdown(dims, plan.strategy).peak_bytes
    return out


def cp_prefill_act_bytes(cfg, *, seq_len: int, cp: int = 1) -> float:
    """Activation bytes of ONE cp-sharded long-prompt prefill forward
    (the serving CP lane, ``ServingEngine(long_max_len=)``): per-device
    residuals of a no-remat, batch-1 forward at ``seq_len``, divided
    over the cp axis. The serving admission gate uses this to refuse a
    ``long_max_len`` whose prefill could not fit next to the arena."""
    from hetu_tpu.tools.galvatron.cost_model import ModelDims
    dims = ModelDims.from_config(cfg, seq_len=int(seq_len),
                                 global_batch=1)
    bd = estimate_breakdown(dims, Strategy(cp=max(int(cp), 1)))
    return bd.act_bytes_per_microbatch


# -- serving plane: KV-pool sizing -------------------------------------------
#
# The serving engine's admission control is a BYTES question — how many
# fixed-shape KV slots fit next to the weights — and this ledger is the
# one place that arithmetic lives (the training planner and the serving
# scheduler must not disagree about what a layer weighs).

#: bytes per KV element by cache dtype: fp32/bf16 dense caches, int8 =
#: 1 byte/elem + per-(position, head) fp32 scales amortized over
#: head_dim (``generation.init_kv_caches`` quantized layout)
KV_CACHE_BYTES_PER_EL = {"fp32": 4.0, "bf16": 2.0, "int8": 1.0}


def kv_bytes_per_block(cfg, *, block_size: int,
                       cache_dtype: str = "fp32", tp: int = 1) -> float:
    """Bytes of one ``block_size``-token K+V page across every layer —
    the allocation unit of the PAGED serving pool (the scheduler's
    free-block admission gate prices requests in these)."""
    if cache_dtype not in KV_CACHE_BYTES_PER_EL:
        raise ValueError(f"cache_dtype must be one of "
                         f"{sorted(KV_CACHE_BYTES_PER_EL)}, "
                         f"got {cache_dtype!r}")
    hkv = getattr(cfg, "num_kv_heads", None) or cfg.num_heads
    d = getattr(cfg, "head_dim", None) or cfg.hidden_size // cfg.num_heads
    rows = cfg.num_layers * block_size * (hkv / max(tp, 1))
    per_el = KV_CACHE_BYTES_PER_EL[cache_dtype]
    bytes_kv = 2.0 * rows * d * per_el          # K and V
    if cache_dtype == "int8":
        bytes_kv += 2.0 * rows * 4.0            # fp32 row scales
    return bytes_kv


def kv_bytes_per_slot(cfg, *, max_len: int, cache_dtype: str = "fp32",
                      tp: int = 1) -> float:
    """Per-slot bytes of one request's worst-case K+V rows across every
    layer — a ``max_len``-token page (back-compat unit; the paged pool
    allocates :func:`kv_bytes_per_block` at a time)."""
    return kv_bytes_per_block(cfg, block_size=max_len,
                              cache_dtype=cache_dtype, tp=tp)


def size_kv_blocks(cfg, *, hbm_budget_bytes: float, block_size: int,
                   cache_dtype: str = "fp32", tp: int = 1,
                   param_bytes_per_el: float = 4.0,
                   headroom: float = 0.1) -> int:
    """How many KV blocks fit in ``hbm_budget_bytes`` next to the
    weights (``param_bytes_per_el`` per parameter, sharded over tp).

    Raises ``ValueError`` when not even one block fits — the caller
    must shrink ``block_size``, quantize the cache, or raise tp."""
    from hetu_tpu.tools.galvatron.cost_model import ModelDims
    dims = ModelDims.from_config(cfg, seq_len=block_size, global_batch=1)
    weights = dims.total_params() * param_bytes_per_el / max(tp, 1)
    avail = hbm_budget_bytes * (1.0 - headroom) - weights
    per_block = kv_bytes_per_block(cfg, block_size=block_size,
                                   cache_dtype=cache_dtype, tp=tp)
    blocks = int(avail // per_block)
    if blocks < 1:
        raise ValueError(
            f"KV pool does not fit: weights {weights / 1e9:.2f}GB + one "
            f"{per_block / 1e6:.1f}MB block exceed the "
            f"{hbm_budget_bytes / 1e9:.2f}GB budget — shrink the "
            f"block/slot size, use an int8 cache, or raise tp")
    return blocks


def decode_attn_read_bytes(cfg, *, context_len: int, table_len: int,
                           block_size: int, rows: int = 1,
                           cache_dtype: str = "fp32", tp: int = 1,
                           kernel: str = "paged") -> float:
    """HBM bytes ONE slot's decode attention reads per fused step —
    the gather-tax arithmetic the kernel plane exists to kill.

    ``kernel="reference"`` prices the XLA-gather path: every layer
    MATERIALIZES the slot's full ``table_len``-row KV view
    (``gather_block_rows`` — written once by the gather, read back by
    the attention contraction, and on int8 arenas dequantized to the
    compute dtype first), so bytes scale with the TABLE WIDTH the
    long-prompt lane widened, not the live context. ``kernel="paged"``
    prices the Pallas kernel: only the ``ceil(context/block_size)``
    live pages stream HBM→VMEM, once, in the arena's own dtype (int8
    pages + their fp32 scales — the dequant happens in VMEM). ``rows``
    (1 classic decode, k+1 verify-lane, C packed-prefill) does not
    change the KV read — the q tile rides VMEM — so it is accepted and
    ignored; it documents the call shape."""
    del rows
    if kernel not in ("paged", "reference"):
        raise ValueError(f"kernel must be paged|reference, "
                         f"got {kernel!r}")
    if kernel == "paged":
        live = -(-int(context_len) // int(block_size))
        return live * kv_bytes_per_block(
            cfg, block_size=block_size, cache_dtype=cache_dtype, tp=tp)
    gathered = kv_bytes_per_block(cfg, block_size=table_len,
                                  cache_dtype=cache_dtype, tp=tp)
    if cache_dtype == "int8":
        # the reference path dequantizes the gathered view to fp32
        # scratch before the einsum reads it — a second, 4x-wide pass
        gathered += kv_bytes_per_block(cfg, block_size=table_len,
                                       cache_dtype="fp32", tp=tp)
    # written by the gather + read back by the attention contraction
    return 2.0 * gathered


def size_spill_arena(cfg, *, host_budget_bytes: float, block_size: int,
                     cache_dtype: str = "fp32", tp: int = 1) -> int:
    """How many KV blocks the host spill arena may park in
    ``host_budget_bytes`` of host memory.

    The resumable-preemption path (``serving/kv_pool.HostSpillArena``)
    evicts a running request by copying its blocks device→host; this is
    the pricing that gates those copies, and it is the SAME
    :func:`kv_bytes_per_block` arithmetic the device pool allocates
    with — a spilled block costs on the host exactly what it freed on
    the device (no weights term: the host side holds only KV). Raises
    when not even one block fits."""
    per_block = kv_bytes_per_block(cfg, block_size=block_size,
                                   cache_dtype=cache_dtype, tp=tp)
    blocks = int(float(host_budget_bytes) // per_block)
    if blocks < 1:
        raise ValueError(
            f"spill arena does not fit: one {per_block / 1e6:.1f}MB "
            f"block exceeds the {host_budget_bytes / 1e6:.1f}MB host "
            f"budget — raise the budget or shrink block_size")
    return blocks


def size_spill_tiers(cfg, *, host_budget_bytes: float,
                     peer_budget_bytes: float = 0.0, block_size: int,
                     cache_dtype: str = "fp32", tp: int = 1) -> dict:
    """Per-tier block capacities for a chained spill store
    (device→host→peer, ISSUE 18): ``{"host": n, "peer": m}``.

    Both tiers are priced with the SAME :func:`kv_bytes_per_block`
    arithmetic as :func:`size_spill_arena`, so demotion accounting
    stays in arena blocks end to end — a block demoted to the peer
    tier frees on the host exactly what it costs the peer. The host
    tier must fit at least one block (same contract as
    :func:`size_spill_arena`); a zero peer budget prices an unchained
    arena (``peer: 0``)."""
    host = size_spill_arena(cfg, host_budget_bytes=host_budget_bytes,
                            block_size=block_size,
                            cache_dtype=cache_dtype, tp=tp)
    per_block = kv_bytes_per_block(cfg, block_size=block_size,
                                   cache_dtype=cache_dtype, tp=tp)
    peer = int(float(peer_budget_bytes) // per_block) \
        if peer_budget_bytes else 0
    return {"host": host, "peer": peer}


def size_adapter_arena(cfg, *, r: int, max_adapters: int,
                       dtype_bytes: float = 4.0) -> int:
    """Device bytes of the multi-tenant LoRA adapter arena
    (``serving/tenancy.py``): per layer and per arena page, an
    ``(in, r)`` A plus an ``(r, out)`` B for every adapter-targetable
    projection — q/k/v/out always, plus the dense FFN matrices
    (gated gate/up/down when the config carries ``intermediate_size``,
    GPT fc_in/fc_out otherwise; MoE expert weights are not adapter
    targets, so MoE FFNs price zero). This is what the serving
    engine's admission gate subtracts from ``hbm_budget_bytes`` before
    sizing the KV pool — adapter pages are HBM the KV arena can no
    longer have."""
    L = int(cfg.num_layers)
    E = int(cfg.hidden_size)
    heads = int(cfg.num_heads)
    hd = int(getattr(cfg, "head_dim", None) or E // heads)
    kvh = int(getattr(cfg, "num_kv_heads", None) or heads)
    q_out, kv_out = heads * hd, kvh * hd
    dims = [(E, q_out), (E, kv_out), (E, kv_out), (q_out, E)]
    if getattr(cfg, "num_experts", 0) <= 0:
        inter = getattr(cfg, "intermediate_size", None)
        if inter is not None:
            dims += [(E, int(inter)), (E, int(inter)), (int(inter), E)]
        else:
            hidden = int(getattr(cfg, "mlp_ratio", 4)) * E
            dims += [(E, hidden), (hidden, E)]
    per_page = sum((i + o) * int(r) for i, o in dims) * L
    return int(per_page * int(max_adapters) * float(dtype_bytes))


def size_kv_pool(cfg, *, hbm_budget_bytes: float, max_len: int,
                 cache_dtype: str = "fp32", tp: int = 1,
                 param_bytes_per_el: float = 4.0,
                 headroom: float = 0.1) -> int:
    """How many serving slots fit in ``hbm_budget_bytes`` next to the
    weights — :func:`size_kv_blocks` with one ``max_len``-token block
    per slot (back-compat wrapper; the paged pool sizes in blocks)."""
    return size_kv_blocks(cfg, hbm_budget_bytes=hbm_budget_bytes,
                          block_size=max_len, cache_dtype=cache_dtype,
                          tp=tp, param_bytes_per_el=param_bytes_per_el,
                          headroom=headroom)


# -- runtime ledger ----------------------------------------------------------
#
# Mirrors parallel.overlap's pattern: a module-level snapshot tests and
# bench.py read without enabling telemetry, plus mem_* gauges in the
# registry when it is on. Last-write-wins per class (gauge semantics —
# the memory plane is a state, not a flow).

_LOCK = threading.Lock()
_LEDGER: dict[str, float] = {}


def record_memory_plane(bd: MemoryBreakdown,
                        strategy: Optional[Strategy] = None) -> None:
    """Install ``bd`` as the process's current memory-plane snapshot and
    mirror it into the ``mem_*`` telemetry gauges."""
    vals = {f"{k}_bytes": float(v) for k, v in bd.classes().items()}
    vals["peak_bytes"] = float(bd.peak_bytes)
    vals["remat_recompute_flops"] = float(bd.remat_recompute_flops)
    with _LOCK:
        _LEDGER.update(vals)
        _LEDGER["remat"] = bd.remat
        if strategy is not None:
            _LEDGER["strategy"] = strategy.to_json()
    from hetu_tpu import telemetry
    if telemetry.enabled():
        reg = telemetry.get_registry()
        for name, help_ in (
                ("mem_params_bytes", "ledger: param bytes per device"),
                ("mem_grads_bytes", "ledger: gradient bytes per device"),
                ("mem_opt_bytes", "ledger: optimizer-state bytes"),
                ("mem_act_bytes", "ledger: live activation bytes"),
                ("mem_peak_bytes", "ledger: peak HBM estimate"),
                ("mem_remat_recompute_flops",
                 "ledger: recompute FLOPs/step the remat policy costs")):
            key = name[len("mem_"):]
            reg.gauge(name, help_).set(vals[key])


def record_model_memory_plane(model, strategy: Strategy,
                              batch: dict) -> Optional[MemoryBreakdown]:
    """Derive dims from the model config + batch shape and record the
    breakdown (called once per compiled step, on its first invocation).
    Returns None for model families without transformer dims."""
    cfg = getattr(model, "cfg", None)
    if cfg is None or not hasattr(cfg, "num_layers") \
            or not hasattr(cfg, "hidden_size"):
        return None
    ids = batch.get("input_ids") if hasattr(batch, "get") else None
    if ids is None or getattr(ids, "ndim", 0) < 2:
        return None
    from hetu_tpu.tools.galvatron.cost_model import ModelDims
    dims = ModelDims.from_config(cfg, seq_len=int(ids.shape[1]),
                                 global_batch=int(ids.shape[0]))
    bd = estimate_breakdown(dims, strategy)
    record_memory_plane(bd, strategy)
    return bd


def memory_stats() -> dict:
    """Snapshot of the last recorded memory plane ({} before any step)."""
    with _LOCK:
        return dict(_LEDGER)


def reset_memory_stats() -> None:
    with _LOCK:
        _LEDGER.clear()


def device_peak_bytes() -> Optional[int]:
    """Ground truth where available: the backend's own peak allocation
    (``memory_stats()["peak_bytes_in_use"]`` on TPU; None on CPU)."""
    import jax
    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    peak = stats.get("peak_bytes_in_use")
    return int(peak) if peak is not None else None
