"""Train state: one pytree holding step / params / optimizer state.

The reference keeps params, grads and opt states in separate device buffers
tracked by the executable graph (``ParamBuffer``, ``executable_graph.h``);
hot switching re-shards each with dedicated P2P plans
(``switch_exec_graph.h:42-48`` modes). Designing the state as a *single
pytree* makes all of that one ``jax.device_put`` with new shardings.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp


class TrainState(NamedTuple):
    step: jax.Array        # scalar int32
    params: Any            # nested-dict param pytree
    opt_state: Any         # optimizer transform state


def new_train_state(params, opt) -> TrainState:
    return TrainState(jnp.zeros([], jnp.int32), params, opt.init(params))
