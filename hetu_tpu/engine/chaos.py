"""Chaos harness: kill-based fault injection for the preemption plane.

SURVEY §5.3 ends with "no kill-based chaos testing": the reference (and,
until this module, this tree) could *recover* from failures but nothing
ever proved it — every elastic code path was exercised only by polite,
cooperative exits. This module makes failure injectable on purpose and
continuously testable:

- :func:`chaos_point` — named in-line injection sites compiled into
  production code paths (``dist_ckpt.between_tensor_and_index``,
  ``trainer.mid_switch``, ...). Disarmed they cost one dict lookup.
  Armed (programmatically via :func:`arm`, or through the environment
  for subprocess workers — ``HETU_CHAOS_POINT``) they SIGKILL the
  process or raise :class:`ChaosError` at exactly that site, after an
  optional hit count — "die between the tensor-file rename and the
  index write" becomes a one-line test.
- :class:`ChaosMonkey` — a scheduler over named kill targets (pool
  workers via ``ElasticWorkerPool.kill_worker``, simulated in-process
  workers via their heartbeat, the coordinator/controller itself).
  Every kill lands a ``chaos_kill`` flight event and a
  ``chaos_kills_total{target=...}`` counter *in the surviving process*
  (the victim of a SIGKILL writes nothing — the injector is the
  forensic witness), and stamps :func:`last_kill_ts` so the recovery
  path can report detection latency (``elastic_detect_seconds``).

The assertion side lives in ``engine/elastic.py`` (the supervisor that
must survive these kills) and ``tests/test_chaos.py`` (loss-curve
continuity vs an undisturbed run). docs/ELASTICITY.md documents the
knobs.
"""

from __future__ import annotations

import os
import signal
import threading
import time
from typing import Callable, Optional

from hetu_tpu.telemetry.flight import flight_record
from hetu_tpu.utils.logging import get_logger

#: environment knobs for subprocess workers (ElasticWorkerPool ships its
#: env to every worker): HETU_CHAOS_POINT="<name>[:<nth-hit>]" arms one
#: point, HETU_CHAOS_ACTION ∈ {sigkill, raise}, HETU_CHAOS_RANK limits
#: the arming to one worker rank (default: all ranks).
_ENV_POINT = "HETU_CHAOS_POINT"
_ENV_ACTION = "HETU_CHAOS_ACTION"
_ENV_RANK = "HETU_CHAOS_RANK"
_ENV_GEN = "HETU_CHAOS_GEN"


class ChaosError(RuntimeError):
    """Raised by an armed chaos point with ``action="raise"``."""


_lock = threading.Lock()
_armed: dict[str, dict] = {}      # name -> {action, after, hits}
_fired: list[dict] = []           # raise-action firings (test forensics)
_last_kill: dict[str, float] = {}  # target -> unix ts of last injected kill


def arm(name: str, *, action: str = "raise", after: int = 1) -> None:
    """Arm ``name``: the ``after``-th :func:`chaos_point` hit fires
    (``action``: ``"raise"`` → :class:`ChaosError`, ``"sigkill"`` →
    ``SIGKILL`` to *this* process — the real preemption shape)."""
    if action not in ("raise", "sigkill"):
        raise ValueError(f"chaos action must be raise|sigkill: {action!r}")
    with _lock:
        _armed[name] = {"action": action, "after": int(after), "hits": 0}


def disarm(name: Optional[str] = None) -> None:
    """Disarm one point (or all of them; also clears the fired log)."""
    with _lock:
        if name is None:
            _armed.clear()
            _fired.clear()
        else:
            _armed.pop(name, None)


def fired() -> list[dict]:
    """Raise-action firings so far (``[{point, hit, ...fields}]``)."""
    with _lock:
        return list(_fired)


def _env_spec(name: str) -> Optional[dict]:
    """Arming from the environment (subprocess workers). Returns the
    spec when ``name`` is armed for this process, else None."""
    spec = os.environ.get(_ENV_POINT)
    if not spec:
        return None
    rank = os.environ.get(_ENV_RANK)
    if rank is not None and os.environ.get("HETU_RANK") != rank:
        return None
    # restartable pools: arm only one generation, or the restarted
    # worker dies at the same point forever
    gen = os.environ.get(_ENV_GEN)
    if gen is not None and os.environ.get("HETU_GENERATION") != gen:
        return None
    point, _, after = spec.partition(":")
    if point != name:
        return None
    return {"action": os.environ.get(_ENV_ACTION, "sigkill"),
            "after": int(after) if after else 1}


def chaos_point(name: str, **fields) -> None:
    """An injection site. Disarmed: a dict lookup. Armed: count the hit
    and, on the ``after``-th one, record a ``chaos_kill`` flight event
    and die (SIGKILL) or raise (:class:`ChaosError`)."""
    with _lock:
        spec = _armed.get(name)
        if spec is None:
            env = _env_spec(name)
            if env is None:
                return
            spec = _armed[name] = {**env, "hits": 0}
        spec["hits"] += 1
        if spec["hits"] != spec["after"]:
            return
        action = spec["action"]
        _fired.append({"point": name, "hit": spec["hits"], **fields})
    # outside the lock: the flight record and the kill must not deadlock
    # a recorder used by other threads
    flight_record("chaos_kill", target=name, action=action,
                  **_with_trace(fields))
    _count_kill(name)
    get_logger().warning(f"chaos: firing {action} at point {name!r}")
    if action == "sigkill":
        # SIGKILL is uncatchable — leave the postmortem NOW (the dump is
        # atomic; a best-effort failure must not save the victim)
        try:
            from hetu_tpu.telemetry.flight import get_flight_recorder
            get_flight_recorder().dump(reason="chaos_kill")
        except Exception:
            pass
        os.kill(os.getpid(), signal.SIGKILL)
    raise ChaosError(f"chaos point {name!r} fired")


def _with_trace(fields: dict) -> dict:
    """Stamp the process's active trace context (a fleet dispatch or a
    weight push in flight) into a chaos event's fields, so
    ``tools/fleet_trace.py`` can pin a latency spike on the kill that
    caused it (ISSUE 16). No-op when no trace is active or the caller
    already set one."""
    if "trace" in fields:
        return fields
    from hetu_tpu.telemetry.tracecontext import current_traceparent
    tp = current_traceparent()
    return dict(fields, trace=tp) if tp else fields


def _count_kill(target: str) -> None:
    with _lock:
        _last_kill[target] = time.time()
        _last_kill["*"] = _last_kill[target]
    from hetu_tpu import telemetry
    if telemetry.enabled():
        telemetry.get_registry().counter(
            "chaos_kills_total",
            "injected kills by target (chaos harness)").inc(target=target)


def last_kill_ts(target: str = "*") -> Optional[float]:
    """Unix timestamp of the most recent injected kill (``"*"`` = any
    target) — the recovery path subtracts this to report detection
    latency. None when no kill was injected in this process."""
    with _lock:
        return _last_kill.get(target)


def _clear_for_tests() -> None:
    with _lock:
        _armed.clear()
        _fired.clear()
        _last_kill.clear()


class ChaosMonkey:
    """Kill scheduler over named targets.

    A target is ``(name, kill_fn)``: a pool worker
    (``lambda: pool.kill_worker(rank)``), a simulated in-process worker
    (``heartbeat.stop`` — the CPU-simulation stand-in for a SIGKILLed
    host), or the coordinator/controller. Kills can be driven
    explicitly (:meth:`kill` — deterministic tests, step-indexed
    injection) or on a wall-clock period (:meth:`start` — soak runs).
    Every kill is witnessed here: ``chaos_kill`` flight event +
    ``chaos_kills_total{target=...}`` + the :func:`last_kill_ts` stamp.
    """

    def __init__(self, targets: Optional[dict[str, Callable[[], None]]]
                 = None, *, period_s: float = 0.0, max_kills: int = 0,
                 seed: int = 0):
        import random
        self.targets = dict(targets or {})
        self.period_s = float(period_s)
        self.max_kills = int(max_kills)
        self.kills: list[dict] = []
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @classmethod
    def for_pool(cls, pool, ranks=None, **kw) -> "ChaosMonkey":
        """Targets ``worker-<rank>`` → ``pool.kill_worker(rank)`` for an
        :class:`~hetu_tpu.rpc.launcher.ElasticWorkerPool`."""
        ranks = range(pool.num_workers) if ranks is None else ranks
        return cls({f"worker-{r}": (lambda r=r: pool.kill_worker(r))
                    for r in ranks}, **kw)

    def add_target(self, name: str, kill_fn: Callable[[], None]) -> None:
        self.targets[name] = kill_fn

    def kill(self, name: Optional[str] = None, **fields) -> str:
        """Kill ``name`` (or a uniformly random target). Records the
        witness events, then invokes the target's kill function."""
        if not self.targets:
            raise ValueError("chaos monkey has no targets")
        if name is None:
            name = self._rng.choice(sorted(self.targets))
        kill_fn = self.targets[name]
        fields = _with_trace(fields)
        flight_record("chaos_kill", target=name, action="kill", **fields)
        _count_kill(name)
        self.kills.append({"target": name, "ts": time.time(), **fields})
        get_logger().warning(f"chaos: killing {name}")
        kill_fn()
        return name

    # -- wall-clock soak mode ------------------------------------------------
    def start(self) -> "ChaosMonkey":
        if self.period_s <= 0:
            raise ValueError("start() needs period_s > 0")
        self._stop = threading.Event()

        def run():
            while not self._stop.wait(self.period_s):
                if self.max_kills and len(self.kills) >= self.max_kills:
                    return
                try:
                    self.kill()
                except Exception as e:   # a dead target is not fatal
                    get_logger().warning(f"chaos kill failed: {e}")

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="chaos-monkey")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "ChaosMonkey":
        return self

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False
