"""Supervised fine-tuning trainer.

Parity target: ``python/hetu/engine/sft_trainer.py`` — instruction tuning
where loss applies only to response tokens (prompt positions masked to
``ignore_index``), usually combined with LoRA (the LobRA multi-task
example).
"""

from __future__ import annotations

from typing import Iterable, Optional, Sequence

import numpy as np

from hetu_tpu.engine.trainer import Trainer


def make_sft_batch(prompts: Sequence[np.ndarray],
                   responses: Sequence[np.ndarray], seq_len: int, *,
                   pad_id: int = 0, ignore_index: int = -100) -> dict:
    """Build (input_ids, labels, positions) with prompt tokens masked out
    of the loss. Each example is ``prompt + response`` truncated/padded to
    ``seq_len``."""
    n = len(prompts)
    input_ids = np.full((n, seq_len), pad_id, np.int32)
    labels = np.full((n, seq_len), ignore_index, np.int32)
    positions = np.zeros((n, seq_len), np.int32)
    for r, (p, a) in enumerate(zip(prompts, responses)):
        seq = np.concatenate([np.asarray(p), np.asarray(a)])[:seq_len]
        L = len(seq)
        input_ids[r, :L] = seq
        positions[r, :L] = np.arange(L)
        # next-token labels, but only where the *predicted* token is in
        # the response
        lab = np.full(L, ignore_index, np.int64)
        start = max(len(p) - 1, 0)           # predicting first response tok
        lab[start:L - 1] = seq[start + 1:L]
        labels[r, :L] = lab
    return {"input_ids": input_ids, "labels": labels,
            "positions": positions}


def sft_batches(prompts, responses, *, seq_len: int, batch_size: int,
                shuffle: bool = True, seed: int = 0) -> Iterable[dict]:
    idx = np.arange(len(prompts))
    if shuffle:
        np.random.default_rng(seed).shuffle(idx)
    for i in range(0, len(idx) - batch_size + 1, batch_size):
        sel = idx[i:i + batch_size]
        yield make_sft_batch([prompts[j] for j in sel],
                             [responses[j] for j in sel], seq_len)


class SFTTrainer(Trainer):
    """Trainer whose ``fit`` consumes (prompt, response) pairs."""

    def fit(self, prompts, responses, *, seq_len: int, batch_size: int,
            steps: Optional[int] = None, shuffle: bool = True,
            seed: int = 0):
        batches = sft_batches(prompts, responses, seq_len=seq_len,
                              batch_size=batch_size, shuffle=shuffle,
                              seed=seed)
        return self.train(batches, steps=steps)
