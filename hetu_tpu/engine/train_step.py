"""Train-step compiler: Strategy → jitted sharded train step.

This is the TPU-native replacement for the reference's exec-graph pipeline
(``DefineAndRunGraph::Run`` → ``Instantiate`` → ``SubstituteCommOp`` →
``CrucialRun``, SURVEY §3.3): a :class:`TrainPlan` compiles a Strategy into
(mesh, param/opt-state/batch shardings, activation-sharding context), and
:func:`build_train_step` closes a jitted step over it. Every Strategy flag is
consumed here:

- ``dp``      — batch sharded over dp; GSPMD emits the grad allreduce.
- ``tp``      — param logical axes + activation constraints; vocab-parallel
                LM head under ``shard_map``.
- ``cp``      — sequence dim sharded; ring attention (``parallel.ring_attention``).
- ``zero``    — optimizer moments sharded over dp
                (``parallel.zero.opt_state_partition_specs``).
- ``fsdp``    — params themselves sharded over dp via the "embed" axis rule.
- ``remat``/``offload`` — ``jax.checkpoint`` policy applied per block.
- ``num_microbatches`` — grad-accumulation ``lax.scan`` (pp=1) or the
                pipeline schedule (pp>1).

Control-plane latency: a :class:`StepCache` memoizes the compiled
artifacts of :func:`compile_strategy` — (TrainPlan, jitted step, eval) per
(model, optimizer, Strategy, attn/donate/policy) — so hot switching
A→B→A never re-traces on the return leg (the reference's ExecGraphPlan
pool), and :mod:`hetu_tpu.engine.precompile` can AOT-compile candidate
strategies into the same entries on a background thread.
"""

from __future__ import annotations

import dataclasses
import functools
import threading
import time
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hetu_tpu.engine.state import TrainState, new_train_state
from hetu_tpu.nn.module import Module
from hetu_tpu.optim.base import Transform, apply_updates
from hetu_tpu.optim.clipping import global_norm
from hetu_tpu.parallel.sharding import (
    ActivationSharding, named_shardings, param_partition_specs,
)
from hetu_tpu.parallel.strategy import Strategy
from hetu_tpu.parallel.zero import opt_state_partition_specs


@dataclasses.dataclass(frozen=True)
class TrainPlan:
    """Compiled sharding plan for one Strategy (the analogue of the
    reference's ``ExecGraphPlan``, ``define_and_run_graph.h:23-64``)."""

    strategy: Strategy
    mesh: Mesh
    param_specs: Any
    state_specs: TrainState          # pytree of PartitionSpec
    state_shardings: TrainState      # pytree of NamedSharding
    act: ActivationSharding

    def batch_sharding(self, ndim: int = 2) -> NamedSharding:
        return NamedSharding(self.mesh, self.strategy.data_spec(ndim))

    def shard_batch(self, batch: dict) -> dict:
        """Place a host batch onto the mesh per the data spec.

        Under zigzag CP the sequence dim (axis 1) of every batch array is
        permuted into the load-balanced layout first (tokens, labels,
        positions and segment ids all move together, so the per-token loss
        is unchanged); ``positions`` is synthesized when absent so rotary
        still sees *original* positions.
        """
        st = self.strategy
        if st.effective_cp_layout == "zigzag":
            from hetu_tpu.data.packing import zigzag_permute
            batch = dict(batch)
            if batch.get("positions") is None and "input_ids" in batch:
                b, s = batch["input_ids"].shape[:2]
                batch["positions"] = jnp.broadcast_to(
                    jnp.arange(s, dtype=jnp.int32)[None, :], (b, s))
            # only the known seq-dim arrays move; custom keys (per-row
            # weights etc.) keep their layout
            seq_keys = ("input_ids", "labels", "positions", "segment_ids")
            batch = {
                k: zigzag_permute(v, st.cp, axis=1)
                if k in seq_keys and v is not None else v
                for k, v in batch.items()
            }
        return {
            k: jax.device_put(v, self.batch_sharding(jnp.ndim(v)))
            for k, v in batch.items() if v is not None
        }


def make_plan(model: Module, opt: Transform, strategy: Strategy,
              devices=None) -> TrainPlan:
    from hetu_tpu import telemetry
    with telemetry.span("make_plan", strategy=strategy.to_json()):
        return _make_plan(model, opt, strategy, devices)


def _make_plan(model: Module, opt: Transform, strategy: Strategy,
               devices=None) -> TrainPlan:
    mesh = strategy.build_mesh(devices)
    rules = strategy.axis_rules()
    param_specs = param_partition_specs(model, rules, mesh=mesh)
    fsdp_gather_specs = None
    if strategy.fsdp:
        # ZeRO-3 completeness pass: the rule table's "embed"→dp covers the
        # transformer families' big params, but ANY param another model
        # family declares must shard too — add dp onto the first unsharded
        # divisible dim of every leaf the rules left fully replicated
        # (r3 VERDICT weak-7: rule table was model-family-coupled).
        from hetu_tpu.nn.module import ParamSpec
        from hetu_tpu.parallel.zero import add_axis_to_spec
        shapes = jax.tree.map(lambda ps: ps.shape, model.abstract_specs(),
                              is_leaf=lambda x: isinstance(x, ParamSpec))
        # per-layer gather ring (fsdp_overlap="ring"): every block leaf's
        # dp shard must live on an INNER dim — a shard on the stacked
        # ``layers`` dim cannot be regathered one layer at a time — so
        # the completeness pass skips dim 0 for the block subtree. Models
        # without a stacked block list keep the GSPMD formulation.
        ring_blocks = (strategy.fsdp_overlap == "ring"
                       and isinstance(param_specs, dict)
                       and "blocks" in param_specs)

        def _complete(spec_tree, shape_tree, skip0: bool):
            return jax.tree.map(
                lambda spec, shape: add_axis_to_spec(
                    spec, shape, mesh, "dp",
                    skip_dims=(0,) if skip0 else ()),
                spec_tree, shape_tree,
                is_leaf=lambda x: isinstance(x, P))

        if ring_blocks:
            param_specs = {
                k: _complete(v, shapes[k], k == "blocks")
                for k, v in param_specs.items()}
            if mesh.shape.get("dp", 1) > 1:
                from hetu_tpu.parallel.overlap import per_layer_gather_specs
                fsdp_gather_specs = per_layer_gather_specs(
                    param_specs["blocks"])
        else:
            param_specs = _complete(param_specs, shapes, False)
    params_struct = model.abstract_params()
    opt_struct = jax.eval_shape(opt.init, params_struct)
    opt_specs = opt_state_partition_specs(
        opt_struct, params_struct, param_specs, mesh=mesh,
        zero_axis="dp" if strategy.zero else None)
    state_specs = TrainState(P(), param_specs, opt_specs)
    act = ActivationSharding(
        mesh,
        batch=("dp", "ep") if strategy.ep > 1 else "dp",
        seq="cp", tp="tp", cp_layout=strategy.effective_cp_layout,
        cp_impl=strategy.cp_impl, sp=strategy.sp,
        tp_overlap=strategy.tp_overlap,
        fsdp_overlap=strategy.fsdp_overlap if strategy.fsdp else "off",
        fsdp_specs=fsdp_gather_specs,
        ep_overlap=strategy.ep_overlap if strategy.ep > 1 else "off",
        ep_chunks=strategy.ep_chunks)
    return TrainPlan(strategy, mesh, param_specs, state_specs,
                     named_shardings(mesh, state_specs), act)


def init_state(model: Module, opt: Transform, plan: TrainPlan,
               key: jax.Array, dtype=None) -> TrainState:
    """Initialize the train state directly in its sharded layout."""
    fn = jax.jit(lambda k: new_train_state(model.init(k, dtype=dtype), opt),
                 out_shardings=plan.state_shardings)
    return fn(key)


# -- trace accounting -------------------------------------------------------
# jit re-traces run the Python step body; executions do not. A counter
# bumped INSIDE the body is therefore an exact re-trace/recompile count —
# the signal the compile-count regression tests assert on (and the
# telemetry registry mirrors it when enabled).
_TRACE_COUNTS: dict[str, int] = {}
_TRACE_LOCK = threading.Lock()
_TRACE_LOCAL = threading.local()   # per-thread total, see trace_total()


def record_trace(what: str) -> None:
    """Count one (re)trace of a jitted step body. Called at trace time
    only — a warm executable never re-enters the Python body."""
    with _TRACE_LOCK:
        _TRACE_COUNTS[what] = _TRACE_COUNTS.get(what, 0) + 1
    _TRACE_LOCAL.total = getattr(_TRACE_LOCAL, "total", 0) + 1
    from hetu_tpu import telemetry
    if telemetry.enabled():
        telemetry.get_registry().counter(
            "step_traces_total",
            "jit traces of step bodies (recompile detector)").inc(
                what=what)


def trace_counts() -> dict[str, int]:
    """``{step-kind: trace count}`` since process start (or last reset),
    across ALL threads (background AOT lowers included)."""
    with _TRACE_LOCK:
        return dict(_TRACE_COUNTS)


def trace_total() -> int:
    """Step-body traces recorded ON THE CALLING THREAD. The Trainer
    snapshots this around each step call to attribute a traced step's
    wall time to the ``compile`` goodput category instead of
    ``compute`` — per-thread so a background precompile worker tracing
    concurrently never misclassifies foreground compute as compile."""
    return getattr(_TRACE_LOCAL, "total", 0)


def reset_trace_counts() -> None:
    with _TRACE_LOCK:
        _TRACE_COUNTS.clear()


# -- step cache -------------------------------------------------------------
def _batch_key(batch: dict) -> tuple:
    """Shape/dtype signature of a batch dict (device arrays, host numpy
    or ShapeDtypeStructs — anything with .shape/.dtype)."""
    def sig(v):
        if not hasattr(v, "shape") or not hasattr(v, "dtype"):
            import numpy as np
            v = np.asarray(v)
        return tuple(v.shape), str(v.dtype)

    return tuple(sorted((k,) + sig(v) for k, v in batch.items()
                        if v is not None))


class CachedStep:
    """One compiled strategy: plan + jitted step/eval + AOT executables.

    Calling the entry runs the step. When an ahead-of-time executable for
    the batch signature exists (``engine.precompile``), it is used — zero
    traces even on the very first step after a switch; otherwise the
    jitted ``step_fn`` runs (which re-uses ITS executable cache across
    A→B→A switches because the entry object itself is memoized).
    """

    __slots__ = ("plan", "step_fn", "eval_fn", "aot", "_aot_ok",
                 "compile_seconds", "_refs")

    def __init__(self, plan, step_fn, eval_fn=None, *,
                 compile_seconds: float = 0.0, refs: tuple = ()):
        self.plan = plan
        self.step_fn = step_fn
        self.eval_fn = eval_fn
        self.aot: dict = {}          # batch signature -> Compiled
        self._aot_ok: set = set()    # signatures proven callable
        self.compile_seconds = compile_seconds
        # strong refs (model, opt): entries are keyed by object identity,
        # pinning the objects guarantees an id() is never reused while
        # its cache entry is alive
        self._refs = refs

    def __call__(self, state, batch):
        if self.aot:
            key = _batch_key(batch)
            exe = self.aot.get(key)
            if exe is not None:
                # the AOT executable bypasses step_fn, and with it the
                # host-side data/memory-plane accounting — invoke the
                # hook build_train_step attached (None for pipeline /
                # hetero step fns, which do their own accounting)
                hook = getattr(self.step_fn, "on_execute", None)
                if key in self._aot_ok:
                    if hook is not None:
                        hook(batch)
                    return exe(state, batch)
                try:
                    out = exe(state, batch)
                except (TypeError, ValueError):
                    # aval drift raises TypeError, sharding drift raises
                    # ValueError — both BEFORE consuming donated buffers
                    # — drop the stale executable and fall back to jit
                    self.aot.pop(key, None)
                else:
                    self._aot_ok.add(key)
                    if hook is not None:
                        hook(batch)
                    return out
        return self.step_fn(state, batch)


class StepCache:
    """Memo of :class:`CachedStep` entries keyed by
    (model, optimizer, Strategy, attn_impl, donate, policy, devices).

    The analogue of the reference's ``ExecGraphPlan`` pool
    (``define_and_run_graph.h:23-64``) lifted to a process-wide resource:
    every Trainer (and the AOT pre-compiler) shares the default instance,
    so a strategy compiled once — eagerly, in the background, or by a
    previous run via the persistent XLA cache — is a lookup forever
    after. Bounded LRU so long sweeps cannot pin unbounded executables.
    Thread-safe with single-flight builds (a background precompile and a
    foreground switch racing to the same key compile once).
    """

    def __init__(self, capacity: int = 64):
        self.capacity = capacity
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self._entries: dict = {}          # insertion-ordered => LRU
        self._building: dict = {}         # key -> threading.Event
        self._gen = 0                     # bumped by clear(): in-flight
                                          # builds from before a clear
                                          # must not re-populate it
        self._lock = threading.RLock()

    @staticmethod
    def key_for(model, opt, strategy, *, attn_impl: str = "auto",
                donate: bool = True, policy_key: str = "",
                devices=None, bucket: int = 0) -> tuple:
        """``bucket``: the seq-len bucket this entry serves (0 = the
        unbucketed entry). Bucketed training (``TrainerConfig(
        seq_buckets=...)``) keeps one CachedStep per (strategy, bucket)
        so each entry's jit/AOT caches hold exactly one shape and the
        AOT pre-compiler (``engine.precompile``) can enumerate bucketed
        variants addressably — every key-bearing field here must
        round-trip through its candidate enumeration (quick-tier lint
        in tests/test_shape_plane.py)."""
        dev_key = None if devices is None else \
            tuple(getattr(d, "id", d) for d in devices)
        return (id(model), id(opt), strategy, attn_impl, donate,
                policy_key, dev_key, int(bucket))

    def _count(self, hit: bool) -> None:
        from hetu_tpu import telemetry
        if hit:
            self.hits += 1
        else:
            self.misses += 1
        if telemetry.enabled():
            telemetry.get_registry().counter(
                "step_cache_hits_total" if hit
                else "step_cache_misses_total",
                "StepCache lookups that found / missed a compiled "
                "entry").inc()

    def lookup(self, key) -> Optional[CachedStep]:
        """Peek without building (does not count a miss)."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:                    # refresh LRU order
                self._entries.pop(key)
                self._entries[key] = entry
            return entry

    def get_or_build(self, key, builder: Callable[[], CachedStep]
                     ) -> CachedStep:
        """Return the cached entry for ``key``, building it (once, even
        under concurrent callers) via ``builder`` on a miss."""
        while True:
            with self._lock:
                entry = self._entries.get(key)
                if entry is not None:
                    self._entries.pop(key)
                    self._entries[key] = entry
                    self._count(hit=True)
                    return entry
                ev = self._building.get(key)
                if ev is None:
                    ev = self._building[key] = threading.Event()
                    gen = self._gen
                    break
            ev.wait()        # another thread is compiling this key
        try:
            entry = builder()
        except BaseException:
            with self._lock:
                self._building.pop(key, None)
            ev.set()
            raise
        with self._lock:
            if self._gen == gen:
                # a clear() during the build (device loss) invalidates
                # what we just compiled — hand it to the caller but do
                # NOT resurrect it in the pool
                self._entries[key] = entry
                while len(self._entries) > self.capacity:
                    self._entries.pop(next(iter(self._entries)))
                    self.evictions += 1
            self._count(hit=False)
            self._building.pop(key, None)
        ev.set()
        return entry

    def stats(self) -> dict:
        with self._lock:
            total = self.hits + self.misses
            return {"entries": len(self._entries), "hits": self.hits,
                    "misses": self.misses, "evictions": self.evictions,
                    "hit_rate": self.hits / total if total else 0.0}

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._gen += 1   # in-flight builds must not re-insert

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key) -> bool:
        return key in self._entries


_STEP_CACHE = StepCache()


def get_step_cache() -> StepCache:
    """The process-default :class:`StepCache` (shared by Trainers and
    ``engine.precompile`` unless one is injected explicitly)."""
    return _STEP_CACHE


def compile_strategy(model: Module, opt: Transform, strategy: Strategy, *,
                     devices=None, attn_impl: str = "auto",
                     donate: bool = True, loss_fn: Optional[Callable] = None,
                     build_eval: bool = True) -> CachedStep:
    """Plan + build the jitted step (and eval) for one Strategy, returning
    a :class:`CachedStep`. Callers wanting memoization go through
    :meth:`StepCache.get_or_build`; callers wanting dtype policy wrap this
    in ``autocast(policy)`` (tracing happens lazily at first call / AOT
    lower, but ``make_plan``'s init shapes are taken here)."""
    from hetu_tpu import telemetry
    t0 = time.perf_counter()
    with telemetry.span("build_plan_and_step",
                        strategy=strategy.to_json()):
        plan = make_plan(model, opt, strategy, devices)
        step_fn = build_train_step(model, opt, plan, loss_fn=loss_fn,
                                   attn_impl=attn_impl, donate=donate)
        eval_fn = None
        if build_eval:
            eval_fn = build_eval_step(model, plan, loss_fn=loss_fn,
                                      attn_impl=attn_impl)
    return CachedStep(plan, step_fn, eval_fn,
                      compile_seconds=time.perf_counter() - t0,
                      refs=(model, opt))


def abstract_train_state(model: Module, opt: Transform, plan: TrainPlan,
                         dtype=None) -> TrainState:
    """ShapeDtypeStruct pytree of the sharded train state — the abstract
    argument AOT lowering needs (``engine.precompile``). Run under the
    same ``autocast`` policy as the real ``init_state`` so dtypes match."""
    shapes = jax.eval_shape(
        lambda k: new_train_state(model.init(k, dtype=dtype), opt),
        jax.random.key(0))
    return jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        shapes, plan.state_shardings)


def abstract_batch(plan: TrainPlan, batch_shape: tuple, *,
                   keys=("input_ids", "labels"), dtype=jnp.int32) -> dict:
    """ShapeDtypeStruct batch dict for AOT lowering: ``batch_shape`` is
    the global (batch, seq) the training loop will feed (post
    ``shard_batch`` — zigzag permutes never change shapes)."""
    sharding = plan.batch_sharding(len(batch_shape))
    return {k: jax.ShapeDtypeStruct(tuple(batch_shape), dtype,
                                    sharding=sharding) for k in keys}


def effective_remat(strategy: Strategy) -> str:
    if strategy.offload:
        return "offload"
    return strategy.remat


def step_dropout_key(step) -> jax.Array:
    """Per-step dropout base key. One definition shared by every train
    path (plain/pipeline/hetero-dp) — the resume-reproducibility guarantee
    (same step => same masks) depends on them deriving keys identically."""
    return jax.random.fold_in(jax.random.key(0x0d0), step)


def model_dropout_active(model: Module) -> bool:
    """True iff the model's config enables any dropout rate."""
    cfg = getattr(model, "cfg", None)
    return any(getattr(cfg, f, 0.0) > 0.0 for f in
               ("embd_pdrop", "resid_pdrop", "attn_pdrop", "hidden_pdrop"))


def default_loss_fn(model: Module, strategy: Strategy,
                    attn_impl: str = "auto") -> Callable:
    """loss(params, batch[, dropout_key]) for LM models exposing ``.loss``.

    ``dropout_key`` is threaded by the train step (derived from
    ``state.step``, so a resumed run reproduces the same mask sequence);
    eval paths omit it and dropout is off.
    """
    remat = effective_remat(strategy)

    def loss_fn(params, batch, dropout_key=None):
        return model.loss(params, batch["input_ids"], batch["labels"],
                          positions=batch.get("positions"),
                          segment_ids=batch.get("segment_ids"),
                          attn_impl=attn_impl, remat=remat,
                          remat_mask=strategy.remat_mask,
                          unroll=strategy.unroll,
                          dropout_key=dropout_key)

    return loss_fn


def _spec_has_axis(spec: P, axis: str) -> bool:
    return any(p == axis or (isinstance(p, (tuple, list)) and axis in p)
               for p in spec)


def _manual_projection(spec: P, manual: tuple) -> P:
    """Project a param PartitionSpec onto ``manual`` axes: entries keep
    only the components bound by the partial-manual region (the rest —
    tp, cp — ride GSPMD-auto, which in_specs must not name)."""
    parts = []
    for p in spec:
        if isinstance(p, (tuple, list)):
            kept = tuple(a for a in p if a in manual)
            parts.append(kept[0] if len(kept) == 1 else (kept or None))
        else:
            parts.append(p if p in manual else None)
    while parts and parts[-1] is None:
        parts.pop()
    return P(*parts)


def _delayed_acc_layout(plan: "TrainPlan", ndp: int, nep: int):
    """Lane layout of the delayed-sync grad accumulator, shared by the
    in-scan (``build_train_step``) and split-phase
    (``build_grad_accum_steps``) paths: dense leaves carry a
    ``("dp","ep")``-sharded lane dim of ``ndp·nep`` local grads, expert
    leaves (an "ep" component in their spec) a dp-sharded one of
    ``ndp`` — their ep sum already happened through the backward
    all_to_all. Returns ``(acc_specs, acc_shardings, acc_leads)``."""
    def spec(s):
        if nep > 1 and not _spec_has_axis(s, "ep"):
            return P(("dp", "ep"), *tuple(s))
        return P("dp", *tuple(s))

    leaf = lambda x: isinstance(x, P)
    acc_specs = jax.tree.map(spec, plan.state_specs.params, is_leaf=leaf)
    acc_leads = jax.tree.map(
        lambda s: ndp if (nep > 1 and _spec_has_axis(s, "ep"))
        else ndp * nep,
        plan.state_specs.params, is_leaf=leaf)
    return acc_specs, named_shardings(plan.mesh, acc_specs), acc_leads


def build_local_grad_fn(base_loss, mesh: Mesh, ndp: int, *,
                        nep: int = 1, param_specs=None,
                        ep_overlap: str = "off",
                        ep_chunks: int = 2) -> Callable:
    """Per-group ``(loss, grads)`` with a leading group dim and ZERO
    cross-group gradient traffic: a partial-manual ``shard_map`` over
    the group axes — each group differentiates its local batch shard;
    tp/cp collectives stay GSPMD-auto exactly as in the pipeline
    executor's manual region. Shared by the split-phase path
    (``build_grad_accum_steps(delay_grad_sync=True)``) and the in-scan
    path (``Strategy(delay_grad_sync=True)`` with
    ``num_microbatches > 1``). Returns ``local_grads(params, batch,
    key)``; the key-vs-keyless shard_map variant is picked at trace
    time from ``key is None``.

    With ``nep > 1`` the group is **dp×ep** (the batch dim is sharded
    over both): "ep" joins the manual set so the MoE layers run the real
    all_to_all dispatch on the bound axis (``nn.moe`` consults
    ``current_manual_axes``), and the param handling splits by spec —

    - **dense leaves** enter replicated over the whole group (``P()``
      projection) and come back with a ``("dp","ep")``-sharded leading
      lane dim: every group holds its own local grad;
    - **expert leaves** (an "ep" component in ``param_specs``) enter
      ep-SHARDED on their expert dim — each rank differentiates only
      its local experts, and the backward ``all_to_all`` already sums
      their grads over ep — so their leading lane dim is sharded over
      dp only.

    Either way ONE post-scan sum over the leading dim divided by
    ``ndp·nep`` (per microbatch) reproduces the eager gradient."""
    from hetu_tpu.parallel.sharding import ManualAxes, no_act_sharding
    group = ("dp", "ep") if nep > 1 else ("dp",)
    manual = frozenset(group)
    ngroups = ndp * nep
    if nep > 1 and param_specs is None:
        raise ValueError(
            "param_specs is required for ep-aware delayed grad sync "
            "(the dense/expert spec split drives the lane layout)")

    def param_in_spec(spec: P) -> P:
        # expert leaves keep their ep shard inside the region; dense
        # leaves replicate over the group
        if nep > 1 and _spec_has_axis(spec, "ep"):
            return _manual_projection(spec, ("ep",))
        return P()

    def grad_out_spec(spec: P) -> P:
        if nep > 1 and _spec_has_axis(spec, "ep"):
            return P("dp", *tuple(_manual_projection(spec, ("ep",))))
        return P(group if nep > 1 else "dp")

    def local_grads(params, batch, key):
        def body(params, batch_l, gid, *key_arg):
            def lloss(p):
                k = None
                if key_arg:
                    # decorrelate groups via the explicit group-id
                    # operand (axis_index would lower to PartitionId,
                    # which SPMD partitioning of the auto axes rejects)
                    k = jax.random.fold_in(key_arg[0], gid[0])
                with no_act_sharding(), \
                        ManualAxes(mesh, manual, ep_overlap=ep_overlap,
                                   ep_chunks=ep_chunks):
                    if k is not None:
                        return base_loss(p, batch_l, dropout_key=k)
                    return base_loss(p, batch_l)

            loss, g = jax.value_and_grad(lloss)(params)
            return loss.reshape(1), jax.tree.map(lambda v: v[None], g)

        in_b = {k: P(group if nep > 1 else "dp") for k in batch}
        if param_specs is not None:
            in_p = jax.tree.map(param_in_spec, param_specs,
                                is_leaf=lambda x: isinstance(x, P))
            out_g = jax.tree.map(grad_out_spec, param_specs,
                                 is_leaf=lambda x: isinstance(x, P))
        else:
            in_p = jax.tree.map(lambda _: P(), params)
            out_g = jax.tree.map(lambda _: P("dp"), params)
        gids = jnp.arange(ngroups, dtype=jnp.int32)
        lane = P(group if nep > 1 else "dp")
        if key is None:
            f = shard_map(lambda p, b, g: body(p, b, g), mesh=mesh,
                          in_specs=(in_p, in_b, lane),
                          out_specs=(lane, out_g),
                          axis_names=manual, check_vma=False)
            losses, grads = f(params, batch, gids)
        else:
            f = shard_map(body, mesh=mesh,
                          in_specs=(in_p, in_b, lane, P()),
                          out_specs=(lane, out_g),
                          axis_names=manual, check_vma=False)
            losses, grads = f(params, batch, gids, key)
        # scalarizing the per-group loss vector moves 4·ngroups bytes —
        # a metric read, not a gradient sync
        return jnp.mean(losses), grads

    return local_grads


def _fsdp_gspmd_gather_bytes(model: Module, param_specs, ndp: int, *,
                             skip_blocks: bool) -> int:
    """Analytic payload of the monolithic GSPMD param all-gather: every
    dp-sharded leaf's (ndp-1)/ndp remote share. With the per-layer ring
    active (``skip_blocks``) the block subtree gathers on the ring and
    only the remaining leaves (embeddings, LM head, final norm) stay on
    the serialized GSPMD path — they must still be accounted, or the
    overlap ratio overstates the ring's coverage."""
    from hetu_tpu.parallel.overlap import _dp_dim
    abstract = model.abstract_params()
    if skip_blocks and isinstance(param_specs, dict):
        param_specs = {k: v for k, v in param_specs.items()
                       if k != "blocks"}
        abstract = {k: v for k, v in abstract.items() if k != "blocks"}
    spec_leaves = jax.tree.leaves(param_specs,
                                  is_leaf=lambda x: isinstance(x, P))
    leaves = jax.tree.leaves(abstract)
    if len(spec_leaves) != len(leaves):
        return 0
    total = 0
    for leaf, spec in zip(leaves, spec_leaves):
        if _dp_dim(spec) is None:
            continue
        size = functools.reduce(lambda a, b: a * int(b), leaf.shape, 1)
        total += size * leaf.dtype.itemsize * (ndp - 1) // ndp
    return total


def build_train_step(model: Module, opt: Transform, plan: TrainPlan, *,
                     loss_fn: Optional[Callable] = None,
                     attn_impl: str = "auto",
                     donate: bool = True) -> Callable:
    """Return jitted ``step(state, batch) -> (state, metrics)``.

    pp>1 routes through the pipeline executor
    (``hetu_tpu.parallel.pipeline.build_pipeline_train_step``).

    ``Strategy(delay_grad_sync=True)`` with ``num_microbatches > 1``
    moves the DP gradient reduction OUT of the accumulation ``lax.scan``:
    microbatch grads stay dp-group-local (leading dp-sharded accumulator
    dim, grads computed in a partial-manual ``shard_map`` over dp) and
    ONE reduction fires per optimizer update instead of one per
    microbatch — the in-jit twin of
    ``build_grad_accum_steps(delay_grad_sync=True)``, counter-audited by
    ``dp_grad_syncs_total`` / ``optimizer_updates_total``.
    """
    from hetu_tpu import telemetry
    strategy = plan.strategy
    if strategy.delay_grad_sync and strategy.pp > 1:
        raise ValueError(
            "delay_grad_sync=True is unsupported with pp > 1 — the "
            "pipeline executor owns its own microbatch schedule")
    if strategy.pp > 1:
        if loss_fn is not None:
            raise ValueError(
                "custom loss_fn is not supported with pp > 1 — the pipeline "
                "executor schedules model.embed/blocks/head_loss itself; "
                "override model.head_loss instead")
        from hetu_tpu.parallel.pipeline import build_pipeline_train_step
        with telemetry.span("build_step", kind="pipeline"):
            return build_pipeline_train_step(
                model, opt, plan, attn_impl=attn_impl, donate=donate)

    base_loss = loss_fn or default_loss_fn(model, strategy, attn_impl)
    nm = strategy.num_microbatches

    # thread dropout keys only when the model config asks for dropout AND
    # the loss fn accepts them (custom loss fns keep their 2-arg form)
    import inspect
    sig = inspect.signature(base_loss)
    explicit_key = "dropout_key" in sig.parameters
    var_kw = any(p.kind is inspect.Parameter.VAR_KEYWORD
                 for p in sig.parameters.values())
    thread_dropout = model_dropout_active(model) and \
        (explicit_key or var_kw)
    if model_dropout_active(model) and loss_fn is not None:
        import warnings
        if not thread_dropout:
            warnings.warn(
                "model config enables dropout but the custom loss_fn has "
                "no dropout_key parameter — dropout will be OFF; accept a "
                "dropout_key kwarg (and pass it to model.loss) to enable "
                "it", stacklevel=2)
        elif var_kw and not explicit_key:
            warnings.warn(
                "dropout_key will be passed to the custom loss_fn via "
                "**kwargs — make sure it forwards the key to model.loss, "
                "or dropout silently stays off", stacklevel=2)

    def compute_loss(params, batch, dropout_key=None):
        with plan.act:
            if thread_dropout:
                return base_loss(params, batch, dropout_key=dropout_key)
            return base_loss(params, batch)

    grad_fn = jax.value_and_grad(compute_loss)
    ndp = plan.mesh.shape.get("dp", 1)
    nep = plan.mesh.shape.get("ep", 1)
    ngroups = ndp * nep
    if strategy.delay_grad_sync and strategy.fsdp:
        raise ValueError(
            "delay_grad_sync=True is incompatible with fsdp: params are "
            "dp-sharded, so group-local gradients would require the "
            "param all-gather the delay is meant to avoid")
    delayed = strategy.delay_grad_sync and ngroups > 1 and nm > 1
    if delayed:
        # group-local grads need the RAW loss fn (no GSPMD activation
        # constraints inside the manual region). With ep > 1 the group
        # is dp×ep: dense grads carry a ("dp","ep")-sharded lane dim,
        # expert grads a dp-sharded one (their ep sum already happened
        # through the backward all_to_all) — ONE post-scan reduction
        # per update either way.
        local_grad_fn = build_local_grad_fn(
            base_loss, plan.mesh, ndp, nep=nep,
            param_specs=plan.state_specs.params,
            ep_overlap=strategy.ep_overlap, ep_chunks=strategy.ep_chunks)
        _, acc_shardings, acc_leads = _delayed_acc_layout(plan, ndp, nep)

    from hetu_tpu.parallel import overlap as _overlap
    fsdp_gspmd_bytes = 0
    if strategy.fsdp and ndp > 1:
        # GSPMD gather accounting (serialized): ALL dp-sharded leaves on
        # the fallback path; with the per-block ring active, just the
        # non-block leaves (embeddings/head) — the ring path accounts
        # its per-block gathers itself, as overlapped
        fsdp_gspmd_bytes = _fsdp_gspmd_gather_bytes(
            model, plan.param_specs, ndp,
            skip_blocks=getattr(plan.act, "fsdp_specs", None) is not None)

    def step(state: TrainState, batch: dict):
        record_trace("train_step")   # runs at trace time only
        if fsdp_gspmd_bytes:         # trace-time, like the ring kernels
            _overlap.record_comm_bytes("fsdp_gather", fsdp_gspmd_bytes,
                                       overlapped=False)
        # deterministic per-step key: resume-at-step-N reproduces masks
        key = step_dropout_key(state.step) if thread_dropout else None
        if nm > 1:
            mbs = jax.tree.map(
                lambda x: x.reshape((nm, x.shape[0] // nm) + x.shape[1:]),
                batch)

            if delayed:
                # leading dp-sharded dim: each dp group accumulates its
                # OWN grads — no cross-dp traffic inside the scan
                def body(acc, xs):
                    mb, i = xs
                    mb_key = None if key is None \
                        else jax.random.fold_in(key, i)
                    loss, grads = local_grad_fn(state.params, mb, mb_key)
                    acc_loss, acc_g = acc
                    return (acc_loss + loss,
                            jax.tree.map(
                                lambda a, g: a + g.astype(jnp.float32),
                                acc_g, grads)), None

                zeros = jax.lax.with_sharding_constraint(
                    jax.tree.map(
                        lambda p, lead: jnp.zeros((lead,) + p.shape,
                                                  jnp.float32),
                        state.params, acc_leads),
                    acc_shardings)
                (loss, acc_g), _ = jax.lax.scan(
                    body, (jnp.zeros([], jnp.float32), zeros),
                    (mbs, jnp.arange(nm)))
                loss = loss / nm
                # THE one gradient reduction of the whole update:
                # summing the leading (group-sharded) lane dim down to
                # the synced grad — dense lanes sum over dp×ep, expert
                # lanes over dp; under ZeRO it becomes the
                # reduce-scatter → update → all-gather triplet, once
                grads = jax.tree.map(
                    lambda g: jnp.sum(g, axis=0) / (ngroups * nm), acc_g)
            else:
                def body(acc, xs):
                    mb, i = xs
                    mb_key = None if key is None \
                        else jax.random.fold_in(key, i)
                    loss, grads = grad_fn(state.params, mb, mb_key)
                    acc_loss, acc_g = acc
                    return (acc_loss + loss,
                            jax.tree.map(
                                lambda a, g: a + g.astype(jnp.float32),
                                acc_g, grads)), None

                zeros = jax.tree.map(
                    lambda p: jnp.zeros(p.shape, jnp.float32),
                    state.params)
                (loss, grads), _ = jax.lax.scan(
                    body, (jnp.zeros([], jnp.float32), zeros),
                    (mbs, jnp.arange(nm)))
                loss = loss / nm
                grads = jax.tree.map(lambda g: g / nm, grads)
        else:
            loss, grads = grad_fn(state.params, batch, key)

        gnorm = global_norm(grads)
        updates, new_opt = opt.update(grads, state.opt_state, state.params)
        new_params = apply_updates(state.params, updates)
        metrics = {"loss": loss, "grad_norm": gnorm}
        return TrainState(state.step + 1, new_params, new_opt), metrics

    jitted = jax.jit(
        step,
        out_shardings=(plan.state_shardings, None),
        donate_argnums=(0,) if donate else ())

    # host-side data-plane accounting (exact per call, mirroring
    # build_grad_accum_steps): the jitted path issues one DP grad
    # reduction per microbatch when eager, exactly one per update when
    # delayed (or when nm == 1 — nothing to delay). First call also
    # seeds the memory-plane ledger from the model config + batch shape.
    syncs_per_call = 0 if ngroups <= 1 \
        else (1 if (nm == 1 or delayed) else nm)
    grad_bytes = 4 * int(sum(
        functools.reduce(lambda a, b: a * b, l.shape, 1)
        for l in jax.tree.leaves(model.abstract_params())))
    seeded = []

    def _host_account(batch):
        if not seeded:
            seeded.append(True)
            try:
                from hetu_tpu.engine.memory import record_model_memory_plane
                record_model_memory_plane(model, strategy, batch)
            except Exception:   # ledger is observability, never fatal
                pass
        if syncs_per_call:
            _overlap.record_dp_sync(syncs_per_call, grad_bytes=grad_bytes)
        _overlap.record_optimizer_update(1)

    def step_call(state, batch):
        _host_account(batch)
        return jitted(state, batch)

    # AOT lowering (engine.precompile) goes through .lower on the entry;
    # AOT EXECUTION bypasses step_call (CachedStep dispatches the
    # executable directly), so the accounting hook rides along for
    # CachedStep.__call__ to invoke on that path
    step_call.lower = jitted.lower
    step_call.on_execute = _host_account
    return step_call


def build_eval_step(model: Module, plan: TrainPlan, *,
                    loss_fn: Optional[Callable] = None,
                    attn_impl: str = "auto") -> Callable:
    base_loss = loss_fn or default_loss_fn(model, plan.strategy, attn_impl)

    def step(params, batch):
        with plan.act:
            return base_loss(params, batch)

    return jax.jit(step)


def build_grad_accum_steps(model: Module, opt: Transform, plan: TrainPlan,
                           *, loss_fn: Optional[Callable] = None,
                           attn_impl: str = "auto",
                           donate_acc: bool = True,
                           delay_grad_sync: bool = False):
    """Split-phase training — the reference's partial-execution RunLevels
    (``graph.h:33-39``): RunLevel::GRAD accumulates gradients across
    *separate step calls* (arbitrary-size global batches without holding
    every microbatch in one feed), RunLevel::UPDATE applies them.

    Returns ``(init_acc, grad_step, apply_step)``:

    - ``acc = init_acc()`` — zeroed fp32 grad buffer (param-sharded)
    - ``acc, loss = grad_step(state, acc, batch, accum_index=i)`` — one
      forward/backward, grads added into ``acc`` (donated). Pass the
      per-update accumulation counter ``i`` when dropout is active —
      dropout keys fold (step, i) so every grad call draws independent
      masks (``i`` is a traced operand: no recompile per index)
    - ``state, metrics = apply_step(state, acc, n_accum)`` — mean over
      ``n_accum`` accumulations, optimizer update; ``acc`` is consumed

    Accumulator buffer lifecycle (``donate_acc``): with the default
    ``True``, ``apply_step`` donates ``acc`` so XLA reuses its fp32
    param-shaped buffers for the update's outputs — optimal *peak*
    memory, but the next update must allocate a fresh buffer via
    ``init_acc()``. With ``donate_acc=False``, ``apply_step`` only reads
    ``acc`` and the caller recycles the same buffer across updates with
    ``acc = init_acc(like=acc)`` — the ``like`` argument is donated to a
    zero-fill, so steady-state training performs **no** accumulator
    allocation at all (HBM allocator churn is the enemy on long runs).
    ``init_acc(like=...)`` after a donating ``apply_step`` raises jax's
    deleted-buffer error — the two modes are mutually exclusive by
    construction.

    Delayed gradient synchronization (``delay_grad_sync=True``, ZeRO
    SC'20 §5 / DDP ``no_sync``): per-microbatch gradients stay **local
    to each dp group** — the accumulator gains a leading ``dp`` dim
    sharded over dp and ``grad_step`` computes group-local grads inside
    a partial-manual ``shard_map`` over dp (tp/cp stay GSPMD-auto), so
    NO cross-dp gradient traffic moves until ``apply_step`` reduces the
    leading dim once per optimizer update — an O(accum_steps) reduction
    in DP bytes. With ZeRO on, that single reduction feeds the sharded
    optimizer directly (reduce-scatter → update → all-gather, once).
    The per-call ``dp_grad_syncs_total`` / ``optimizer_updates_total``
    counters (``parallel.overlap``) make the rate auditable:
    eager = ``accum_steps`` syncs/update, delayed = exactly 1.
    With ``ep > 1`` the group is dp×ep: "ep" joins the manual region so
    MoE layers run the real all_to_all dispatch, dense grads carry a
    ``("dp","ep")``-sharded lane dim, and expert grads (ep-sharded
    specs) a dp-sharded one — their ep sum already happened through the
    backward all_to_all. Unsupported with ``fsdp`` (params are
    dp-sharded — group-local grads of a sharded param would need the
    very gather being delayed); raises.
    """
    strategy = plan.strategy
    if strategy.pp > 1:
        raise NotImplementedError(
            "split-phase accumulation with pp > 1: use "
            "num_microbatches inside the pipeline step instead")
    if delay_grad_sync and strategy.fsdp:
        raise ValueError(
            "delay_grad_sync=True is incompatible with fsdp: params are "
            "dp-sharded, so group-local gradients would require the "
            "param all-gather the delay is meant to avoid")
    base_loss = loss_fn or default_loss_fn(model, strategy, attn_impl)

    def compute_loss(params, batch, key):
        with plan.act:
            if key is not None:
                return base_loss(params, batch, dropout_key=key)
            return base_loss(params, batch)

    grad_fn = jax.value_and_grad(compute_loss)
    param_shardings = plan.state_shardings.params
    ndp = plan.mesh.shape.get("dp", 1)
    nep = plan.mesh.shape.get("ep", 1)
    ngroups = ndp * nep
    delayed = delay_grad_sync and ngroups > 1  # one group: nothing to delay
    # same dropout contract as build_train_step: thread keys when the
    # model wants dropout AND the loss fn can take them; warn otherwise
    import inspect
    sig = inspect.signature(base_loss)
    accepts_key = "dropout_key" in sig.parameters or any(
        p.kind is inspect.Parameter.VAR_KEYWORD
        for p in sig.parameters.values())
    thread_dropout = model_dropout_active(model) and accepts_key
    if model_dropout_active(model) and loss_fn is not None \
            and not accepts_key:
        import warnings
        warnings.warn(
            "model config enables dropout but the custom loss_fn has no "
            "dropout_key parameter — dropout will be OFF in "
            "build_grad_accum_steps; accept a dropout_key kwarg to "
            "enable it", stacklevel=2)

    if delayed:
        # the accumulator gains a leading lane dim (one local grad
        # shard per group) — group-sharded specs keep each group's
        # shard on its own devices, so accumulation is comm-free
        _, acc_shardings, acc_leads = _delayed_acc_layout(plan, ndp, nep)
    else:
        acc_shardings = param_shardings
        acc_leads = jax.tree.map(lambda s: 0, plan.state_specs.params,
                                 is_leaf=lambda x: isinstance(x, P))

    @functools.partial(jax.jit, out_shardings=acc_shardings)
    def _fresh_acc():
        return jax.tree.map(
            lambda s, lead: jnp.zeros(
                ((lead,) if lead else ()) + tuple(s.shape), jnp.float32),
            model.abstract_params(), acc_leads)

    # zero-fill INTO the donated previous accumulator: XLA rewrites this
    # to an in-place memset of the existing buffer — no allocation
    @functools.partial(jax.jit, donate_argnums=(0,),
                       out_shardings=acc_shardings)
    def _rezero_acc(like):
        return jax.tree.map(lambda x: jnp.zeros(x.shape, jnp.float32),
                            like)

    def init_acc(like=None):
        """Zeroed fp32 grad accumulator. Pass the previous update's
        ``acc`` as ``like`` (requires ``donate_acc=False``) to recycle
        its buffer instead of allocating a fresh one."""
        if like is None:
            return _fresh_acc()
        return _rezero_acc(like)

    @functools.partial(jax.jit, donate_argnums=(1,),
                       out_shardings=(acc_shardings, None))
    def grad_step(state: TrainState, acc, batch, accum_index=0):
        record_trace("grad_step")
        # accum_index is traced (fold_in takes traced ints): one compile
        # serves every index
        key = jax.random.fold_in(step_dropout_key(state.step),
                                 accum_index) if thread_dropout else None
        if not delayed:
            loss, grads = grad_fn(state.params, batch, key)
            return jax.tree.map(lambda a, g: a + g.astype(jnp.float32),
                                acc, grads), loss
        loss, grads = _local_grads(state.params, batch, key)
        return jax.tree.map(lambda a, g: a + g.astype(jnp.float32),
                            acc, grads), loss

    # shared with the in-scan path (Strategy(delay_grad_sync=True)):
    # partial-manual shard_map over the group axes, group-local grads,
    # leading lane dim
    _local_grads = build_local_grad_fn(
        base_loss, plan.mesh, ndp, nep=nep,
        param_specs=plan.state_specs.params,
        ep_overlap=strategy.ep_overlap, ep_chunks=strategy.ep_chunks) \
        if delayed else None

    # delayed acc buffers ((ndp, ...) leaves) can never alias the
    # update's outputs — donating them only buys a warning per compile
    @functools.partial(jax.jit,
                       donate_argnums=(0, 1) if donate_acc and not delayed
                       else (0,),
                       out_shardings=(plan.state_shardings, None))
    def apply_step(state: TrainState, acc, n_accum):
        if delayed:
            # THE one gradient reduction of the whole update: the
            # leading (group-sharded) lane dim sums down to the synced
            # grad (dense lanes over dp×ep, expert lanes over dp) —
            # under ZeRO the sharded moment specs turn it into the
            # reduce-scatter → update → all-gather triplet, once
            grads = jax.tree.map(
                lambda g: jnp.sum(g, axis=0) / (ngroups * n_accum), acc)
        else:
            grads = jax.tree.map(lambda g: g / n_accum, acc)
        gnorm = global_norm(grads)
        updates, new_opt = opt.update(grads, state.opt_state, state.params)
        new_params = apply_updates(state.params, updates)
        return (TrainState(state.step + 1, new_params, new_opt),
                {"grad_norm": gnorm})

    # host-side data-plane accounting (exact per call): eager issues one
    # DP grad reduction per MICROBATCH, delayed exactly one per UPDATE
    from hetu_tpu.parallel import overlap as _overlap
    grad_bytes = 4 * int(sum(
        int(functools.reduce(lambda a, b: a * b, l.shape, 1))
        for l in jax.tree.leaves(model.abstract_params())))

    def grad_step_fn(state, acc, batch, accum_index=0):
        if ngroups > 1 and not delayed:
            _overlap.record_dp_sync(1, grad_bytes=grad_bytes)
        return grad_step(state, acc, batch, accum_index)

    def apply_step_fn(state, acc, n_accum):
        _overlap.record_optimizer_update(1)
        if delayed:
            _overlap.record_dp_sync(1, grad_bytes=grad_bytes)
        return apply_step(state, acc, n_accum)

    return init_acc, grad_step_fn, apply_step_fn
