"""Malleus-style straggler-aware hetero-parallel planner.

The reference's Malleus planner (``python/hetu/engine/strategy.py:99``) takes
per-device straggler ratios and solves a PuLP ILP that (a) groups devices
into TP groups so stragglers share a group, and (b) assigns pipeline layers
to groups proportional to group throughput. This module solves the same
problem with an exact enumeration over group-size compositions (the search
space on a TPU pod slice is tiny — group sizes are powers of two), which
avoids the PuLP dependency while keeping the ILP's optimality for the
objective below.

Model: a TP group executes in lockstep, so its throughput is
``size × min(speed of members)`` with ``speed = 1/ratio``. For a fixed
partition of devices into groups and fractional layer assignment, the
pipeline's steady-state step time is ``total_layers / Σ group_throughput`` —
so the planner (1) maximizes total throughput by choosing group sizes and a
sorted device assignment (grouping similar speeds together is optimal; the
ILP's core insight), then (2) rounds per-group layer counts by largest
remainder.
"""

from __future__ import annotations

import itertools
from typing import Optional, Sequence

from hetu_tpu.engine.straggler import StragglerReport


def _compositions(n: int, k: int, allowed: Sequence[int]):
    """All ways to write n as an ordered sum of k values from ``allowed``."""
    if k == 1:
        if n in allowed:
            yield (n,)
        return
    for first in allowed:
        if first < n - max(allowed) * (k - 1):
            continue
        if first <= n - (k - 1) * min(allowed):
            for rest in _compositions(n - first, k - 1, allowed):
                yield (first,) + rest


def _largest_remainder(weights: Sequence[float], total: int,
                       minimum: int = 1) -> list[int]:
    """Integer allocation of ``total`` proportional to ``weights``."""
    k = len(weights)
    wsum = sum(weights) or 1.0
    raw = [w / wsum * (total - minimum * k) for w in weights]
    out = [minimum + int(r) for r in raw]
    rem = total - sum(out)
    order = sorted(range(k), key=lambda i: raw[i] - int(raw[i]), reverse=True)
    for i in range(rem):
        out[order[i % k]] += 1
    return out


def plan_hetero(report: StragglerReport, num_layers: int, *,
                num_stages: int, max_tp: int = 8,
                num_microbatches: Optional[int] = None,
                remat: str = "none") -> "HeteroStrategy":
    """Emit a HeteroStrategy from measured straggler ratios.

    Devices are sorted fastest-first and cut into ``num_stages`` contiguous
    TP groups (sizes chosen over power-of-two compositions to maximize
    total lockstep throughput); layers are assigned per group by
    throughput. Stragglers therefore end up co-located in a group that
    gets few layers instead of dragging every TP matmul of a fast group —
    the Malleus objective.
    """
    # function-level import: hetero imports engine.state, so a module-level
    # import here would be circular through engine/__init__
    from hetu_tpu.parallel.hetero import HeteroStrategy, StageSpec
    ids = sorted(report.ratios, key=lambda d: report.ratios[d])
    speeds = [1.0 / report.ratios[d] for d in ids]
    n = len(ids)
    if num_stages < 1 or num_stages > n:
        raise ValueError(f"num_stages={num_stages} with {n} devices")
    if num_layers < num_stages:
        raise ValueError("need at least one layer per stage")

    allowed = [s for s in (1, 2, 4, 8, 16, 32) if s <= max_tp]
    best = None
    for sizes in _compositions(n, num_stages, allowed):
        # contiguous cut of the sorted-by-speed device list
        cuts, k = [], 0
        for s in sizes:
            cuts.append((k, k + s))
            k += s
        thr = [sizes[i] * min(speeds[lo:hi])
               for i, (lo, hi) in enumerate(cuts)]
        total = sum(thr)
        if best is None or total > best[0]:
            best = (total, sizes, cuts, thr)
    if best is None:
        raise ValueError(
            f"no power-of-two composition of {n} devices into "
            f"{num_stages} stages with max_tp={max_tp}")
    _, sizes, cuts, thr = best

    layers = _largest_remainder(thr, num_layers)
    # faster stages first is conventional (embedding stage does extra work)
    order = sorted(range(num_stages), key=lambda i: thr[i], reverse=True)
    stages = tuple(StageSpec(layers=layers[i], tp=sizes[i]) for i in order)
    device_ids = tuple(
        d for i in order for d in ids[cuts[i][0]:cuts[i][1]])
    nm = num_microbatches if num_microbatches is not None \
        else max(2 * num_stages, 4)
    return HeteroStrategy(stages=stages, num_microbatches=nm, remat=remat,
                          device_ids=device_ids).validate(n)


def replan_if_straggling(report: StragglerReport, num_layers: int, *,
                         threshold: float = 1.5, num_stages: int = 2,
                         **kw) -> Optional["HeteroStrategy"]:
    """The Malleus trigger: when stragglers exceed ``threshold``, emit a
    hetero strategy that keeps them (with less work) instead of evicting
    them (``engine.straggler.replan_for_stragglers``'s shrink approach);
    None when the fleet is healthy. Feed the result to
    ``Trainer.set_strategy`` — the hot switch preserves the state."""
    if not report.stragglers(threshold):
        return None
    return plan_hetero(report, num_layers, num_stages=num_stages, **kw)
