"""Elastic training: heartbeat-based failure detection + re-planning.

Parity target: the reference's elastic server flow
(``rpc/heturpc_elastic_server.py:39-559``): workers heartbeat, the server
tracks last-beat times and declares death (:463-486), then the cluster
re-plans (Malleus/Ampelos, ``engine/strategy*.py``) and restarts from
checkpoint (``ht_safetensors.py:881`` load_by_training). TPU-native shape:
the Coordinator service tracks membership; on failure the controller picks
a new Strategy for the surviving device count via the Galvatron search and
the Trainer resumes from the latest checkpoint under the new plan (our
checkpoints are global-valued, so cross-topology restore is just a load).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from hetu_tpu.rpc.client import CoordinatorClient
from hetu_tpu.utils.logging import get_logger


class HeartbeatSender:
    """Background heartbeat thread for one worker."""

    def __init__(self, port: int, name: str, interval_s: float = 1.0):
        self.client = CoordinatorClient(port)
        self.name = name
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self):
        self.client.heartbeat(self.name)
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.client.heartbeat(self.name)
            except Exception:
                return

    def stop(self):
        self._stop.set()


class ElasticController:
    """Watches membership; on failure computes a recovery plan."""

    def __init__(self, port: int, *, timeout_ms: int = 3000):
        self.client = CoordinatorClient(port)
        self.timeout_ms = timeout_ms

    def check(self) -> tuple[list[str], list[str]]:
        return self.client.status(self.timeout_ms)

    def recovery_plan(self, dims, topo, n_alive_devices: int):
        """New Strategy for the surviving device count (largest
        power-of-two subset), via the auto-parallel search."""
        from hetu_tpu.tools.galvatron import TPUTopology, search_uniform

        n = n_alive_devices
        while n > 1 and (n & (n - 1)):
            n -= 1
        if n < 1:
            return None
        new_topo = TPUTopology(
            num_devices=n, peak_flops=topo.peak_flops, ici_bw=topo.ici_bw,
            dcn_bw=topo.dcn_bw, hbm_bytes=topo.hbm_bytes,
            mxu_efficiency=topo.mxu_efficiency, dp_overlap=topo.dp_overlap)
        cands = search_uniform(dims, new_topo)
        if not cands:
            return None
        get_logger().info(
            f"elastic replan: {n_alive_devices} alive → n={n}, "
            f"strategy={cands[0].strategy.to_json()}")
        return cands[0].strategy

    def watch(self, on_failure: Callable[[list[str], list[str]], None], *,
              poll_s: float = 1.0, stop: Optional[threading.Event] = None):
        """Poll membership; invoke ``on_failure(alive, dead)`` once when
        deaths appear. Returns the watcher thread."""
        stop = stop or threading.Event()

        def run():
            while not stop.wait(poll_s):
                alive, dead = self.check()
                if dead:
                    on_failure(alive, dead)
                    return

        t = threading.Thread(target=run, daemon=True)
        t.start()
        t.stop_event = stop  # type: ignore[attr-defined]
        return t


def elastic_resume(model, opt, new_strategy, *, state=None, devices=None,
                   checkpoint_dir: Optional[str] = None):
    """Resume training after a failure, preferring LIVE state.

    The reference's elastic server restarts survivors from the latest
    checkpoint (``heturpc_elastic_server.py:497-559`` → load_by_training).
    The TPU-native controller can do better: when the controller process
    survived (its train state is still resident), the state is resharded
    in memory onto the recovery plan via the hot-switch path
    (``parallel.switch.switch_strategy`` → ``cross_topology_switch``) —
    NO checkpoint read, no disk round trip. Disk is the fallback only
    when the controller itself died (``state=None``).

    ``devices``: the surviving device list for the new plan's mesh
    (defaults to all visible devices). Returns ``(new_plan, new_state)``.
    """
    from hetu_tpu.engine.train_step import make_plan

    new_plan = make_plan(model, opt, new_strategy, devices=devices)
    if state is not None:
        from hetu_tpu.parallel.switch import switch_strategy
        try:
            new_state = switch_strategy(state, new_plan)
        except Exception as e:
            # live reshard can be impossible: e.g. tp-sharded state whose
            # only copy of some shards lived on the dead devices — fall
            # back to disk when we can
            if checkpoint_dir is None:
                raise
            get_logger().warning(
                f"elastic_resume: in-memory reshard failed ({e!r}) — "
                f"falling back to the sharded checkpoint")
        else:
            get_logger().info(
                "elastic_resume: live state present — in-memory reshard "
                "(no checkpoint read)")
            return new_plan, new_state
    if checkpoint_dir is None:
        raise ValueError(
            "elastic_resume: no live state and no checkpoint_dir — "
            "nothing to resume from")
    get_logger().info(
        "elastic_resume: loading sharded checkpoint"
        + ("" if state is not None else " (controller died)"))
    from hetu_tpu.utils.dist_checkpoint import load_checkpoint_distributed
    return new_plan, load_checkpoint_distributed(
        checkpoint_dir, model, opt, plan=new_plan)
