"""Elastic training: heartbeat-based failure detection + re-planning.

Parity target: the reference's elastic server flow
(``rpc/heturpc_elastic_server.py:39-559``): workers heartbeat, the server
tracks last-beat times and declares death (:463-486), then the cluster
re-plans (Malleus/Ampelos, ``engine/strategy*.py``) and restarts from
checkpoint (``ht_safetensors.py:881`` load_by_training). TPU-native shape:
the Coordinator service tracks membership; on failure the controller picks
a new Strategy for the surviving device count via the Galvatron search and
the Trainer resumes from the latest checkpoint under the new plan (our
checkpoints are global-valued, so cross-topology restore is just a load).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from hetu_tpu.rpc.client import CoordinatorClient
from hetu_tpu.utils.logging import get_logger


class HeartbeatSender:
    """Background heartbeat thread for one worker."""

    def __init__(self, port: int, name: str, interval_s: float = 1.0):
        self.client = CoordinatorClient(port)
        self.name = name
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self):
        self.client.heartbeat(self.name)
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.client.heartbeat(self.name)
            except Exception:
                return

    def stop(self):
        self._stop.set()


class ElasticController:
    """Watches membership; on failure computes a recovery plan."""

    def __init__(self, port: int, *, timeout_ms: int = 3000):
        self.client = CoordinatorClient(port)
        self.timeout_ms = timeout_ms

    def check(self) -> tuple[list[str], list[str]]:
        return self.client.status(self.timeout_ms)

    def recovery_plan(self, dims, topo, n_alive_devices: int):
        """New Strategy for the surviving device count (largest
        power-of-two subset), via the auto-parallel search."""
        from hetu_tpu.tools.galvatron import TPUTopology, search_uniform

        n = n_alive_devices
        while n > 1 and (n & (n - 1)):
            n -= 1
        if n < 1:
            return None
        new_topo = TPUTopology(
            num_devices=n, peak_flops=topo.peak_flops, ici_bw=topo.ici_bw,
            dcn_bw=topo.dcn_bw, hbm_bytes=topo.hbm_bytes,
            mxu_efficiency=topo.mxu_efficiency, dp_overlap=topo.dp_overlap)
        cands = search_uniform(dims, new_topo)
        if not cands:
            return None
        get_logger().info(
            f"elastic replan: {n_alive_devices} alive → n={n}, "
            f"strategy={cands[0].strategy.to_json()}")
        return cands[0].strategy

    def watch(self, on_failure: Callable[[list[str], list[str]], None], *,
              poll_s: float = 1.0, stop: Optional[threading.Event] = None):
        """Poll membership; invoke ``on_failure(alive, dead)`` once when
        deaths appear. Returns the watcher thread."""
        stop = stop or threading.Event()

        def run():
            while not stop.wait(poll_s):
                alive, dead = self.check()
                if dead:
                    on_failure(alive, dead)
                    return

        t = threading.Thread(target=run, daemon=True)
        t.start()
        t.stop_event = stop  # type: ignore[attr-defined]
        return t
